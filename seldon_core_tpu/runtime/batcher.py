"""Continuous batching for LLM decode.

The engine-side request batcher of the BASELINE.json north star ("the
orchestrator's gRPC request batcher shards inference-graph traffic across a
v5e slice"), specialised for autoregressive decode: requests join and leave a
fixed pool of cache slots *between decode steps*, so one compiled decode
program serves overlapping requests at arbitrary arrival times — no
head-of-line blocking on the longest generation, no recompilation.

Design (all shapes static):
- one slot-batched KV cache [S, max_len, ...] lives on device;
- admission: a single-prompt prefill (compiled per length bucket) produces a
  1-sequence cache which is written into a free slot (jitted insert);
- every step runs ONE jitted decode over all S slots with per-slot cache
  offsets (models/transformer.py vector ``cache_index``); inactive slots
  compute garbage into their own slot, which the next insert overwrites;
- completion: EOS or per-request max_new_tokens frees the slot.

The transformer's position-tracked cache (PAD_POS masking) is what makes the
mixed-occupancy batch exact: each slot only attends to its own written
positions.

Pipelined decode (PR 3): the decode loop is device-resident. Per-slot token,
position and rng-key state live in device arrays threaded through the
compiled step (``LLMServer._get_decode_step``), so dispatching step N+1
never waits for step N's tokens to land in Python. The host runs one step
(or more) BEHIND the device: a consumer drains the oldest in-flight step's
token array and does all bookkeeping there — EOS detection, ``n_new``
accounting, ``on_token`` streaming, ``_finish``, admissions.

EOS semantics under the lag: the device may run up to ``pipeline_depth``
run-ahead steps past a sequence's EOS before the host sees it. Those
trailing tokens are masked by a per-slot generation counter (a slot freed
and re-admitted between dispatch and drain fails the ``gen`` check), and the
trailing KV writes land in a slot that the next insert overwrites whole —
the lag can only cost wasted compute, never wrong output
(tests/test_batcher_pipeline.py holds token parity against ``generate()``).
The masking is advance-agnostic: a dispatched step may land 1 token
(plain decode), a fixed K (fused scan) or a data-dependent 1..K+1
(speculative verify, below) — in every case the drain credits tokens to a
slot only while ``(slot, gen)`` still matches the dispatch-time snapshot
and the slot still has budget, so trailing tokens of ANY width for a
finished or replaced occupant are dropped, never surfaced.

When the admit queue is empty, ``decode_fuse_steps`` K>1 fuses K steps into
one device-side ``lax.scan`` between syncs (one dispatch + one host read
per K tokens).

Speculative decoding (PR 8): with ``spec_mode`` "ngram" or "draft" each
dispatched step is a fused draft+verify program
(``LLMServer._get_spec_step``): up to K tokens are proposed per slot — by
a zero-weight device-side prompt-lookup match over the slot's
prompt+generated history, or by a small draft model with its own KV pool —
and verified in ONE K+1-token target forward that accepts the longest
prefix agreeing with the slot's exact sampling chain. Each step therefore
advances a slot by a VARIABLE 1..K+1 tokens (``n_acc``), known only at
drain time: the dispatch side books the pessimistic maximum into
``disp_new`` (page provisioning and cache-edge caps must cover the
all-accepted case) and the drain corrects it back to
``n_new + pending-in-flight maxima`` once actual advances land. Rejected
drafts' KV rows are position-reset to PAD_POS inside the verify program
itself, so the cache never holds tokens that lost verification.
``decode_fuse_steps`` > 1 is rejected in combination with speculation: a
fused fixed-K scan and variable accept lengths are incompatible until a
follow-up (the scan would need per-slot variable stride).

Disaggregated prefill/decode (PR 9): with ``disaggregation="remote_prefill"``
admission prefill leaves this batcher's device entirely — the device world
splits into a prefill slice and a decode slice (parallel/mesh.py
``disaggregated_mesh``; the decode slice anchors the process default
device, where the slot pool lives), prefill-slice workers
(runtime/disagg.py) run the server's own compiled prefill programs on
their devices and ``jax.device_put`` the written KV straight onto the
decode device, and the admission path here stages remote jobs and
consumes finished handoffs instead of prefilling locally: one donated
jitted scatter imports the staged pages into the slot's pool pages
(``_get_handoff_import``; dense handoffs reuse ``insert``), then the slot
commits exactly as a local admission would. Because the prefill programs
and the sampling chain are shared with the local path, remote-prefill
serving is bit-exact against single-slice serving (tests/test_disagg.py);
what changes is WHO pays for the burst — the decode slice's worst victim
inter-token gap under a long-prefill adversary drops from "a chunk's
forward" to "one jitted page import" (docs/performance.md
"Disaggregated serving"). Unlike the single local chunked-prefill job,
MULTIPLE remote jobs may be staged at once (that concurrency is the
point); sheds cancel a staged job through the TransferQueue's
exactly-once protocol, so a handoff racing a shed can never double-free
its decode-side pages (tests/test_schedules.py).

Request-scoped tracing (PR 10): when the tracer is enabled (TRACING=1)
every request records a flight-recorder timeline (runtime/flight.py) —
queue wait, each prefill chunk, handoff stages, every drained decode step
with token/accept counts, page-grow stalls, sheds, EOS — written
single-writer from this loop's serialized offload context at points that
already touch host state (NO new lock acquisition or device sync on the
decode path), and materialized into one span tree per request at
completion, rooted at the transport ingress that carried the request's
``traceparent``. Disabled tracing leaves ``_flight`` None and every hook
is a None check; the compiled step programs are identical either way.

Paged KV cache (PR 7): with ``kv_cache_layout="paged"`` (the default) the
dense ``[S, max_len, ...]`` slot pool is replaced by a GLOBAL pool of
fixed-size KV pages plus a device-resident per-slot block table — the
vLLM/PagedAttention design (Kwon et al., SOSP 2023). HBM is billed for
pages actually written, so a deliberately undersized pool
(``kv_pool_pages``) oversubscribes: more concurrent slots per HBM byte,
with page-exhaustion shedding (503 + Retry-After, runtime/resilience.py
ShedError) as the relief valve — the decode loop never raises. Admission
prefill runs in fixed-size chunks (``prefill_chunk``) interleaved with
decode dispatches (Sarathi-Serve; Agrawal et al., OSDI 2024), so a
2k-token prompt never stalls in-flight decodes for its whole compile
bucket. Page bookkeeping is host-side (PageAllocator, lock-guarded);
block-table updates are jitted device ops that serialize behind in-flight
steps in device program order, exactly like the dense ``insert``.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from seldon_core_tpu.models.transformer import (
    NULL_PAGE,
    PAD_POS,
    RESERVED_PAGES,
    TRASH_PAGE,
    normalize_kv_cache_layout,
)
from seldon_core_tpu.runtime.flight import (
    EV_FIRST_TOKEN,
    EV_HANDOFF_IMPORT,
    EV_HANDOFF_STAGED,
    EV_PAGE_GROW,
    EV_PREFILL,
    EV_PREFILL_CHUNK,
    EV_PREFIX_HIT,
    EV_RESUME,
    EV_SHED,
    EV_STEP,
)
from seldon_core_tpu.servers.llmserver import LLMServer, _bucket

logger = logging.getLogger(__name__)

DEFAULT_PAGE_SIZE = 64
DEFAULT_PREFILL_CHUNK = 256


def pow2_bucket(n: int, cap: int) -> int:
    """Power-of-two page-bucket size covering ``n`` pages, capped at
    ``cap``. THE one definition shared by every staged-transfer producer
    (disagg handoffs, prefix exports — runtime/disagg.py) and consumer:
    bucket shapes name compiled import programs on both sides, so a
    divergent rounding rule would silently desynchronize exporter and
    importer shapes (and the hlolint contract dims built on them)."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


def _page_table_ops():
    """Jitted block-table / page ops, shared by every batcher instance
    (jax.jit caches per input shape, so two batchers with equal shapes
    share compiled code — a per-batcher closure would recompile these on
    every instance, and the page-growth path runs them MID-DECODE where a
    compile is a stall). Built on first use; the double-build race is
    benign (both results are equivalent, last write wins)."""
    ops = _page_table_ops.__dict__.get("ops")
    if ops is not None:
        return ops
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def set_block_row(bt, slot, row):
        return bt.at[slot].set(row)

    @partial(jax.jit, donate_argnums=(0,))
    def set_block_entry(bt, slot, idx, page):
        return bt.at[slot, idx].set(page)

    # Reset the POSITION rows of newly-allocated pages to PAD_POS: a page
    # off the free list still holds its previous owner's positions, and a
    # stale real position would make another sequence's mask attend
    # garbage. page_ids is padded to a fixed length with TRASH_PAGE
    # (re-masking trash is harmless), so one compile serves every
    # allocation size.
    @partial(jax.jit, donate_argnums=(0,))
    def reset_pages(caches, page_ids):
        return [
            layer[:-1] + (layer[-1].at[page_ids].set(PAD_POS),)
            for layer in caches
        ]

    # Per-slot admission update for the device-resident decode state (both
    # layouts; slot index is traced, so one compile serves every slot). The
    # position and key arrays are donated — the host never reads them;
    # last_tok is NOT donated because its buffer may alias a stacked token
    # output the host still has to read (see LLMServer._get_decode_step).
    @partial(jax.jit, donate_argnums=(1, 2))
    def set_slot(last_tok, next_pos, keys, slot, tok, pos, key):
        return (last_tok.at[slot].set(tok), next_pos.at[slot].set(pos),
                keys.at[slot].set(key))

    # Admission write of a slot's token-history row (speculative decoding:
    # the n-gram proposer and the verify step's accepted-token appends read
    # and extend this device-resident history). Donated like the other
    # per-slot state — the host keeps no mirror.
    @partial(jax.jit, donate_argnums=(0,))
    def set_hist_row(hist, slot, row):
        return hist.at[slot].set(row)

    # Per-slot adapter-id write (batched LoRA, runtime/adapters.py): the
    # admitted tenant's adapter row, gathered by every adapted step.
    # Donated like the other per-slot admission state.
    @partial(jax.jit, donate_argnums=(0,))
    def set_adapter_id(ids, slot, aid):
        return ids.at[slot].set(aid)

    # Copy-on-write page copy (radix prefix cache, runtime/radix.py): a
    # slot that must WRITE into a shared cached page gets a fresh page
    # plus this one donated copy — values move whole-page, but the
    # position row is masked to the source's VALID length (offsets past
    # n_valid go PAD_POS: the source page may carry a previous occupant's
    # run-ahead positions past its credited history, and copying those
    # live would make the new slot attend another sequence's tail). The
    # compiled form is pinned by the batcher.cow_page_copy hlolint
    # contract (pool donated in place, zero host transfers, budgeted
    # bytes — ONE page, not a prefix gather).
    @partial(jax.jit, donate_argnums=(0,))
    def cow_page_copy(caches, src, dst, n_valid):
        import jax.numpy as jnp

        out = []
        for layer in caches:
            vals = tuple(pool.at[dst].set(pool[src]) for pool in layer[:-1])
            pos = layer[-1]
            row = jnp.where(jnp.arange(pos.shape[1]) < n_valid,
                            pos[src], PAD_POS)
            out.append(vals + (pos.at[dst].set(row),))
        return out

    # Page export (disaggregated prefix reuse): gather the decode pool's
    # cached-prefix pages into a staged handoff-shaped bucket, so a
    # prefill worker can import them into its staging pool and compute
    # ONLY the uncached suffix. NOT donated — the pool (and the trie's
    # pages in it) stays live; the bucket is a transient the worker
    # device_puts away. Pinned by the disagg.prefix_export hlolint
    # contract (zero host transfers, bucket-not-pool bytes).
    @jax.jit
    def export_pages(caches, idx):
        return [tuple(pool[idx] for pool in layer) for layer in caches]

    ops = (set_block_row, set_block_entry, reset_pages, set_slot,
           set_hist_row, cow_page_copy, export_pages, set_adapter_id)
    _page_table_ops.ops = ops
    return ops


class PageAllocator:
    """Host-side refcounted free-list allocator over the global KV page
    pool.

    Pages 0/1 are reserved (NULL/TRASH — models/transformer.py); the rest
    are handed out lowest-id-first, all-or-nothing, at refcount 1. The
    radix prefix cache (runtime/radix.py) shares live pages between the
    trie and slot block tables by growing the refcount (``retain``);
    ``free`` is one uniform decrement-and-free-on-zero for every release
    path, so a page returns to the free list exactly when its LAST owner
    lets go — and a page's refcount is the shared-ownership truth the
    trie's eviction policy reads (refcount 1 = trie-only, evictable;
    >1 = a live slot references it, never evictable). Every state
    transition happens under ``self._lock``: alloc/retain/free run on the
    batcher loop's worker threads while /metrics scrapes read the gauges
    from transport threads, and an unlocked refcount read-modify-write is
    exactly the double-free/double-allocation the deterministic-
    interleaving suite (tests/test_schedules.py) guards against.
    Over-freeing raises — a page freed past zero would be handed to two
    slots and silently cross-corrupt their KV."""

    def __init__(self, total_pages: int, page_size: int):
        if total_pages <= RESERVED_PAGES:
            raise ValueError(
                f"page pool needs > {RESERVED_PAGES} pages (got {total_pages})")
        self.total = int(total_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # pop() from the tail hands out the lowest free id: deterministic
        # placement makes schedule replays and parity tests reproducible
        self._free = list(range(self.total - 1, RESERVED_PAGES - 1, -1))
        self._refs: Dict[int, int] = {}   # page -> refcount (allocated only)
        self.shed_total = 0

    @property
    def capacity(self) -> int:
        return self.total - RESERVED_PAGES

    def alloc(self, n: int) -> Optional[List[int]]:
        """n pages at refcount 1, all-or-nothing; None when the pool
        can't cover it."""
        with self._lock:
            if n > len(self._free):
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            return pages

    def retain(self, pages: Sequence[int]) -> None:
        """Add one reference to each (already-allocated) page — the trie
        pinning matched pages into a slot's block table. Retaining a free
        page raises: it would resurrect a page another alloc may own."""
        with self._lock:
            for p in pages:
                if p not in self._refs:
                    raise ValueError(f"retain of unallocated page {p}")
            for p in pages:
                self._refs[p] += 1

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page rejoins the free list when
        its count reaches zero. Raises on a page not currently allocated
        (double free / reserved id)."""
        with self._lock:
            for p in pages:
                rc = self._refs.get(p)
                if rc is None or not (RESERVED_PAGES <= p < self.total):
                    raise ValueError(f"double/invalid free of page {p}")
                if rc > 1:
                    self._refs[p] = rc - 1
                else:
                    del self._refs[p]
                    self._free.append(p)

    def refs_of(self, page: int) -> int:
        """Current refcount (0 = free) — the trie's evictability probe."""
        with self._lock:
            return self._refs.get(page, 0)

    def refs_map(self, pages: Sequence[int]) -> List[int]:
        """Refcounts for many pages under ONE lock acquisition (the
        trie's stats walk reads every node's count per /metrics scrape —
        per-page locking would be O(nodes) lock round-trips)."""
        with self._lock:
            return [self._refs.get(p, 0) for p in pages]

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def count_shed(self) -> None:
        """One page-exhaustion shed (counted under the same lock as the
        free list it describes)."""
        with self._lock:
            self.shed_total += 1

    def stats(self):
        """(total, in_use, shed_total) — one consistent snapshot."""
        with self._lock:
            return self.total, self.capacity - len(self._free), self.shed_total


class _PrefillJob:
    """One chunked admission in progress: the slot it targets, the (already
    truncated) prompt, the next write offset, and the device block-table
    row its chunks write through. Only one job runs at a time; decode
    dispatches interleave between its chunks."""

    __slots__ = ("slot", "ids", "L", "next", "chunk", "max_new", "fut",
                 "on_token", "info", "seed", "bt_row", "pages", "t_arrival",
                 "req")

    def __init__(self, slot, ids, start, chunk, max_new, fut, on_token,
                 info, seed, bt_row, pages, t_arrival=None, req=None):
        self.slot = slot
        self.ids = ids
        self.L = len(ids)
        self.next = start            # first position the next chunk writes
        self.chunk = chunk
        self.max_new = max_new
        self.fut = fut
        self.on_token = on_token
        self.info = info
        self.seed = seed
        self.bt_row = bt_row         # device [1, n_pages] int32
        self.pages = pages           # host mirror of the allocated pages
        self.t_arrival = t_arrival   # submit() wall clock, for TTFT
        # the scheduler's PendingRequest: tenant/SLO identity, adapter id,
        # and the preemption return path (an interactive admission may
        # push a staged batch-class job back into the queue)
        self.req = req


class _RemoteJob:
    """One admission staged on the prefill slice (disaggregated serving):
    the slot reserved for it, the (already truncated) prompt, the
    decode-side pages allocated for the import (paged layout; ``row`` is
    the NULL-padded host block row those pages form, led by
    ``prefix_pages`` shared radix-trie pages the worker never recomputes),
    and the request bookkeeping the consume path needs to commit the
    slot. The handoff itself travels through the TransferQueue; this
    record is the decode side's half of the rendezvous, keyed by
    ``job_id``."""

    __slots__ = ("job_id", "slot", "ids", "L", "plen", "max_new", "fut",
                 "on_token", "info", "seed", "pages", "row", "prefix_pages",
                 "t_arrival", "req")

    def __init__(self, job_id, slot, ids, plen, max_new, fut, on_token,
                 info, seed, pages, row, t_arrival, prefix_pages=0,
                 req=None):
        self.job_id = job_id
        self.slot = slot
        self.ids = ids
        self.L = len(ids)
        self.plen = plen
        self.max_new = max_new
        self.fut = fut
        self.on_token = on_token
        self.info = info
        self.seed = seed
        self.pages = pages           # decode-side SUFFIX pages (host mirror)
        self.row = row               # host [n_pages] int32 block row, or None
        self.prefix_pages = int(prefix_pages)  # shared trie pages leading row
        self.t_arrival = t_arrival
        self.req = req               # scheduler PendingRequest (tenant/SLO)


class _Slot:
    __slots__ = ("future", "tokens", "true_len", "n_new", "max_new", "active",
                 "on_token", "gen", "disp_new", "pages", "shared", "ids",
                 "prefilling", "admit_seq", "t_last", "tenant", "slo_class",
                 "adapter_id")

    def __init__(self):
        self.active = False
        # multi-tenant identity (runtime/scheduler.py): who this occupant
        # belongs to, which SLO class its latency counts against, and the
        # LoRA adapter row every adapted step gathers for it (0=identity).
        # The adapter is PINNED in the registry while this slot holds it.
        self.tenant = ""
        self.slo_class = "interactive"
        self.adapter_id = 0
        # wall clock of the last token surfaced for this occupant (TTFT /
        # inter-token-gap observability; reset at every commit)
        self.t_last = None
        self.future: Optional[asyncio.Future] = None
        self.tokens: List[int] = []
        self.true_len = 0
        self.n_new = 0          # tokens the HOST has processed (drain side)
        self.max_new = 0
        self.on_token: Optional[Any] = None
        # pipelining state: gen disambiguates a slot reused between a step's
        # dispatch and its drain (trailing speculative tokens for the old
        # occupant must be ignored, never credited to the new one);
        # disp_new is the DISPATCH-side token count advanced when a step is
        # enqueued, used to stop dispatching for exhausted slots and to
        # clamp the fused-K block so it never overruns max_new/max_len
        self.gen = 0
        self.disp_new = 0
        # paged layout: the slot's OWNED page ids (host mirror of the
        # owned tail of its block-table row — freed, or adopted by the
        # radix trie, at release), the SHARED trie pages its row leads
        # with (radix prefix hit: pinned at admission, unpinned at
        # release, never written by this slot), whether a chunked prefill
        # is mid-flight for it, and its admission sequence number
        # (shed-victim ordering: newest admitted sheds first on page
        # exhaustion). ``ids`` keeps the truncated prompt so completion
        # can insert prompt+generated blocks back into the trie.
        self.pages: List[int] = []
        self.shared: List[int] = []
        self.ids: Optional[List[int]] = None
        self.prefilling = False
        self.admit_seq = 0

    def covered_pages(self) -> int:
        """Block-table entries pointing at real pages (shared + owned)."""
        return len(self.shared) + len(self.pages)

    # cache positions are derived, never mirrored: after the prompt's L
    # tokens the n-th generated token sits at position true_len + n - 1
    def host_pos(self) -> int:
        return self.true_len + self.n_new - 1

    def dispatched_pos(self) -> int:
        return self.true_len + self.disp_new - 1


class _InFlight:
    """One dispatched (possibly K-fused) decode step the host has not yet
    drained: the device token array, the per-slot (index, gen) snapshot
    taken at dispatch, and the dispatch timestamp.

    Speculative verify steps additionally carry ``acc`` (the device [S]
    accepted-token counts — how far each slot ACTUALLY advanced, 1..K+1)
    and ``booked`` (slot -> the pessimistic K+1 maximum the dispatch
    side credited to ``disp_new``; the drain reconciles the difference)."""

    __slots__ = ("tokens", "k", "snapshot", "t_dispatch", "acc", "booked")

    def __init__(self, tokens, k, snapshot, t_dispatch, acc=None,
                 booked=None):
        self.tokens = tokens
        self.k = k
        self.snapshot = snapshot
        self.t_dispatch = t_dispatch
        self.acc = acc
        self.booked = booked


class BatcherService:
    """Owns a ContinuousBatcher on a dedicated event-loop thread so every
    transport can reach ONE shared batch: async REST handlers await
    ``submit``, the sync gRPC servicer blocks on ``submit_sync`` — either
    way the request joins the in-flight decode batch instead of running its
    own ``generate()``. Created lazily per component by
    ``get_batcher_service`` (keyed on the component, so REST and gRPC in one
    process share slots)."""

    def __init__(self, server: "LLMServer", max_slots: int = 4):
        import threading

        self._loop = asyncio.new_event_loop()
        threading.Thread(target=self._loop.run_forever, name="batcher-loop",
                         daemon=True).start()
        max_len = getattr(server, "continuous_batching_max_len", None)

        async def make():
            return ContinuousBatcher(server, max_slots=max_slots,
                                     max_len=max_len)

        self.batcher = asyncio.run_coroutine_threadsafe(make(), self._loop).result()
        self.submitted = 0
        # Requests handed to the loop whose futures have not resolved yet.
        # This covers the drain blind window the batcher itself cannot see:
        # between run_coroutine_threadsafe and the submit coroutine actually
        # running on the loop thread, a request exists in NO batcher
        # structure (_pending/_slots/_inflight) — is_idle() must still
        # count it, or collect_drained could close a batcher holding a
        # live client request.
        self._inflight_reqs = 0
        # submit() runs on transport loops and submit_sync() on gRPC worker
        # threads at once; the counter bumps are read-modify-writes, and
        # unlocked concurrent increments lose updates
        self._stats_lock = threading.Lock()

    def _track(self, cfut):
        """Count one submission in flight until its future settles (any
        outcome — tokens, shed, error: settled means the batcher no longer
        owes the client anything). Incremented BEFORE the caller can
        observe the future, so is_idle() has no window where a submitted
        request is invisible."""
        with self._stats_lock:
            self.submitted += 1
            self._inflight_reqs += 1

        def _settled(_f):
            with self._stats_lock:
                self._inflight_reqs -= 1

        cfut.add_done_callback(_settled)
        return cfut

    def submit_sync(self, prompt: Any, max_new_tokens: Optional[int] = None,
                    timeout_s: float = 600.0,
                    info: Optional[dict] = None,
                    seed: Optional[int] = None,
                    trace: Optional[Any] = None,
                    tenant: Optional[str] = None,
                    slo_class: Optional[str] = None,
                    adapter: Optional[str] = None,
                    deadline_s: Optional[float] = None,
                    on_token: Optional[Any] = None,
                    resume_tokens: int = 0) -> List[int]:
        return self._track(asyncio.run_coroutine_threadsafe(
            self.batcher.submit(prompt, max_new_tokens, on_token=on_token,
                                info=info, seed=seed,
                                trace=trace, tenant=tenant,
                                slo_class=slo_class, adapter=adapter,
                                deadline_s=deadline_s,
                                resume_tokens=resume_tokens),
            self._loop
        )).result(timeout_s)

    async def submit(self, prompt: Any, max_new_tokens: Optional[int] = None,
                     on_token: Optional[Any] = None,
                     info: Optional[dict] = None,
                     seed: Optional[int] = None,
                     trace: Optional[Any] = None,
                     tenant: Optional[str] = None,
                     slo_class: Optional[str] = None,
                     adapter: Optional[str] = None,
                     deadline_s: Optional[float] = None,
                     resume_tokens: int = 0) -> List[int]:
        cfut = self._track(asyncio.run_coroutine_threadsafe(
            self.batcher.submit(prompt, max_new_tokens, on_token=on_token,
                                info=info, seed=seed, trace=trace,
                                tenant=tenant, slo_class=slo_class,
                                adapter=adapter, deadline_s=deadline_s,
                                resume_tokens=resume_tokens),
            self._loop))
        return await asyncio.wrap_future(cfut)

    def submit_stream(self, prompt: Any,
                      max_new_tokens: Optional[int] = None,
                      on_token: Optional[Any] = None,
                      info: Optional[dict] = None,
                      seed: Optional[int] = None,
                      trace: Optional[Any] = None,
                      tenant: Optional[str] = None,
                      slo_class: Optional[str] = None,
                      adapter: Optional[str] = None,
                      deadline_s: Optional[float] = None,
                      resume_tokens: int = 0):
        """Streaming submit from a SYNC thread (the gRPC server-streaming
        servicer): returns the concurrent.futures.Future of the final token
        list while ``on_token`` fires per token from the batcher's worker
        thread — the caller pumps its own response stream from them."""
        return self._track(asyncio.run_coroutine_threadsafe(
            self.batcher.submit(prompt, max_new_tokens, on_token=on_token,
                                info=info, seed=seed, trace=trace,
                                tenant=tenant, slo_class=slo_class,
                                adapter=adapter, deadline_s=deadline_s,
                                resume_tokens=resume_tokens),
            self._loop))

    def drain(self) -> None:
        """Scale-down drain mark (docs/control-plane.md): flips the
        batcher's advisory flag — in-flight and queued work is untouched."""
        self.batcher.drain()

    def resume(self) -> None:
        """Cancel a drain (scale-up arrived before detach): the warm
        batcher rejoins fleet dispatch."""
        self.batcher.resume()

    def is_idle(self) -> bool:
        """Detach gate for the autoscaler's collect sweep: the batcher's
        own idle check AND zero unsettled service-level submissions — the
        latter closes the window where a request scheduled onto the loop
        thread is not yet visible in any batcher structure."""
        with self._stats_lock:
            busy = self._inflight_reqs
        return busy == 0 and self.batcher.is_idle()

    def close(self) -> None:
        asyncio.run_coroutine_threadsafe(self.batcher.close(), self._loop).result(30)
        self._loop.call_soon_threadsafe(self._loop.stop)


# created at import time: a lazily-created lock would itself race, which is
# the exact bug this lock exists to prevent
import threading as _threading

_service_init_lock = _threading.Lock()


def _init_lock():
    return _service_init_lock


def get_batcher_service(component: Any) -> Optional[BatcherService]:
    """The component's shared BatcherService, created on first use when the
    component opted in (``continuous_batching`` slots > 0) and exposes the
    LLM generate surface; None otherwise. Creation is locked: the first REST
    request (event loop) and first gRPC request (worker thread) can race,
    and two batchers would each allocate slot caches and step the device."""
    if getattr(component, "is_fleet", False):
        # a ReplicaSet IS the service: it fans submits across replicas
        # (with health ejection + deterministic resume — runtime/engine.py)
        # and must never be wrapped in a batcher of its own
        return component
    svc = getattr(component, "_batcher_service", None)
    if svc is not None:
        return svc  # reuse even when batching is off (streaming's 1-slot svc)
    slots = int(getattr(component, "continuous_batching", 0) or 0)
    if slots <= 0 or not hasattr(component, "generate"):
        return None
    with _init_lock():
        svc = getattr(component, "_batcher_service", None)
        if svc is None:
            svc = BatcherService(component, max_slots=slots)
            component._batcher_service = svc
    return svc


def ensure_stream_service(component: Any) -> BatcherService:
    """Streaming without continuous batching: one shared 1-slot service per
    component (same double-checked lock; never one per request).
    A fleet (ReplicaSet) short-circuits through get_batcher_service."""
    svc = get_batcher_service(component)
    if svc is not None:
        return svc
    with _init_lock():
        svc = getattr(component, "_batcher_service", None)
        if svc is None:
            svc = BatcherService(component, max_slots=1)
            component._batcher_service = svc
    return svc


class ContinuousBatcher:
    def __init__(
        self,
        server: LLMServer,
        max_slots: int = 4,
        max_len: Optional[int] = None,
        len_buckets: Optional[Sequence[int]] = None,
        pipeline_depth: Optional[int] = None,
        fuse_steps: Optional[int] = None,
        layout: Optional[str] = None,
        page_size: Optional[int] = None,
        pool_pages: Optional[int] = None,
        prefill_chunk: Optional[int] = None,
        spec_mode: Optional[str] = None,
        spec_k: Optional[int] = None,
        disaggregation: Optional[str] = None,
        disagg_mesh: Optional[Any] = None,
        prefill_workers: Optional[int] = None,
        handoff_transport: Optional[str] = None,
        tracing: Optional[bool] = None,
    ):
        server.load()
        self.server = server
        self.S = int(max_slots)
        cfg = server._cfg
        # Slot caches are HBM-resident for the batcher's whole life (S slots
        # x max_len x KV bytes/token — ~0.5 MB/token at 7B), so size them to
        # what serving actually admits: prompts bucket to len_buckets with
        # one round-up step past the top bucket (_bucket), plus decode
        # headroom. Defaulting to the model's full trained context instead
        # (4k at 7B) allocates 17 GB of KV and OOMs the chip before the
        # first request. Prompts longer than 2x the top bucket truncate to
        # the cache (admit keeps the TAIL, same rule as before); a
        # deployment expecting longer prompts passes max_len explicitly
        # (LLMServer.continuous_batching_max_len).
        self.len_buckets = tuple(len_buckets or server.len_buckets)
        if max_len is not None and int(max_len) <= 0:
            # 0/negative means "unset" from every caller's point of view;
            # taking it literally would produce plen=min(...,-1) nonsense
            # tail slicing (ADVICE.md round 5)
            max_len = None
        if max_len is None:
            max_len = min(2 * max(self.len_buckets), cfg.max_seq_len) + max(
                int(server.max_new_tokens), 1
            )
        self.max_len = int(max_len)
        self.eos_id = server.eos_id
        self._slots = [_Slot() for _ in range(self.S)]
        from collections import deque

        # SLO-aware weighted-fair admission queue (runtime/scheduler.py,
        # ISSUE 15): replaces the FIFO deque — requests order by SLO class
        # (interactive vs batch) and tenant under stride-scheduled
        # weighted fairness, with per-tenant quotas shedding early and
        # deadline-carrying requests ordered EDF within their tenant. The
        # peek-try-commit admission idiom is unchanged: a failed admit
        # keeps the request queued.
        from seldon_core_tpu.runtime.scheduler import WeightedFairScheduler

        self._pending: Any = WeightedFairScheduler(
            class_weights=getattr(server, "slo_class_weights", None),
            tenant_weights=getattr(server, "tenant_weights", None),
            tenant_quota=int(getattr(server, "tenant_quota", 0) or 0),
            tenant_quotas=getattr(server, "tenant_quotas", None))
        # Batched LoRA (runtime/adapters.py): when the server carries an
        # AdapterRegistry every compiled step runs the adapted variant —
        # per-slot adapter ids gather each tenant's low-rank delta, with
        # id 0 the zero-delta identity for untenanted traffic.
        self._adapters = getattr(server, "adapter_registry", None)
        self._wakeup = asyncio.Event()
        self._closed = False
        self._task: Optional[asyncio.Task] = None
        # Fleet health view (docs/resilience.md "Fleet fault tolerance"):
        # the loop stamps ``heartbeat`` once per turn from its single
        # serialized context and parks its terminal exception in
        # ``crashed`` — plain single-writer fields ReplicaSet.check_health
        # reads to eject a dead replica from dispatch. ``clock`` is
        # injectable so chaos tests drive staleness from a FaultClock, and
        # ``_chaos`` is the deterministic fault hook the chaos harness
        # installs (called at the top of every loop turn; raising there
        # kills the loop exactly where a real device fault would).
        import time as _time

        self.clock: Any = _time.monotonic
        self.heartbeat: float = self.clock()
        self.crashed: Optional[BaseException] = None
        self._chaos: Optional[Any] = None
        # dispatch-ahead pipeline: how many steps may be in flight before
        # the host drains the oldest (>=2 overlaps host bookkeeping with
        # device compute), and the fused-K knob (0/1 = single steps)
        depth = pipeline_depth if pipeline_depth is not None else getattr(
            server, "decode_pipeline_depth", 2)
        self.pipeline_depth = max(int(depth), 1)
        fuse = fuse_steps if fuse_steps is not None else getattr(
            server, "decode_fuse_steps", 0)
        self.fuse_steps = max(int(fuse), 0)
        # Speculative decoding (module docstring): draft mode + depth K,
        # resolved from the server unless overridden. The per-slot
        # acceptance-rate controller adapts the offered draft length to
        # what each slot's text actually accepts.
        from seldon_core_tpu.runtime.spec import (
            DEFAULT_SPEC_K, SpecController, normalize_spec_mode)

        mode = spec_mode if spec_mode is not None else getattr(
            server, "spec_mode", "off")
        self.spec_mode = normalize_spec_mode(mode)
        k = spec_k if spec_k is not None else getattr(server, "spec_k", 0)
        self.spec_k = int(k or 0) or DEFAULT_SPEC_K
        if self.spec_mode != "off":
            if self.fuse_steps > 1:
                raise ValueError(
                    f"decode_fuse_steps={self.fuse_steps} cannot combine "
                    f"with spec_mode={self.spec_mode!r}: the fused scan "
                    f"runs a FIXED K steps per dispatch while a verify "
                    f"step advances each slot by a data-dependent 1.."
                    f"{self.spec_k + 1} tokens — a fused variable-stride "
                    f"scan is a follow-up; run speculation with "
                    f"decode_fuse_steps=0 (pipelining composes fine)")
            if self.spec_k < 1:
                raise ValueError(
                    f"spec_k={self.spec_k} must be >= 1 when speculation "
                    f"is on")
            if self.spec_mode == "draft" and getattr(
                    server, "_draft_module", None) is None:
                raise ValueError(
                    "spec_mode='draft' needs the server loaded with a "
                    "draft model (draft_model= / draft_model_uri=)")
            self._spec = SpecController(self.S, self.spec_k)
        # KV layout: paged (global page pool + per-slot block tables) or the
        # historical dense slot pool. max_len keeps its requested value —
        # truncation/budget semantics are layout-independent — and the
        # block-table view simply spans ceil(max_len/page_size) pages (the
        # past-max_len tail of the last page is never written and its
        # PAD_POS rows are never attended).
        if layout is None:
            layout = getattr(server, "kv_cache_layout", "dense")
        self.paged = normalize_kv_cache_layout(layout) == "paged"
        if self.paged:
            ps = int(page_size if page_size is not None else
                     getattr(server, "kv_page_size", 0) or 0) or DEFAULT_PAGE_SIZE
            if ps <= 0:
                raise ValueError(f"kv_page_size={ps} must be positive")
            self.page_size = ps
            self.n_pages = -(-self.max_len // ps)   # pages per slot
            pool = int(pool_pages if pool_pages is not None else
                       getattr(server, "kv_pool_pages", 0) or 0)
            # 0 = fully provisioned (every slot can reach max_len at once —
            # never sheds on pages); smaller pools oversubscribe
            self.pool_pages = pool or (self.S * self.n_pages + RESERVED_PAGES)
            if self.pool_pages - RESERVED_PAGES < self.n_pages:
                raise ValueError(
                    f"kv_pool_pages={self.pool_pages} cannot hold even one "
                    f"max_len sequence ({self.n_pages} pages of {ps} tokens "
                    f"+ {RESERVED_PAGES} reserved)")
            chunk = int(prefill_chunk if prefill_chunk is not None else
                        getattr(server, "prefill_chunk", 0) or 0)
            self.prefill_chunk = chunk or DEFAULT_PREFILL_CHUNK
            self._allocator = PageAllocator(self.pool_pages, ps)
        # Radix prefix cache (runtime/radix.py, docs/performance.md "Radix
        # prefix cache"): paged layout + prefix caching opted in. The trie
        # shares pool pages between cached prefixes and live slots
        # (refcounted, copy-on-write), so a hit costs block-table entries
        # instead of a page gather/copy; completed slots insert their
        # blocks back in place. The dense layout keeps no batcher-side
        # prefix reuse (its slots pre-reserve whole caches).
        self._radix = None
        if self.paged and int(getattr(server, "prefix_cache_size", 0)) > 0:
            from seldon_core_tpu.models.transformer import \
                kv_cache_bytes_per_token
            from seldon_core_tpu.runtime.radix import RadixPrefixCache

            self._radix = RadixPrefixCache(
                self._allocator, self.page_size,
                bytes_per_block=self.page_size * kv_cache_bytes_per_token(
                    cfg, server.kv_cache_dtype))
        self._prefill: Optional[_PrefillJob] = None
        self._admit_seq = 0
        # Drain state (docs/control-plane.md "Drain semantics"): set by the
        # autoscaler's scale-down path through ReplicaSet.drain_replica —
        # fleet routing stops targeting this replica, but anything already
        # queued or in flight here runs to completion, and a request that
        # slipped through the routing race window is still served (a drain
        # may delay detach; it must never fail a client).
        self.draining = False
        self._inflight: Any = deque()
        self._inflight_hwm = 0       # max steps in flight ever reached
        self._last_admit_inflight = 0  # steps in flight at the last admit
        self._last_drain_t: Optional[float] = None
        # Disaggregated prefill/decode (module docstring): remote-prefill
        # admission stages jobs on prefill-slice workers and consumes
        # finished handoffs from the TransferQueue instead of prefilling
        # locally. Resolved from the server unless overridden.
        from seldon_core_tpu.runtime.disagg import normalize_disaggregation

        disagg = disaggregation if disaggregation is not None else getattr(
            server, "disaggregation", "off")
        self.disaggregation = normalize_disaggregation(disagg)
        # How finished prefills reach the decode slice: "device" keeps the
        # jax.device_put fast path; "network" frames the KV bucket and
        # streams it through a HandoffReceiver (cross-host decode —
        # bit-exact vs device, tests/test_network_handoff.py).
        from seldon_core_tpu.runtime.disagg import HANDOFF_TRANSPORTS

        ht = handoff_transport if handoff_transport is not None else getattr(
            server, "handoff_transport", "") or "device"
        if ht not in HANDOFF_TRANSPORTS:
            raise ValueError(
                f"unknown handoff_transport {ht!r}: expected one of "
                f"{HANDOFF_TRANSPORTS}")
        self.handoff_transport = ht
        self._remote = None
        self._transfer = None
        self._receiver = None
        self._remote_jobs: "dict[int, _RemoteJob]" = {}
        self._job_seq = 0
        # Flight recorder (module docstring, runtime/flight.py): built only
        # when the tracer is enabled (``tracing`` overrides for tests and
        # the bench's overhead arm) — disabled tracing leaves every hook a
        # None check and the compiled step path untouched.
        from seldon_core_tpu.tracing import get_tracer, tail_thresholds

        self._tracer = get_tracer()
        enabled = self._tracer.enabled if tracing is None else bool(tracing)
        if enabled:
            from seldon_core_tpu.runtime.flight import FlightRecorder

            tail_ttft_s, tail_gap_s = tail_thresholds()
            self._flight: Optional[Any] = FlightRecorder(
                self.S, tail_ttft_s=tail_ttft_s, tail_gap_s=tail_gap_s)
        else:
            self._flight = None
        self._build()
        if self.disaggregation != "off":
            self._build_remote(disagg_mesh, prefill_workers)

    # ------------------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import init_kv_caches

        from functools import partial

        server, cfg = self.server, self.server._cfg
        # slot caches inherit the server's KV storage format (int8 halves
        # the per-step attention read traffic — the dominant b8 term in
        # benchmarks/DECODE_NOTES.md)
        if self.paged:
            from seldon_core_tpu.models.transformer import (
                PAD_POS, init_paged_kv_caches)

            self._caches = jax.jit(
                lambda: init_paged_kv_caches(
                    cfg, self.pool_pages, self.page_size, server.kv_cache_dtype)
            )()
        else:
            self._caches = jax.jit(
                lambda: init_kv_caches(cfg, self.S, self.max_len, server.kv_cache_dtype)
            )()
        self._cache_nbytes = sum(
            int(getattr(leaf, "nbytes", 0)) for leaf in jax.tree.leaves(self._caches)
        )

        if self.paged:
            # Paged pool: no insert — chunked prefill writes straight into
            # the pool through the slot's block-table row. The device block
            # table (one row per slot) starts all-TRASH so inactive slots'
            # ride-along decode writes land in the trash page; rows switch
            # to real pages at activation and back to trash at release.
            # Every table/pos mutation is a donated jit, so program order on
            # the device stream serializes it behind in-flight steps exactly
            # like the dense insert (see module docstring).
            self._block_tables = jnp.full(
                (self.S, self.n_pages), TRASH_PAGE, jnp.int32)
            self._trash_row = jnp.full((self.n_pages,), TRASH_PAGE, jnp.int32)
        else:
            # donate the big slot cache through both mutating jits (insert
            # and the decode step): self._caches is reassigned from the
            # output each time, so XLA aliases the buffers and updates in
            # place instead of copying S x max_len of KV per call. These
            # donations are verified at the COMPILED level
            # (input_output_alias) by the batcher.insert / batcher.set_slot
            # / llm.decode_step_s4 contracts in tools/hlolint — a
            # cache-structure change that silently breaks the aliasing fails
            # CI, not a 7B perf round. (small is NOT donated: its 1-slot
            # buffers can alias no output, XLA would just drop it.)
            @partial(jax.jit, donate_argnums=(0,))
            def insert(big, small, slot):
                return jax.tree.map(lambda b, s: b.at[slot].set(s[0]), big, small)

            self._insert = insert

        # jitted table/slot-state ops are process-shared singletons
        # (_page_table_ops): a fresh batcher reuses the compiled code of
        # any prior batcher with the same shapes instead of recompiling
        # its own closures — page growth runs these mid-decode, where a
        # compile is a serving stall
        (self._set_block_row, self._set_block_entry, self._reset_pages,
         self._set_slot, self._set_hist_row, self._cow_page_copy,
         self._export_pages, self._set_adapter_id) = _page_table_ops()

        if self._adapters is not None:
            # per-slot adapter ids, device-resident like the decode state
            # (every adapted step gathers by them); 0 = identity
            self._adapter_ids = jnp.zeros((self.S,), jnp.int32)

        if self.spec_mode != "off":
            # Per-slot prompt+generated token history, device-resident: the
            # n-gram proposer matches against it and the verify step appends
            # accepted tokens to it inside the compiled program. One entry
            # per cache position, so every token a slot can ever hold fits.
            self.hist_len = self.max_len
            self._hist = jnp.zeros((self.S, self.hist_len), jnp.int32)
            if self.spec_mode == "draft":
                # The draft model's KV is always DENSE [S, max_len]: the
                # draft is small by construction, so paging it would buy
                # nothing and cost a second allocator. Prompt prefill lands
                # through the same insert idiom as the dense target path.
                dcfg = server._draft_cfg
                self._draft_caches = jax.jit(
                    lambda: init_kv_caches(dcfg, self.S, self.max_len))()

                @partial(jax.jit, donate_argnums=(0,))
                def draft_insert(big, small, slot):
                    return jax.tree.map(
                        lambda b, s: b.at[slot].set(s[0]), big, small)

                self._draft_insert = draft_insert

        # device-resident per-slot decode state, threaded output->input
        # through every dispatched step (the decode jit updates them; the
        # host never round-trips them through NumPy)
        self._last_tok = jnp.zeros((self.S,), jnp.int32)
        self._next_pos = jnp.zeros((self.S,), jnp.int32)
        self._keys = jnp.zeros((self.S, 2), jnp.uint32)

        self._rng = jax.random.PRNGKey(server.seed)
        self._temp = jnp.asarray(server.temperature, jnp.float32)

    # ------------------------------------------------------------------
    # Disaggregated prefill: slice setup, handoff import, stats
    # ------------------------------------------------------------------
    def _build_remote(self, disagg_mesh, prefill_workers):
        """Split the device world and start the prefill-worker pool. The
        decode slice must contain the process DEFAULT device: the slot
        pool and every decode-side jit live uncommitted there, so
        anchoring the decode role on it means no serving-path array ever
        needs explicit placement — only the prefill workers commit copies
        to their own devices."""
        from seldon_core_tpu.parallel.topology import get_topology
        from seldon_core_tpu.runtime.disagg import (HandoffReceiver,
                                                    PrefillWorkerPool,
                                                    TransferQueue)

        server = self.server
        topo = getattr(server, "topology", None) or get_topology()
        mesh = disagg_mesh or getattr(server, "disagg_mesh", None)
        if mesh is None:
            mesh = topo.disaggregated(
                getattr(server, "prefill_devices", 0) or 1,
                getattr(server, "decode_devices", 0) or 0)
        default = topo.default_device
        if default not in mesh.decode_devices:
            raise ValueError(
                "the decode slice must contain the process default device "
                f"({default}): the batcher's slot pool lives there — put "
                "the PREFILL slice on the non-default devices")
        self.disagg_mesh = mesh
        n_workers = (prefill_workers
                     if prefill_workers is not None else
                     getattr(server, "prefill_workers", 0)) or len(
                         mesh.prefill_devices)
        devices = [mesh.prefill_devices[i % len(mesh.prefill_devices)]
                   for i in range(int(n_workers))]
        # the queue is built here (not inside the pool) so the network
        # receiver and the worker pool share it from birth — rebalance
        # swaps pools around BOTH
        queue = TransferQueue()
        receiver_addr = None
        if self.handoff_transport == "network":
            self._receiver = HandoffReceiver(queue, default)
            receiver_addr = self._receiver.addr
        self._remote = PrefillWorkerPool(
            server, devices, default,
            layout="paged" if self.paged else "dense",
            max_len=self.max_len,
            page_size=self.page_size if self.paged else 0,
            n_pages=self.n_pages if self.paged else 0,
            prefill_chunk=self.prefill_chunk if self.paged else 0,
            queue=queue, transport=self.handoff_transport,
            receiver_addr=receiver_addr)
        self._transfer = self._remote.queue

    def rebalance_disagg(self, prefill_devices: int) -> bool:
        """Move the prefill:decode device split to ``prefill_devices``
        prefill devices — the autoscaler's TPU-native actuator
        (controlplane/autoscaler.py; docs/control-plane.md "Rebalancing
        the disagg split").  Zero requests are dropped and generation is
        bit-exact across the move:

        - the NEW worker pool publishes into the SAME TransferQueue, so
          every registered job keeps its exactly-once delivery path;
        - the OLD pool's close() drains its backlog first — workers
          finish staged jobs and publish them before their threads join;
        - workers run the server's own cached compiled prefill programs,
          so WHERE prefill runs changes, never which KV bits come out
          (tests/test_autoscaler.py parity, dense + paged).

        Returns False when disaggregation is off, the split is already
        there, or the requested split is infeasible (decode must keep the
        process default device — the slot pool lives on it)."""
        if self._remote is None:
            return False
        from seldon_core_tpu.parallel.topology import get_topology
        from seldon_core_tpu.runtime.disagg import PrefillWorkerPool

        topo = getattr(self.server, "topology", None) or get_topology()
        n_pre = int(prefill_devices)
        if n_pre < 1 or n_pre >= topo.device_count:
            return False
        if n_pre == len(self.disagg_mesh.prefill_devices):
            return False
        mesh = topo.disaggregated(n_pre, 0)
        default = topo.default_device
        if default not in mesh.decode_devices:
            return False
        old = self._remote
        new_pool = PrefillWorkerPool(
            self.server, mesh.prefill_devices, default,
            layout="paged" if self.paged else "dense",
            max_len=self.max_len,
            page_size=self.page_size if self.paged else 0,
            n_pages=self.n_pages if self.paged else 0,
            prefill_chunk=self.prefill_chunk if self.paged else 0,
            queue=self._transfer, transport=old.transport,
            receiver_addr=old.receiver_addr)
        self.disagg_mesh = mesh
        # swap first (new admissions land on the new pool), then drain the
        # old pool: an admission that grabbed the old reference mid-swap
        # either submits before close (job drains normally) or gets the
        # closed error and retries on the new pool (_admit_remote)
        self._remote = new_pool
        old.close()
        logger.info("rebalanced disagg split to %d prefill / %d decode "
                    "devices", len(mesh.prefill_devices),
                    len(mesh.decode_devices))
        return True

    def _get_handoff_import(self, staged_pages: Optional[int] = None):
        """Jitted staged-pool -> slot-pool page import (the decode-side
        half of the KV handoff). ``staged_pages`` is the page count of the
        transferred buffer beyond the reserved rows (workers ship a
        power-of-two bucket, not the whole staging pool). Compiled and
        cached ON THE SERVER (servers/llmserver.py ``_get_handoff_import``,
        like the prefill programs) so rebuilt batchers and bench arms
        share one compile per bucket. Compiled-form contract:
        ``disagg.import_pages`` in tools/hlolint (zero host transfers,
        donation intact, bytes within budget)."""
        return self.server._get_handoff_import(self.n_pages, staged_pages)

    def drain(self) -> None:
        """Mark this batcher draining (scale-down): purely advisory state —
        admission keeps working so nothing routed here can ever fail, but
        the fleet dispatcher (ReplicaSet) stops targeting the replica and
        the scaling snapshot reports the state."""
        self.draining = True

    def resume(self) -> None:
        self.draining = False

    def is_idle(self) -> bool:
        """True when detaching this batcher cannot drop work: no queued
        request, no occupied or prefilling slot, no in-flight step, no
        staged local or remote prefill job.  The autoscaler's
        ``collect_drained`` gate."""
        return (len(self._pending) == 0 and not self._inflight
                and self._prefill is None and not self._remote_jobs
                and not any(s.active or s.prefilling for s in self._slots))

    def retry_after_hint(self) -> float:
        """Dynamic ``Retry-After`` for shed responses, derived from the
        actual backlog instead of the fixed constant: the drain capacity
        is S slots per wave, so a client retrying after
        ``base x ceil(queued work / S)`` seconds arrives roughly when the
        work ahead of it has drained — backoff scales with the exact
        spike the autoscaler is reacting to, instead of stampeding back
        into it.  Near page-pool exhaustion the hint doubles (pages free
        slower than slots under LIFO shedding).  Clamped to
        [base, 30s]."""
        from seldon_core_tpu.runtime.resilience import DEFAULT_RETRY_AFTER_S

        base = float(getattr(self.server, "shed_retry_after_s",
                             DEFAULT_RETRY_AFTER_S))
        queued = len(self._pending) + sum(
            1 for s in self._slots if s.active or s.prefilling)
        waves = -(-queued // max(self.S, 1))
        hint = base * max(waves, 1)
        if self.paged:
            total, in_use, _ = self._allocator.stats()
            usable = max(total - RESERVED_PAGES, 1)
            if in_use / usable >= 0.9:
                hint *= 2
        # the cap must never undercut an explicitly configured base: a
        # 60s floor stays 60s, it does not become 30s
        return float(min(max(hint, base), max(30.0, base)))

    def handoff_stats(self) -> dict:
        """Transfer-queue counters for llm_stats/metrics: handoffs
        delivered, bytes moved device-to-device, and the jobs currently
        staged or ready (the prefill-slice backlog signal replica routing
        steers by). All-off zeros when disaggregation is off."""
        if self._remote is None:
            return {"disaggregation": "off", "handoffs_total": 0,
                    "handoff_transfer_bytes_total": 0,
                    "handoff_queue_depth": 0,
                    "handoff_network_bytes_total": 0}
        total, nbytes, depth = self._transfer.stats()
        net = (self._receiver.stats()["handoff_network_bytes_total"]
               if self._receiver is not None else 0)
        return {
            "disaggregation": self.disaggregation,
            "handoffs_total": total,
            "handoff_transfer_bytes_total": nbytes,
            # staged + ready jobs (a registered job stays counted while it
            # waits in a worker backlog, runs, and sits ready — exactly
            # the prefill-side congestion a replica router cares about)
            "handoff_queue_depth": depth,
            # wire payload bytes received by the network transport (0 on
            # the device fast path — the split tells an operator which
            # transport is actually carrying the KV)
            "handoff_network_bytes_total": net,
        }

    # ------------------------------------------------------------------
    async def submit(self, prompt: Any, max_new_tokens: Optional[int] = None,
                     on_token: Optional[Any] = None,
                     info: Optional[dict] = None,
                     seed: Optional[int] = None,
                     trace: Optional[Any] = None,
                     tenant: Optional[str] = None,
                     slo_class: Optional[str] = None,
                     adapter: Optional[str] = None,
                     deadline_s: Optional[float] = None,
                     resume_tokens: int = 0) -> List[int]:
        """prompt: str or token sequence. Resolves to generated token ids.

        ``resume_tokens`` (fleet recovery, docs/resilience.md): non-zero
        marks this submission as the RESUMPTION of a generation that
        already delivered that many tokens on a replica that died —
        ``prompt`` then carries prompt+generated-prefix and the sampling
        chain fast-forwards past the delivered tokens so the continuation
        is bit-exact (see _sample_first).

        Multi-tenant identity (docs/multitenancy.md): ``tenant`` names the
        traffic owner (``Seldon-Tenant`` header), ``slo_class`` its
        scheduling class ("interactive" default / "batch" — the
        ``Seldon-SLO-Class`` header; unknown values raise), ``adapter``
        a loaded LoRA adapter (``"adapter"`` body/jsonData field; unknown
        names raise — never a silent base-model fallback), and
        ``deadline_s`` a latency budget in seconds that orders this
        request EDF within its tenant queue and marks it for the
        interactive preemption path.

        ``trace`` (optional ``tracing.TraceContext``) carries the request's
        trace identity from the transport ingress (W3C ``traceparent``) into
        the flight recorder, which roots this request's span tree at it. A
        None trace with the recorder running still records a timeline under
        a fresh trace id; with the recorder off it is ignored entirely.

        ``on_token(tok)`` (optional) fires for every generated token as it is
        decoded and ``on_token(None)`` once at completion — from a worker
        thread, so the callback must be thread-safe (streaming transports
        bridge it onto their loop with call_soon_threadsafe). Under
        pipelining the callback trails the device by up to
        ``pipeline_depth`` steps (token ORDER is unchanged).

        ``info`` (optional dict) is filled in-place at admission with
        anything the caller should surface to the client — today the
        ``truncated_prompt`` record when the slot cache is smaller than the
        prompt (transports attach it to the response meta).

        ``seed`` (optional) pins this request's sampling rng to the same
        chain ``generate(..., seed=seed)`` uses, so a seeded sampled request
        decodes the identical token sequence through the batcher (each slot
        carries its own per-request key device-side)."""
        if self._closed:
            # retryable, not a hard failure: the only way a live request
            # reaches a closed batcher is the stale-dispatch tail of a
            # scale-down (a pick held across multiple autoscaler ticks —
            # docs/control-plane.md "Drain semantics"); a 503+Retry-After
            # sends the client back through routing onto a live replica
            from seldon_core_tpu.runtime.resilience import ShedError

            raise ShedError("batcher closed (replica detached by "
                            "scale-down); retry routes to a live replica")
        import time

        if isinstance(prompt, str):
            ids = self.server._tokenizer.encode(prompt)
        else:
            ids = [int(t) for t in np.asarray(prompt).ravel()]
        if not ids:
            raise ValueError("empty prompt")
        self._loop = asyncio.get_running_loop()
        if self._transfer is not None and self._transfer.on_ready is None:
            # a finished handoff must wake the loop like a submit does —
            # otherwise activation waits out the 0.5 s idle timeout
            loop = self._loop
            self._transfer.on_ready = lambda: loop.call_soon_threadsafe(
                self._wakeup.set)
        from seldon_core_tpu.contracts.payload import SeldonError
        from seldon_core_tpu.runtime.scheduler import (PendingRequest,
                                                       normalize_slo_class)

        try:
            cls = normalize_slo_class(slo_class)
        except ValueError as e:
            raise SeldonError(str(e), status_code=400)
        aid = 0
        if adapter:
            if self._adapters is None:
                raise SeldonError(
                    f"adapter {adapter!r} requested but the server has no "
                    f"adapter pool (set lora_rank > 0)", status_code=400)
            # resolve + pin atomically, from the moment the request
            # exists anywhere: eviction refuses while this request is
            # queued or in a slot, so the dispatch-time gather can never
            # read a freed (or evict+load-repurposed) row. Unpinned
            # exactly once: on the terminal shed/fail while queued
            # (_unpin_request), or at slot release once admitted
            # (ownership moves to the slot at _commit_slot).
            try:
                aid = self._adapters.resolve_and_pin(adapter)
            except KeyError as e:
                raise SeldonError(str(e.args[0]), status_code=400)
        now = time.perf_counter()
        fut: asyncio.Future = self._loop.create_future()
        req = PendingRequest(
            ids=ids, max_new=int(max_new_tokens or self.server.max_new_tokens),
            fut=fut, on_token=on_token, info=info, seed=seed,
            t_arrival=now, trace=trace, tenant=str(tenant or ""),
            slo_class=cls, adapter_id=aid,
            deadline_t=((now + float(deadline_s))
                        if deadline_s is not None else None),
            resume_tokens=int(resume_tokens or 0))
        if not self._pending.push(req):
            # tenant over its queued-request quota: shed NOW with the
            # backlog-derived Retry-After (the scheduler counted it
            # against the tenant — seldon_tenant_shed_total)
            if aid:
                self._adapters.unpin(aid)
            from seldon_core_tpu.runtime.resilience import ShedError

            raise ShedError(
                f"tenant {req.tenant!r} over its admission quota",
                retry_after_s=self.retry_after_hint())
        self._ensure_running()
        self._wakeup.set()
        return await fut

    def accommodates(self, prompt: Any,
                     max_new_tokens: Optional[int] = None) -> bool:
        """True when this batcher decodes the request IDENTICALLY to a
        private ``generate()`` call: the prompt fits the fixed slot cache
        at the same bucketed length generate() would use (no extra
        truncation) and the token budget fits behind it (no clipping).
        Transports use this to keep the seeded-reproducibility contract —
        a seeded request that does NOT fit falls back to generate(), whose
        cache is sized per request."""
        if isinstance(prompt, str):
            n = len(self.server._tokenizer.encode(prompt))
        else:
            n = int(np.asarray(prompt).size)
        # _admit's exact prompt cap: beyond it the batcher keeps the tail
        # (generate() only truncates past the model context, which is
        # covered by the same min) — and the slot cache must leave the
        # whole token budget behind the prompt (the batcher stops at the
        # cache edge; generate() never clips)
        plen = min(_bucket(n, self.len_buckets), self.server._cfg.max_seq_len,
                   self.max_len - 1)
        max_new = int(max_new_tokens or self.server.max_new_tokens)
        return n <= plen and max_new <= self.max_len - n

    def _ensure_running(self):
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._run())

    def _resolve(self, fut: asyncio.Future, result=None, exc: Optional[BaseException] = None):
        """Thread-safe future completion: _finish runs inside asyncio.to_thread,
        and Future.set_result must happen on the loop thread."""

        def do():
            if fut.done():
                return
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)

        self._loop.call_soon_threadsafe(do)

    async def close(self):
        self._closed = True
        self._wakeup.set()
        if self._task is not None:
            await self._task
        if self._remote is not None:
            # bounded worker joins (runtime/disagg.py close uses timeouts);
            # workers first — their last frames must land before the
            # receiver's listener goes away
            await asyncio.to_thread(self._remote.close)
        if self._receiver is not None:
            await asyncio.to_thread(self._receiver.close)

    # ------------------------------------------------------------------
    def _truncate_prompt(self, ids: List[int], max_new: int,
                         info: Optional[dict]):
        """Shared admission clipping: same truncation rule as
        LLMServer.generate — never beyond the model's trained context, and
        leave room for at least one generated token. Returns
        (clipped ids, plen bucket)."""
        plen = min(
            _bucket(len(ids), self.len_buckets),
            self.server._cfg.max_seq_len,
            self.max_len - 1,
        )
        if len(ids) > plen:
            # same tail-keeping rule as before, but observable: batched and
            # unbatched serving can differ here (generate() sizes its cache
            # per request; the batcher's slot cache is fixed at max_len).
            # The info record travels back to the CLIENT as a response meta
            # tag / field — truncation changes outputs, so a server-side log
            # alone is not enough (ADVICE.md round 5)
            if info is not None:
                info["truncated_prompt"] = {
                    "prompt_tokens": len(ids),
                    "kept_tokens": plen,
                    "max_len": self.max_len,
                }
            logger.warning(
                "batcher truncating %d-token prompt to its last %d tokens "
                "(slot cache max_len=%d; raise continuous_batching_max_len "
                "to match generate())", len(ids), plen, self.max_len)
        if max_new > self.max_len - plen:
            logger.warning(
                "batcher will stop at %d new tokens (requested %d): slot "
                "cache max_len=%d minus prompt %d",
                self.max_len - plen, max_new, self.max_len, plen)
        return ids[-plen:], plen

    def _sample_first(self, first_logits: np.ndarray, seed: Optional[int],
                      resume_tokens: int = 0):
        """Host-side first-token draw from the prefill logits, on exactly
        generate()'s rng chain (PRNGKey -> split for the first token ->
        split per decode step). Returns (token, per-slot device key).

        ``resume_tokens`` > 0 means this admission RESUMES a generation
        interrupted after that many delivered tokens (fleet recovery,
        docs/resilience.md): the prompt already carries the generated
        prefix and the token drawn here is token ``resume_tokens`` of the
        ORIGINAL chain — which the device sampler would have produced. The
        chain consumes exactly one first-component split per emitted token
        (host first draw and every device step alike), so fast-forwarding
        PRNGKey(seed) by ``resume_tokens`` splits and then drawing with the
        DEVICE sampler's op order (split -> lax.top_k descending ->
        categorical -> gather) reproduces it bit-exactly. The host path's
        argsort ordering must NOT be used here: categorical over a
        differently-ordered top-k draws a different index for the same
        key."""
        import jax
        import jax.numpy as jnp

        # Per-request rng: an explicit seed reproduces generate(seed=...)'s
        # exact chain; otherwise derive an independent key from the batcher
        # rng so concurrent requests don't share a stream.
        if seed is not None:
            key = jax.random.PRNGKey(int(seed))
        else:
            self._rng, key = jax.random.split(self._rng)
        if float(self._temp) <= 0.0:
            # greedy is key-independent (the device sampler selects argmax
            # through jnp.where regardless of the key), so resume needs no
            # fast-forward: argmax over the re-prefilled logits IS token N
            first = int(first_logits.argmax())
        elif resume_tokens > 0 and seed is not None:
            from seldon_core_tpu.servers.llmserver import fast_forward_key

            key = fast_forward_key(seed, resume_tokens)
            key, sub = jax.random.split(key)
            k = min(self.server.top_k, first_logits.shape[-1])
            topv, topi = jax.lax.top_k(jnp.asarray(first_logits), k)
            draw = jax.random.categorical(
                sub, topv / max(float(self._temp), 1e-6))
            # graftlint: allow-host-sync-in-hot-path(single admission-time sync of the resumed token, once per recovery; the device sampler's exact op order is required for bit-exact continuation)
            first = int(np.asarray(topi[draw]))
        else:
            key, sub = jax.random.split(key)
            k = min(self.server.top_k, first_logits.shape[-1])
            topi = np.argsort(first_logits)[-k:]
            # graftlint: allow-host-sync-in-hot-path(admission-time sample of the prefill token, once per request; generate()'s exact rng chain requires drawing it here)
            draw = int(np.asarray(jax.random.categorical(
                sub, jnp.asarray(first_logits[topi]) / max(float(self._temp), 1e-6))))
            first = int(topi[draw])
        return first, key

    def _commit_slot(self, i: int, first: int, key, L: int, max_new: int,
                     fut: asyncio.Future, on_token: Optional[Any],
                     ids: Optional[List[int]] = None,
                     t_arrival: Optional[float] = None,
                     req: Optional[Any] = None):
        """Slot bookkeeping shared by dense admission and paged activation:
        thread the new occupant's state into the device arrays and surface
        the first token. Program order on the device stream puts the
        set_slot after every already-dispatched step, so in-flight steps
        still see (and waste compute on) the old state while step N+1 picks
        up the new occupant. ``ids`` (the truncated prompt) seeds the
        speculative token history and the draft-model cache when
        speculation is on."""
        import time

        import jax.numpy as jnp

        slot = self._slots[i]
        slot.active = True
        slot.prefilling = False
        slot.future = fut
        slot.true_len = L
        slot.max_new = max_new
        slot.n_new = 1
        slot.tokens = [first]
        slot.on_token = on_token
        # multi-tenant identity rides the slot for the whole occupancy:
        # tenant token/shed accounting, per-class TTFT, and the adapter
        # row every adapted dispatch gathers for this slot
        slot.tenant = req.tenant if req is not None else ""
        slot.slo_class = req.slo_class if req is not None else "interactive"
        slot.adapter_id = req.adapter_id if req is not None else 0
        if self._adapters is not None:
            self._adapter_ids = self._set_adapter_id(
                self._adapter_ids, jnp.asarray(i, jnp.int32),
                jnp.asarray(slot.adapter_id, jnp.int32))
        # the truncated prompt feeds the radix trie's completion-time
        # insertion (prompt + generated blocks re-enter the cache)
        slot.ids = list(ids) if ids is not None else None
        # first token surfaced NOW: time-to-first-token from submit(), and
        # the baseline the next token's gap measures from
        now = time.perf_counter()
        if t_arrival is not None:
            self.server._ttft_times.append(now - t_arrival)
            self.server._ttft_by_class.append(
                (slot.slo_class, now - t_arrival))
        self._pending.count_tokens(slot.tenant, slot.slo_class, 1)
        slot.t_last = now
        if self._flight is not None:
            if req is not None and getattr(req, "resume_tokens", 0):
                # fleet recovery: this admission continues an interrupted
                # generation — mark the timeline so the span tree shows
                # where the failover re-attached (docs/resilience.md)
                self._flight.record(i, EV_RESUME,
                                    tokens=int(req.resume_tokens))
            self._flight.record(i, EV_FIRST_TOKEN, tokens=1)
        slot.gen += 1          # invalidates in-flight tokens for the old occupant
        slot.disp_new = 1      # the prefill-sampled first token counts
        self._admit_seq += 1
        slot.admit_seq = self._admit_seq
        self._last_tok, self._next_pos, self._keys = self._set_slot(
            self._last_tok, self._next_pos, self._keys,
            jnp.asarray(i, jnp.int32), jnp.asarray(first, jnp.int32),
            jnp.asarray(L, jnp.int32), key)
        if self.spec_mode != "off" and ids is not None:
            # Seed the slot's device-resident token history: prompt at
            # positions 0..L-1, the prefill-sampled first token at L
            # (L <= max_len - 1 — _truncate_prompt leaves decode room).
            # Overwriting the WHOLE row retires the previous occupant's
            # tokens, exactly like the dense cache insert.
            row = np.zeros((self.hist_len,), np.int32)
            row[:L] = ids
            row[L] = first
            self._hist = self._set_hist_row(
                self._hist, jnp.asarray(i, jnp.int32), jnp.asarray(row))
            self._spec.reset(i)
            if self.spec_mode == "draft":
                self._draft_prefill_slot(i, ids)
        self._last_admit_inflight = len(self._inflight)
        if on_token is not None and first != self.eos_id:
            on_token(first)
        if first == self.eos_id or max_new <= 1:
            self._finish(i)

    def _draft_prefill_slot(self, i: int, ids: List[int]):
        """spec_mode='draft': prefill the slot's DENSE draft-model cache
        over the (already truncated) prompt and insert it whole — the
        fresh cache covers all max_len positions, so the previous
        occupant's rows are retired exactly like the dense target insert.
        The draft's logits are discarded: drafting always restarts from
        the last accepted TARGET token inside the verify step."""
        import jax.numpy as jnp

        L = len(ids)
        plen = min(_bucket(L, self.len_buckets),
                   self.server._cfg.max_seq_len, self.max_len - 1)
        toks = np.zeros((1, plen), np.int32)
        pos = np.full((1, plen), PAD_POS, np.int32)
        toks[0, :L] = ids
        pos[0, :L] = np.arange(L)
        fn = self.server._get_draft_prefill(1, plen, self.max_len)
        _, dcache = fn(self.server._draft_params, jnp.asarray(toks),
                       jnp.asarray(pos))
        self._draft_caches = self._draft_insert(
            self._draft_caches, dcache, jnp.asarray(i, jnp.int32))

    def _admit(self, req) -> bool:
        """Dense-layout admission: one-shot prefill into a 1-sequence cache,
        jitted insert into the free slot. ``req`` is the scheduler's
        PendingRequest (tenant/SLO/adapter identity rides it)."""
        import time

        import jax.numpy as jnp

        free = next((i for i, s in enumerate(self._slots) if not s.active), None)
        if free is None:
            return False
        ids, plen = self._truncate_prompt(req.ids, req.max_new, req.info)
        L = len(ids)
        if self._flight is not None:
            self._flight.begin(free, req.trace, req.t_arrival, L,
                               tags=self._flight_tags(req))
        tokens = np.zeros((1, plen), np.int32)
        positions = np.full((1, plen), PAD_POS, np.int32)
        tokens[0, :L] = ids
        positions[0, :L] = np.arange(L)

        t0 = time.perf_counter()
        if self._adapters is not None:
            prefill = self.server._get_prefill(1, plen, self.max_len,
                                               lora=True)
            logits, cache1 = prefill(
                self.server._params, jnp.asarray(tokens),
                jnp.asarray(positions), self._adapters.pool(),
                jnp.asarray([req.adapter_id], jnp.int32))
        else:
            prefill = self.server._get_prefill(1, plen, self.max_len)
            logits, cache1 = prefill(self.server._params, jnp.asarray(tokens),
                                     jnp.asarray(positions))
        self._caches = self._insert(self._caches, cache1, free)
        # graftlint: allow-host-sync-in-hot-path(admission-time sync, once per request not per token: the first sampled token must reach the host to seed slot bookkeeping before the slot joins the pipelined batch)
        first_logits = np.asarray(logits[0, L - 1]).astype(np.float32)
        if self._flight is not None:
            self._flight.record(free, EV_PREFILL, tokens=L,
                                dur_s=time.perf_counter() - t0)
        first, key = self._sample_first(first_logits, req.seed,
                                        req.resume_tokens)
        self._commit_slot(free, first, key, L, req.max_new, req.fut,
                          req.on_token, ids=ids, t_arrival=req.t_arrival,
                          req=req)
        return True

    @staticmethod
    def _flight_tags(req) -> Optional[dict]:
        """Tenant identity on the request's flight-recorder timeline/root
        span (None when untenanted — the timeline stays byte-identical to
        the single-tenant layout)."""
        if not req.tenant and req.slo_class == "interactive" \
                and not req.adapter_id:
            return None
        return {"tenant": req.tenant, "slo_class": req.slo_class,
                "adapter_id": req.adapter_id}

    # ------------------------------------------------------------------
    # Disaggregated admission: stage remote jobs, consume handoffs
    # ------------------------------------------------------------------
    def _admit_remote(self, req) -> bool:
        """Remote-prefill admission, decode-side half: reserve a slot,
        consult the radix trie so the prefill slice only computes the
        UNCACHED suffix (matched whole blocks stay decode-side, shared
        into the slot's row; their KV ships forward to the worker as one
        exported page bucket so its suffix chunks can attend over them),
        allocate the suffix pages the import will land in, and stage the
        job. Returns True when the request was CONSUMED (staged or shed)
        — False leaves it pending. No prefill compute happens here: that
        is the point."""
        import jax.numpy as jnp

        free = next((i for i, s in enumerate(self._slots)
                     if not s.active and not s.prefilling), None)
        if free is None:
            return False
        ids, plen = self._truncate_prompt(req.ids, req.max_new, req.info)
        L = len(ids)
        pages: List[int] = []
        shared: List[int] = []
        row = None
        prefix_staged = None
        k0 = 0
        n0 = 0
        if self.paged:
            n0 = -(-L // self.page_size)
            if self._radix is not None:
                # whole blocks only: the worker's suffix prefill starts at
                # a page boundary and partial-block COW stays a local
                # (decode-side) move — capped at L-1 so the worker always
                # computes the first-token logits
                # leaklint: allow-leak-on-path(full_blocks_only=True guarantees cow is None — no cow pin is ever taken, so the discarded third element holds nothing)
                k0, shared, _ = self._radix.match_and_pin(
                    ids, limit=L - 1, full_blocks_only=True)
            got = self._alloc_pages(n0 - len(shared))
            if got is None:
                if shared:
                    self._allocator.free(shared)  # drop pins: retry later
                # same liveness posture as _admit_begin: with no tenant in
                # flight anywhere (active, local prefill, or staged remote
                # — remote slots hold prefilling=True), nothing will ever
                # free a page, so shed now instead of queueing forever
                if not any(s.active or s.prefilling for s in self._slots):
                    self._shed_queued_request(
                        req,
                        f"admission needs {n0} KV pages "
                        f"(pool capacity {self._allocator.capacity}, "
                        f"{self._allocator.stats()[1]} in use)")
                    return True
                return False
            pages = got
            row = np.full((self.n_pages,), NULL_PAGE, np.int32)
            row[:n0] = shared + pages
            if shared:
                # export the matched blocks as a power-of-two page bucket
                # (handoff-shaped: RESERVED leading rows, then pages) the
                # worker imports into its staging pool — D2D forward
                # shipment of already-computed KV, never a recompute
                b = pow2_bucket(len(shared), self.n_pages)
                idx = np.full((RESERVED_PAGES + b,), TRASH_PAGE, np.int32)
                idx[RESERVED_PAGES:RESERVED_PAGES + len(shared)] = shared
                prefix_staged = self._export_pages(self._caches,
                                                   jnp.asarray(idx))
        from seldon_core_tpu.runtime.disagg import PrefillRequest

        slot = self._slots[free]
        slot.pages = list(pages)
        slot.shared = list(shared)
        slot.prefilling = True
        slot.future = req.fut
        slot.on_token = req.on_token
        slot.tenant = req.tenant
        slot.slo_class = req.slo_class
        self._job_seq += 1
        job = _RemoteJob(self._job_seq, free, ids, plen, req.max_new,
                         req.fut, req.on_token, req.info, req.seed, pages,
                         row, req.t_arrival, prefix_pages=len(shared),
                         req=req)
        self._remote_jobs[job.job_id] = job
        if k0:
            # once per funded admission, like the local path
            self._radix.record_hit(k0, len(shared), False)
        if self._flight is not None:
            self._flight.begin(free, req.trace, req.t_arrival, L,
                               tags=self._flight_tags(req))
            if k0:
                self._flight.record(free, EV_PREFIX_HIT, tokens=k0,
                                    blocks=len(shared))
            self._flight.record(free, EV_HANDOFF_STAGED, job_id=job.job_id,
                                pages=n0 - len(shared))
        req = PrefillRequest(job.job_id, ids, plen, n0,
                             record_events=self._flight is not None,
                             prefix_len=k0,
                             prefix_pages=len(shared),
                             prefix_staged=prefix_staged)
        pool = self._remote
        try:
            pool.submit(req)
        except RuntimeError:
            # a rebalance swapped the worker pool between our read of
            # self._remote and the submit: the old pool is closing (its
            # backlog drains into the SHARED TransferQueue, so nothing
            # already staged is lost) — retry once on the new pool, which
            # publishes into the same queue
            self._remote.submit(req)
        return True

    def _consume_handoffs(self):
        """Drain every READY handoff: import the staged KV into the slot
        pool (one donated jitted scatter through the slot's block row;
        dense handoffs reuse the insert), then commit the slot exactly as
        a local admission would — same first-token sampling chain, so
        tokens are bit-identical to single-slice serving."""
        import time

        import jax.numpy as jnp

        while True:
            h = self._transfer.pop()
            if h is None:
                return
            job = self._remote_jobs.pop(h.job_id, None)
            if job is None:
                continue  # defensive: cancel removes READY records itself
            if h.error is not None:
                # worker-side failure: fail THIS request, release its slot
                # and pages — the batch keeps serving (release before
                # notifying, like _finish)
                if self._flight is not None:
                    self._flight.complete(job.slot, "error", 0, self._tracer)
                self._release_slot(job.slot)
                if job.on_token is not None:
                    try:
                        job.on_token(None)
                    except Exception:
                        pass
                self._resolve(job.fut, exc=h.error)
                continue
            if self._flight is not None and h.events:
                # worker-stamped stages (compute, D2D transfer) recorded on
                # the prefill thread BEFORE the handoff was published —
                # ownership moved through the TransferQueue's lock
                self._flight.extend(job.slot, h.events)
            try:
                t0 = time.perf_counter()
                if self.paged:
                    import jax

                    n0 = -(-job.L // self.page_size)
                    # only the SUFFIX pages travelled (the prefix blocks
                    # never left this device — they are shared trie pages
                    # already in the row's lead); import targets row
                    # entries past them
                    n_suffix = n0 - job.prefix_pages
                    # the worker shipped a power-of-two page bucket; the
                    # buffer's own shape names the compile to import it
                    staged_pages = (jax.tree.leaves(h.staged)[0].shape[0]
                                    - RESERVED_PAGES)
                    imp = self._get_handoff_import(staged_pages)
                    row_suffix = np.full((self.n_pages,), NULL_PAGE,
                                         np.int32)
                    row_suffix[:n_suffix] = job.row[
                        job.prefix_pages:job.prefix_pages + n_suffix]
                    self._caches = imp(self._caches, h.staged,
                                       jnp.asarray(row_suffix),
                                       jnp.asarray(n_suffix, jnp.int32))
                    self._block_tables = self._set_block_row(
                        self._block_tables,
                        jnp.asarray(job.slot, jnp.int32),
                        jnp.asarray(job.row))
                else:
                    self._caches = self._insert(self._caches, h.staged,
                                                job.slot)
            except Exception as e:
                # poisoned handoff (malformed staged payload, import
                # raising): fail THIS request and free its slot + staging
                # pages — exactly the h.error semantics above. Letting it
                # propagate would kill the whole consume sweep and, one
                # frame up, the batcher loop itself — one bad handoff
                # must never take down the batch (ISSUE 16 satellite).
                logger.exception("poisoned handoff (slot %d): %s",
                                 job.slot, e)
                if self._flight is not None:
                    self._flight.complete(job.slot, "error", 0,
                                          self._tracer)
                self._release_slot(job.slot)
                if job.on_token is not None:
                    try:
                        job.on_token(None)
                    except Exception:
                        pass
                self._resolve(job.fut, exc=e)
                continue
            self.server._handoff_times.append(
                h.prefill_s + (time.perf_counter() - t0))
            if self._flight is not None:
                self._flight.record(job.slot, EV_HANDOFF_IMPORT,
                                    bytes=h.transfer_bytes,
                                    dur_s=time.perf_counter() - t0)
            first, key = self._sample_first(
                h.first_logits, job.seed,
                job.req.resume_tokens if job.req is not None else 0)
            self._commit_slot(job.slot, first, key, job.L, job.max_new,
                              job.fut, job.on_token, ids=job.ids,
                              t_arrival=job.t_arrival, req=job.req)

    def _shed_remote_job(self, job_id: int, why: str):
        """Shed a staged remote admission (page pressure / shutdown): the
        TransferQueue's cancel makes the outcome exactly-once — either we
        take the READY handoff out of the queue (its payload drops with
        it) or the worker's later put is refused; in BOTH cases this
        path, and only this path, frees the decode-side pages (via the
        slot release)."""
        job = self._remote_jobs.pop(job_id, None)
        if job is None:
            return
        self._transfer.cancel(job_id)
        if self.paged:
            self._allocator.count_shed()
        if job.req is not None:
            self._pending.count_shed(job.req.tenant, job.req.slo_class)
            # adapters reject disaggregation at load() today, so this is
            # a no-op — kept so the pin-ownership rule (queue entry owns
            # it until _commit_slot) survives that restriction lifting
            self._unpin_request(job.req)
        logger.warning("shedding staged remote prefill (slot %d): %s",
                       job.slot, why)
        if self._flight is not None:
            self._flight.record(job.slot, EV_SHED, why=why)
            self._flight.complete(job.slot, "shed", 0, self._tracer)
        self._release_slot(job.slot)  # before notifying, like _finish
        if job.on_token is not None:
            try:
                job.on_token(None)
            except Exception:
                pass
        self._resolve(job.fut, exc=self._shed_error(why))

    def _fail_remote_jobs(self, exc: BaseException):
        """Shutdown/crash path: no staged request may leave its future
        hanging."""
        for job_id in list(self._remote_jobs):
            job = self._remote_jobs.pop(job_id)
            self._transfer.cancel(job_id)
            if self._flight is not None:
                self._flight.complete(job.slot, "error", 0, self._tracer)
            self._release_slot(job.slot)  # before notifying, like _finish
            if job.on_token is not None:
                try:
                    job.on_token(None)
                except Exception:
                    pass
            self._resolve(job.fut, exc=exc)

    # ------------------------------------------------------------------
    # Paged admission: page allocation + chunked prefill + activation
    # ------------------------------------------------------------------
    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """Pool allocation with radix-eviction relief: when the free list
        can't cover ``n``, ask the trie to evict LRU leaf blocks nothing
        references (refcount 1) before giving up — cached prefixes are a
        cache, live slots are the tenants, and the cache yields first.
        Shedding only starts where eviction ends."""
        got = self._allocator.alloc(n)
        if got is not None or self._radix is None:
            return got
        if not self._radix.evict(n):
            return None
        return self._allocator.alloc(n)

    def _admit_begin(self, req) -> bool:
        """Paged admission, phase 1 (host-side, cheap): match the prompt
        against the radix prefix cache (shared full blocks enter the block
        row as-is — zero copies; a partial-block continuation pays one
        copy-on-write page copy), allocate fresh pages for the uncached
        suffix, reset their stale positions, and stage a chunked-prefill
        job covering ONLY the suffix. The match is capped at L-1 tokens so
        the last prompt token always prefills — its logits seed the first
        sampled token on generate()'s exact rng chain (the trie stores
        pages, never logits). Returns True when the request was CONSUMED
        (job staged or shed with 503) — False leaves it pending for a
        later loop turn."""
        import jax.numpy as jnp

        free = next((i for i, s in enumerate(self._slots)
                     if not s.active and not s.prefilling), None)
        if free is None:
            return False
        ids, plen = self._truncate_prompt(req.ids, req.max_new, req.info)
        L = len(ids)
        n0 = -(-L // self.page_size)
        k0, shared, cow = 0, [], None
        if self._radix is not None and req.adapter_id == 0:
            # radix reuse serves BASE-adapter traffic only: an adapted
            # request's hidden states embed its q/o/FFN deltas from layer
            # 1 on, so its deep-layer KV is not the trie's KV (the k/v
            # PROJECTIONS are base for everyone — runtime/adapters.py —
            # but projection inputs differ). Adapted admissions prefill
            # their whole prompt and never insert (docs/multitenancy.md).
            k0, shared, cow = self._radix.match_and_pin(ids, limit=L - 1)
        n_fresh = n0 - len(shared) - (1 if cow is not None else 0)
        fresh = self._alloc_pages(n_fresh + (1 if cow is not None else 0))
        if fresh is None and cow is not None:
            # the cow pin itself can be what starves the pool: its source
            # page is refcount-2 (unevictable) while pinned, so on a
            # minimum-size pool the eviction pass may be exactly one page
            # short. A partial-block match is an OPTIMIZATION, never a
            # requirement — drop it (treat the tail as a miss, keeping
            # the full-block shares) and retry before parking/shedding,
            # preserving the invariant that an admission always fits an
            # otherwise-idle pool.
            self._allocator.free([cow[0]])
            k0 -= cow[1]
            cow = None
            fresh = self._alloc_pages(n0 - len(shared))
        if fresh is None:
            if shared:
                self._allocator.free(shared)  # drop the pins: retry later
            # Liveness rests entirely on this busy check: _truncate_prompt
            # caps prompts at max_len-1 so n0 <= n_pages, and the
            # constructor rejects pools with capacity < n_pages — an
            # admission can always fit an empty pool (the radix trie
            # yields its unreferenced blocks first, via _alloc_pages). So
            # if nothing is in flight to ever free a page, shed now
            # instead of queueing forever; otherwise wait for in-flight
            # completions.
            if not any(s.active or s.prefilling for s in self._slots):
                self._shed_queued_request(
                    req,
                    f"admission needs {n0} KV pages "
                    f"(pool capacity {self._allocator.capacity}, "
                    f"{self._allocator.stats()[1]} in use)")
                return True
            return False  # wait: in-flight completions will free pages
        cow_dst = fresh[0] if cow is not None else None
        plain = fresh[1:] if cow is not None else fresh
        slot = self._slots[free]
        slot.shared = list(shared)
        slot.pages = ([cow_dst] if cow_dst is not None else []) + plain
        slot.prefilling = True
        slot.future = req.fut
        slot.on_token = req.on_token
        slot.tenant = req.tenant
        slot.slo_class = req.slo_class
        if self._flight is not None:
            self._flight.begin(free, req.trace, req.t_arrival, L,
                               tags=self._flight_tags(req))
        # neutralize the FRESH pages' previous-owner positions BEFORE any
        # write lands through them (stale real positions would make this
        # slot's mask attend another sequence's leftover KV). Shared trie
        # pages are live cached KV — never reset; the cow destination is
        # fully overwritten (values + masked position row) by the copy.
        if plain:
            ids_np = np.full((self.n_pages,), TRASH_PAGE, np.int32)
            ids_np[:len(plain)] = plain
            self._caches = self._reset_pages(self._caches,
                                             jnp.asarray(ids_np))
        if cow is not None:
            # one donated jitted page copy: the shared page's valid prefix
            # moves into this slot's own page, stale positions masked —
            # the ONLY copy a radix hit can cost (full blocks share). The
            # source was PINNED by match_and_pin (the _alloc_pages above
            # may have evicted its leaf; unpinned it could have been
            # handed back as one of OUR fresh pages) — drop the pin now
            # that the copy is in device program order before any reuse.
            self._caches = self._cow_page_copy(
                self._caches, jnp.asarray(cow[0], jnp.int32),
                jnp.asarray(cow_dst, jnp.int32),
                jnp.asarray(cow[1], jnp.int32))
            self._allocator.free([cow[0]])
        row = np.full((self.n_pages,), NULL_PAGE, np.int32)
        row[:n0] = slot.shared + slot.pages
        bt_row = jnp.asarray(row[None, :])
        if k0:
            # counted HERE, once per funded admission — a match that
            # failed allocation above retries every loop turn and must
            # not inflate the reuse counters per retry
            self._radix.record_hit(k0, len(shared), cow is not None)
            if self._flight is not None:
                self._flight.record(free, EV_PREFIX_HIT, tokens=k0,
                                    blocks=len(shared) +
                                    (1 if cow is not None else 0))
        job = _PrefillJob(free, ids, k0, min(self.prefill_chunk, plen),
                          req.max_new, req.fut, req.on_token, req.info,
                          req.seed, bt_row, slot.pages,
                          t_arrival=req.t_arrival, req=req)
        self._prefill = job
        return True

    def _prefill_step(self):
        """One chunked-prefill dispatch (worker thread): write the next
        ``chunk`` prompt tokens into the pool through the job's block-table
        row. Only the LAST chunk syncs (the first-token logits must reach
        the host) — intermediate chunks are enqueue-only, so decode steps
        interleave between them and in-flight requests keep streaming."""
        import jax.numpy as jnp

        job = self._prefill
        if job is None:
            return
        import time

        C = job.chunk
        start = job.next
        part = job.ids[start:start + C]
        n = len(part)
        toks = np.zeros((1, C), np.int32)
        pos = np.full((1, C), PAD_POS, np.int32)
        toks[0, :n] = part
        pos[0, :n] = np.arange(start, start + n)
        t0 = time.perf_counter()
        if self._adapters is not None:
            fn = self.server._get_prefill_chunk(C, self.n_pages, lora=True)
            aid = job.req.adapter_id if job.req is not None else 0
            logits, self._caches = fn(
                self.server._params, self._caches, job.bt_row,
                jnp.asarray(toks), jnp.asarray(pos), self._adapters.pool(),
                jnp.asarray([aid], jnp.int32))
        else:
            fn = self.server._get_prefill_chunk(C, self.n_pages)
            logits, self._caches = fn(self.server._params, self._caches,
                                      job.bt_row, jnp.asarray(toks),
                                      jnp.asarray(pos))
        job.next = start + n
        if self._flight is not None:
            # dispatch wall (enqueue-only); the last chunk's logits sync
            # below lands in the gap before the first_token event
            self._flight.record(job.slot, EV_PREFILL_CHUNK, start=start,
                                tokens=n, dur_s=time.perf_counter() - t0)
        if job.next >= job.L:
            # graftlint: allow-host-sync-in-hot-path(admission-time sync, once per request not per chunk: the LAST chunk's logits seed the first sampled token; earlier chunks were enqueue-only)
            first_logits = np.asarray(logits[0, n - 1]).astype(np.float32)
            self._activate(job, first_logits)

    def _activate(self, job: _PrefillJob, first_logits: np.ndarray):
        """Paged admission, final phase: sample the first token on
        generate()'s rng chain, point the slot's DEVICE block-table row at
        the real pages (decode writes route through it from the next
        dispatch; in-flight steps still see the trash row in program
        order), and commit the slot into the decode batch."""
        import jax.numpy as jnp

        first, key = self._sample_first(
            first_logits, job.seed,
            job.req.resume_tokens if job.req is not None else 0)
        self._block_tables = self._set_block_row(
            self._block_tables, jnp.asarray(job.slot, jnp.int32),
            job.bt_row[0])
        self._prefill = None
        self._commit_slot(job.slot, first, key, job.L, job.max_new, job.fut,
                          job.on_token, ids=job.ids, t_arrival=job.t_arrival,
                          req=job.req)

    # ------------------------------------------------------------------
    # Page accounting: growth, exhaustion shedding, release
    # ------------------------------------------------------------------
    def _ensure_slot_pages(self, i: int, last_write_pos: int) -> bool:
        """Grow slot ``i``'s page list to cover decode writes up to
        ``last_write_pos`` BEFORE the step that writes them is dispatched
        (a write through an unallocated table entry is redirected to trash
        device-side — safe, but the token's KV would be lost). On pool
        exhaustion the newest other request sheds (503 + Retry-After) to
        free pages; if this slot is the only tenant left, its generation
        ends early with the tokens it has — the decode loop itself NEVER
        raises. Returns False when the slot was finished/released."""
        import jax.numpy as jnp

        import time

        slot = self._slots[i]
        if not slot.active:
            # released slots own no pages (release freed them) — growing
            # one would allocate pool pages that nothing ever frees
            return False
        need = min(last_write_pos, self.max_len - 1) // self.page_size + 1
        n0_pages = slot.covered_pages()
        t0_grow = time.perf_counter() if n0_pages < need else 0.0
        while slot.covered_pages() < need:
            got = self._alloc_pages(1)
            if got is None:
                victim = self._pick_page_victim()
                if victim is None:
                    # sole tenant outgrew the pool: stop generating with the
                    # tokens it has — the same cache-edge truncation posture
                    # as the dense layout's max_len stop, never an error
                    logger.warning(
                        "kv page pool exhausted with no shed candidate: "
                        "slot %d ends at %d generated tokens", i, slot.n_new)
                    self._finish(i)
                    return False
                if victim == "job":
                    self._shed_prefill_job("page pool exhausted by decode")
                    continue
                if isinstance(victim, tuple):  # ("remote", job_id)
                    self._shed_remote_job(victim[1],
                                          "page pool exhausted by decode")
                    continue
                if victim == i:
                    # the growing slot is itself the newest tenant: LIFO
                    # says it yields to the older requests
                    self._shed_slot(i, "page pool exhausted")
                    return False
                self._shed_slot(victim, "page pool exhausted")
                continue
            page = got[0]
            ids_np = np.full((self.n_pages,), TRASH_PAGE, np.int32)
            ids_np[0] = page
            self._caches = self._reset_pages(self._caches, jnp.asarray(ids_np))
            self._block_tables = self._set_block_entry(
                self._block_tables, jnp.asarray(i, jnp.int32),
                jnp.asarray(slot.covered_pages(), jnp.int32),
                jnp.asarray(page, jnp.int32))
            slot.pages.append(page)
        if self._flight is not None and slot.covered_pages() > n0_pages:
            # mid-decode page growth is the paged layout's stall risk: the
            # allocation (and any shed it forced) ran between this slot's
            # dispatches — the timeline shows it where the gap opened
            self._flight.record(i, EV_PAGE_GROW,
                                pages=slot.covered_pages() - n0_pages,
                                dur_s=time.perf_counter() - t0_grow)
        return True

    def _pick_page_victim(self):
        """LIFO shed order on page exhaustion: the globally NEWEST tenant
        yields — the staged prefill job first (it has produced nothing
        yet), then the newest staged REMOTE job (same reasoning: its
        prefill compute is sunk on the other slice, but no client has a
        token yet), then the most recently admitted active slot, which may
        be the growing slot itself. None when there is at most one tenant
        (shed nothing — the sole request just stops growing)."""
        if self._prefill is not None:
            return "job"
        if self._remote_jobs:
            # dict preserves insertion order: the last key is the newest
            return ("remote", next(reversed(self._remote_jobs)))
        active = [j for j, s in enumerate(self._slots) if s.active and s.pages]
        if len(active) < 2:
            return None
        return max(active, key=lambda j: self._slots[j].admit_seq)

    def _shed_error(self, why: str):
        from seldon_core_tpu.runtime.resilience import ShedError

        # Retry-After derived from the live backlog (retry_after_hint),
        # not the fixed constant: during the exact spikes that cause
        # sheds, a constant backoff stampedes every shed client back at
        # once while the queue is still draining.
        return ShedError(f"kv page pool exhausted: {why}",
                         retry_after_s=self.retry_after_hint())

    def _shed_request(self, fut: asyncio.Future, on_token: Optional[Any],
                      why: str):
        """Shed a not-yet-admitted request (503 + Retry-After)."""
        self._allocator.count_shed()
        logger.warning("shedding admission: %s", why)
        if on_token is not None:
            try:
                on_token(None)
            except Exception:
                pass
        self._resolve(fut, exc=self._shed_error(why))

    def _unpin_request(self, req):
        """Drop a queued/staged request's adapter pin. Ownership lives on
        the queue entry from submit() until _commit_slot moves it to the
        slot, so every TERMINAL pre-commit path (queued shed, staged
        local/remote shed, crash drain) funnels here; the id zeroes so a
        path that fires twice cannot double-unpin."""
        if self._adapters is not None and req.adapter_id:
            self._adapters.unpin(req.adapter_id)
            req.adapter_id = 0

    def _shed_queued_request(self, req, why: str):
        """Shed a request still sitting in the scheduler: remove it there
        (which books the shed against its tenant —
        seldon_tenant_shed_total), drop its adapter pin, then the common
        shed path."""
        self._pending.remove(req)
        self._unpin_request(req)
        self._shed_request(req.fut, req.on_token, why)

    def _shed_slot(self, i: int, why: str):
        """Shed an ACTIVE slot mid-decode to relieve page exhaustion: its
        tokens are discarded and the client gets 503 + Retry-After (the
        dense layout can never hit this — its slots pre-reserve max_len)."""
        slot = self._slots[i]
        self._allocator.count_shed()
        self._pending.count_shed(slot.tenant, slot.slo_class)
        logger.warning(
            "shedding slot %d after %d generated tokens: %s", i, slot.n_new, why)
        fut, on_token = slot.future, slot.on_token
        if self._flight is not None:
            self._flight.record(i, EV_SHED, why=why)
            self._flight.complete(i, "shed", slot.n_new, self._tracer)
        # release BEFORE notifying (same ordering as _finish): the shed
        # client's 503 handler must never observe its own pages as held
        self._release_slot(i)
        if on_token is not None:
            try:
                on_token(None)
            except Exception:
                pass
        if fut is not None:
            self._resolve(fut, exc=self._shed_error(why))

    def _shed_prefill_job(self, why: str):
        job = self._prefill
        if job is None:
            return
        self._prefill = None
        self._allocator.count_shed()
        if job.req is not None:
            self._pending.count_shed(job.req.tenant, job.req.slo_class)
            # pre-commit, the QUEUE ENTRY still owns the adapter pin
            # (slot.adapter_id is only set at _commit_slot, so the slot
            # release below cannot drop it) — this shed is the terminal
            # outcome, so the pin dies here
            self._unpin_request(job.req)
        logger.warning("shedding staged prefill (slot %d): %s", job.slot, why)
        if self._flight is not None:
            self._flight.record(job.slot, EV_SHED, why=why)
            self._flight.complete(job.slot, "shed", 0, self._tracer)
        self._release_slot(job.slot)  # before notifying, like _finish
        if job.on_token is not None:
            try:
                job.on_token(None)
            except Exception:
                pass
        self._resolve(job.fut, exc=self._shed_error(why))

    def _preempt_for_interactive(self) -> bool:
        """Deadline-aware slot reclamation (docs/multitenancy.md): an
        interactive admission blocked on occupied slots pushes ONE staged
        batch-class job back into the scheduler — the local chunked
        prefill first (its compute is sunk but no client has a token),
        else the newest staged remote admission. ACTIVE slots are never
        touched: a slot that has surfaced tokens finishes or sheds on its
        own terms. The preempted request keeps its sequence number
        (re-enters its tenant queue where it left) and is immune to a
        second preemption (``preempted`` flag) — that immunity is what
        makes a sustained interactive flood unable to livelock batch
        admissions: a re-staged job always completes. Returns True when
        something was preempted (the caller retries its admission)."""
        job = self._prefill
        if job is not None and job.req is not None \
                and job.req.slo_class == "batch" and not job.req.preempted:
            self._prefill = None
            return self._requeue_preempted(job.slot, job.req, "local prefill")
        for job_id in reversed(list(self._remote_jobs)):
            rjob = self._remote_jobs[job_id]
            if rjob.req is None or rjob.req.slo_class != "batch" \
                    or rjob.req.preempted:
                continue
            del self._remote_jobs[job_id]
            # exactly-once vs the worker: either the READY handoff leaves
            # the queue with its payload, or the worker's later put is
            # refused — same protocol as _shed_remote_job, different fate
            # for the REQUEST (requeued, not failed)
            self._transfer.cancel(job_id)
            return self._requeue_preempted(rjob.slot, rjob.req,
                                           "staged remote prefill")
        return False

    def _requeue_preempted(self, slot_i: int, req, what: str) -> bool:
        logger.info("preempting %s (slot %d, tenant %r) for an "
                    "interactive admission", what, slot_i, req.tenant)
        slot = self._slots[slot_i]
        # the queue entry keeps the adapter pin: ownership returns to it,
        # so the release below must not unpin (it unpins slot.adapter_id,
        # zeroed here first)
        slot.adapter_id = 0
        if self._flight is not None:
            self._flight.record(slot_i, EV_SHED, why="preempted: " + what)
            self._flight.complete(slot_i, "preempted", 0, self._tracer)
        self._release_slot(slot_i)
        self._pending.push(req, requeue=True)
        return True

    def _release_slot(self, i: int):
        """Common slot teardown: drop page references (owned pages free
        to the pool, shared trie pins decrement — the trie keeps its own
        reference) and point the device block-table row back at trash (in
        device program order, so in-flight steps finish their reads first
        — reused pages are reset/rewritten strictly AFTER)."""
        slot = self._slots[i]
        slot.active = False
        slot.prefilling = False
        slot.future = None
        slot.on_token = None
        slot.ids = None
        slot.tenant = ""
        slot.slo_class = "interactive"
        if self._adapters is not None and slot.adapter_id:
            # the slot's pin was the live reference holding this adapter
            # in the pool; eviction becomes legal once it drops. The
            # device id resets to identity so the released slot's
            # ride-along compute gathers row 0 (zeros), never a row a
            # later load may repopulate for someone else.
            self._adapters.unpin(slot.adapter_id)
            import jax.numpy as _jnp

            self._adapter_ids = self._set_adapter_id(
                self._adapter_ids, _jnp.asarray(i, _jnp.int32),
                _jnp.asarray(0, _jnp.int32))
        slot.adapter_id = 0
        if self.paged:
            if slot.pages:
                self._allocator.free(slot.pages)
                slot.pages = []
            if slot.shared:
                self._allocator.free(slot.shared)  # unpin: refs -= 1
                slot.shared = []
            import jax.numpy as jnp

            self._block_tables = self._set_block_row(
                self._block_tables, jnp.asarray(i, jnp.int32), self._trash_row)

    def page_stats(self, radix_stats: Optional[dict] = None) -> dict:
        """Pool gauges for llm_stats/metrics: in-use/total pages plus
        internal fragmentation (1 - tokens written / page tokens held) —
        the slack the page-size knob trades against table overhead.
        All-zero under the dense layout (no pool exists). Each allocated
        page's tokens count exactly ONCE: slots count only their OWNED
        pages' tokens, trie-held blocks (shared ones included — sharing
        is the trie's page) count as full blocks via ``radix_stats``
        (pass a precomputed ``RadixPrefixCache.stats()`` snapshot to
        avoid a second O(nodes) walk per scrape)."""
        if not self.paged:
            return {"kv_pages_total": 0, "kv_pages_in_use": 0,
                    "kv_page_size": 0, "kv_page_fragmentation": 0.0,
                    "kv_page_sheds": 0}
        total, in_use, sheds = self._allocator.stats()
        ps = self.page_size
        used_tokens = 0
        for s in self._slots:
            if s.active:
                used_tokens += min(
                    max(s.true_len + s.disp_new - len(s.shared) * ps, 0),
                    len(s.pages) * ps)
        job = self._prefill
        if job is not None:
            jslot = self._slots[job.slot]
            used_tokens += min(max(job.next - len(jslot.shared) * ps, 0),
                               len(jslot.pages) * ps)
        if self._radix is not None:
            # trie-held blocks count as used capacity (they are the cache
            # working set, not slack) — once per page, shared or not
            # (slots above counted owned pages only)
            rs = radix_stats if radix_stats is not None \
                else self._radix.stats()
            used_tokens += rs["prefix_cached_blocks"] * ps
        frag = 0.0
        if in_use > 0:
            frag = 1.0 - used_tokens / float(in_use * self.page_size)
        return {
            "kv_pages_total": total,
            "kv_pages_in_use": in_use,
            "kv_page_size": self.page_size,
            "kv_page_fragmentation": max(0.0, min(1.0, frag)),
            "kv_page_sheds": sheds,
        }

    def spec_stats(self) -> dict:
        """Speculation counters for llm_stats/metrics: aggregate draft
        acceptance rate, accepted tokens per target forward (the
        >1-per-cache-read multiplier), the per-slot acceptance EMAs the
        draft-length controller steers by, and the draft-overhead
        fraction (verify-forward token columns wasted on rejected
        drafts). All-off zeros when speculation is disabled."""
        if self.spec_mode == "off":
            return {"spec_mode": "off", "spec_k": 0,
                    "spec_accept_rate": 0.0, "spec_tokens_per_forward": 0.0,
                    "spec_slot_steps_total": 0,
                    "spec_accept_rate_per_slot": [],
                    "spec_draft_overhead_fraction": 0.0}
        snap = self._spec.snapshot()
        return {
            "spec_mode": self.spec_mode,
            "spec_k": self.spec_k,
            "spec_accept_rate": snap["spec_accept_rate"],
            "spec_tokens_per_forward": snap["spec_tokens_per_forward"],
            "spec_slot_steps_total": snap["spec_slot_steps_total"],
            "spec_accept_rate_per_slot": self._spec.rates(),
            "spec_draft_overhead_fraction":
                snap["spec_draft_overhead_fraction"],
        }

    def _finish(self, i: int):
        """Complete slot ``i``: trie insertion, slot release, THEN client
        notification. Resolving the future first was a latent race: the
        awaiting client resumes on the loop thread while this worker is
        still freeing pages, so a client-side stats read (or an immediate
        follow-up submit) could observe the finished request's pages as
        leaked/held — releasing before ``_resolve`` makes completion
        observable only after the pool is consistent."""
        slot = self._slots[i]
        toks = slot.tokens
        if self.eos_id in toks:
            toks = toks[: toks.index(self.eos_id)]
        fut, on_token = slot.future, slot.on_token
        if self._flight is not None:
            # ``tokens`` = tokens CREDITED to the slot (n_new): the sum the
            # per-step events must reproduce; an EOS trim shortens the
            # client's list but never the credited count
            self._flight.complete(i, "done", slot.n_new, self._tracer)
        if self._radix is not None and slot.ids is not None \
                and slot.adapter_id == 0:
            # base-adapter slots only: an adapted slot's KV embeds its
            # q/o/FFN deltas from layer 1 on, and inserting it would serve
            # tenant-specific KV to base traffic (docs/multitenancy.md)
            # insert the slot's prompt+generated blocks back into the trie
            # IN PLACE — page ownership transfers node-by-node, no dense
            # export. Only provably-written positions qualify: every token
            # but the last credited one has been FED to a later step (its
            # KV write is in device program order before any future
            # reader); the last token's write is run-ahead-dependent.
            hist = list(slot.ids) + slot.tokens[:max(slot.n_new - 1, 0)]
            consumed = self._radix.insert(
                hist, slot.shared + slot.pages, len(slot.shared))
            if consumed:
                # adopted/deduped pages are no longer this slot's to free
                slot.pages = [p for p in slot.pages if p not in consumed]
        self._release_slot(i)
        if on_token is not None:
            on_token(None)  # stream end sentinel
        if fut is not None:
            self._resolve(fut, result=toks)

    # ------------------------------------------------------------------
    # Pipelined decode: dispatch (producer) / drain (consumer)
    # ------------------------------------------------------------------
    def _dispatch_eligible(self) -> List[int]:
        """Slots worth stepping: active AND not yet dispatched through their
        token budget or cache length. A budget-exhausted slot still rides
        along (static shapes — the whole batch steps), but when NO slot
        needs tokens there is nothing to dispatch."""
        return [
            i for i, s in enumerate(self._slots)
            if s.active and s.disp_new < s.max_new
            and s.dispatched_pos() < self.max_len
        ]

    def _pick_k(self) -> int:
        """Fused-step block size for the next dispatch. K>1 only when the
        admit queue is empty (a fused block delays admission by K steps) and
        every eligible slot has >= K steps of budget left (so the block
        never overruns max_new or writes past the cache). Falling back to 1
        instead of an arbitrary clamp keeps the compile count at two
        programs (K=1 and K=fuse_steps)."""
        if self.fuse_steps <= 1 or self._pending or self._prefill is not None:
            return 1
        eligible = self._dispatch_eligible()
        if not eligible:
            return 1
        room = min(
            min(s.max_new - s.disp_new, self.max_len - s.dispatched_pos())
            for s in (self._slots[i] for i in eligible)
        )
        return self.fuse_steps if room >= self.fuse_steps else 1

    def _dispatch(self):
        """Enqueue one (possibly K-fused) decode step on the device WITHOUT
        waiting for its tokens: the state arrays are threaded from the
        previous step's outputs, so the device runs ahead of the host."""
        import time

        if self.spec_mode != "off":
            return self._dispatch_spec()
        k = self._pick_k()
        if self.paged:
            # grow every eligible slot's pages to cover this dispatch's k
            # writes FIRST — positions dispatched_pos()..dispatched_pos()+k-1
            # (the device's next_pos equals dispatched_pos()). An exhaustion
            # shed inside the loop can deactivate a LATER slot of this
            # snapshot, so re-check activity before touching each one:
            # growing a released slot would allocate pages nothing owns.
            for i in self._dispatch_eligible():
                if self._slots[i].active:
                    self._ensure_slot_pages(
                        i, self._slots[i].dispatched_pos() + k - 1)
            if not self._dispatch_eligible():
                return
        # adapted steps (llm.lora_decode_step): the pool/id pair rides at
        # the end of either signature, un-donated — same idiom as the
        # spec-step dispatch below
        lora = self._adapters is not None
        extra = () if not lora else (self._adapters.pool(),
                                     self._adapter_ids)
        if self.paged:
            fn = self.server._get_decode_step_paged(
                self.S, self.n_pages, k, lora=lora)
            t0 = time.perf_counter()
            (self._caches, self._last_tok, self._next_pos, self._keys,
             toks) = fn(self.server._params, self._caches, self._last_tok,
                        self._next_pos, self._keys, self._temp,
                        self._block_tables, *extra)
        else:
            fn = self.server._get_decode_step(self.S, self.max_len, k,
                                              lora=lora)
            t0 = time.perf_counter()
            (self._caches, self._last_tok, self._next_pos, self._keys,
             toks) = fn(self.server._params, self._caches, self._last_tok,
                        self._next_pos, self._keys, self._temp, *extra)
        self.server._decode_dispatch_times.append(time.perf_counter() - t0)
        snapshot = [(i, s.gen) for i, s in enumerate(self._slots) if s.active]
        for i, _ in snapshot:
            self._slots[i].disp_new += k
        self._inflight.append(_InFlight(toks, k, snapshot, t0))
        if len(self._inflight) > self._inflight_hwm:
            self._inflight_hwm = len(self._inflight)

    def _dispatch_spec(self):
        """Enqueue one fused draft+verify step (``LLMServer._get_spec_step``)
        WITHOUT waiting for its tokens. Each slot advances a data-dependent
        1..cap+1 tokens known only at drain time, so the dispatch side books
        the PESSIMISTIC maximum (cap+1) into ``disp_new`` — page
        provisioning and the cache-edge/budget caps must cover the
        all-accepted case — and the drain reconciles it back to the actual
        advance. The per-slot cap clamps the drafts offered: the
        acceptance-rate controller's depth, the remaining token budget
        (emits <= cap+1), and the cache edge (writes reach next_pos+cap)."""
        import time

        import jax.numpy as jnp

        K = self.spec_k
        caps = np.zeros((self.S,), np.int32)
        for i in self._dispatch_eligible():
            s = self._slots[i]
            cap = min(self._spec.cap(i), K,
                      s.max_new - s.disp_new - 1,
                      (self.max_len - 1) - s.dispatched_pos())
            caps[i] = max(int(cap), 0)
        if self.paged:
            # provision pages to the step's FURTHEST possible write
            # (next_pos + cap); an exhaustion shed inside the loop can
            # deactivate a later slot of this snapshot — re-check activity
            # (same discipline as the plain dispatch)
            for i in self._dispatch_eligible():
                if self._slots[i].active:
                    self._ensure_slot_pages(
                        i, self._slots[i].dispatched_pos() + int(caps[i]))
            if not self._dispatch_eligible():
                return
            fn = self.server._get_spec_step(
                self.S, K, self.hist_len, mode=self.spec_mode,
                layout="paged", n_pages=self.n_pages,
                lora=self._adapters is not None)
        else:
            fn = self.server._get_spec_step(
                self.S, K, self.hist_len, mode=self.spec_mode,
                layout="dense", lora=self._adapters is not None)
        cap_dev = jnp.asarray(caps)
        draft = self.spec_mode == "draft"
        # adapted verify (llm.lora_verify_step): the pool/id pair rides at
        # the end of every signature variant, un-donated
        extra = () if self._adapters is None else (
            self._adapters.pool(), self._adapter_ids)
        t0 = time.perf_counter()
        if self.paged and draft:
            (self._caches, self._last_tok, self._next_pos, self._keys,
             self._hist, toks, acc, self._draft_caches) = fn(
                self.server._params, self._caches, self._last_tok,
                self._next_pos, self._keys, self._temp, self._block_tables,
                self._hist, cap_dev, self.server._draft_params,
                self._draft_caches, *extra)
        elif self.paged:
            (self._caches, self._last_tok, self._next_pos, self._keys,
             self._hist, toks, acc) = fn(
                self.server._params, self._caches, self._last_tok,
                self._next_pos, self._keys, self._temp, self._block_tables,
                self._hist, cap_dev, *extra)
        elif draft:
            (self._caches, self._last_tok, self._next_pos, self._keys,
             self._hist, toks, acc, self._draft_caches) = fn(
                self.server._params, self._caches, self._last_tok,
                self._next_pos, self._keys, self._temp, self._hist,
                cap_dev, self.server._draft_params, self._draft_caches,
                *extra)
        else:
            (self._caches, self._last_tok, self._next_pos, self._keys,
             self._hist, toks, acc) = fn(
                self.server._params, self._caches, self._last_tok,
                self._next_pos, self._keys, self._temp, self._hist,
                cap_dev, *extra)
        self.server._decode_dispatch_times.append(time.perf_counter() - t0)
        snapshot = [(i, s.gen) for i, s in enumerate(self._slots) if s.active]
        booked = {}
        for i, _ in snapshot:
            booked[i] = int(caps[i]) + 1
            self._slots[i].disp_new += booked[i]
        self._inflight.append(_InFlight(toks, 1, snapshot, t0, acc=acc,
                                        booked=booked))
        if len(self._inflight) > self._inflight_hwm:
            self._inflight_hwm = len(self._inflight)

    def _drain_one(self):
        """Consume the OLDEST in-flight step: block until its tokens land,
        then run all host bookkeeping (EOS, budgets, streaming callbacks,
        slot release). Later steps stay dispatched while this runs — the
        host trails the device, never the other way around."""
        import time

        rec: _InFlight = self._inflight.popleft()
        # host lag in decode STEPS, not dispatch records: a fused record
        # covers k steps, so depth 2 at K=8 is a 16-step lag
        lag = rec.k + sum(r.k for r in self._inflight)
        t0 = time.perf_counter()
        # graftlint: allow-host-sync-in-hot-path(the consumer's deliberate drain sync: the host reads tokens one pipeline_depth BEHIND the device, so this blocks on the oldest step only while newer steps keep the chip busy — docs/performance.md)
        arr = np.asarray(rec.tokens)  # [S, k] — the only per-step host sync
        if rec.acc is not None:
            # graftlint: allow-host-sync-in-hot-path(part of the same drain sync: the verify step's per-slot accepted counts land with its tokens — the program already finished for the token read above)
            accs = np.asarray(rec.acc)  # [S] accepted counts, 1..K+1
        now = time.perf_counter()
        self.server._decode_sync_times.append(now - t0)
        self.server._decode_host_lag.append(lag)
        # steady-state step time: interval since the previous drain (the
        # pipeline overlaps dispatch+sync with device compute, so per-step
        # wall is drain-to-drain), floored at this record's dispatch time so
        # an idle gap doesn't inflate the histogram
        base = rec.t_dispatch if self._last_drain_t is None else max(
            self._last_drain_t, rec.t_dispatch)
        per_step = max(now - base, 0.0) / rec.k
        for _ in range(rec.k):
            self.server._decode_step_times.append(per_step)
        self._last_drain_t = now
        self.server._last_decode_kv_bytes = self._cache_nbytes
        if rec.acc is not None:
            self._credit_spec(rec, arr, accs)
            return
        for i, gen in rec.snapshot:
            slot = self._slots[i]
            if not slot.active or slot.gen != gen:
                # trailing run-ahead token for a finished (or already
                # replaced) occupant — masked, never surfaced
                continue
            if slot.n_new >= slot.max_new:
                continue  # budget-exhausted slot riding along
            credited = 0
            finish = False
            for j in range(rec.k):
                tok = int(arr[i, j])
                slot.tokens.append(tok)
                slot.n_new += 1
                credited += 1
                # inter-token gap at this drain (a fused block surfaces
                # its k tokens in one burst: trailing tokens record ~0)
                if slot.t_last is not None:
                    self.server._inter_token_times.append(now - slot.t_last)
                slot.t_last = now
                if slot.on_token is not None and tok != self.eos_id:
                    slot.on_token(tok)
                if (tok == self.eos_id or slot.n_new >= slot.max_new
                        or slot.host_pos() >= self.max_len):
                    finish = True
                    break
            if credited:
                self._pending.count_tokens(slot.tenant, slot.slo_class,
                                           credited)
            if self._flight is not None and credited:
                # one step event per slot per drain, BEFORE any finish
                # materializes the segment: tokens credited this drain plus
                # the step's device dwell (dispatch -> drain)
                self._flight.record(i, EV_STEP, tokens=credited,
                                    t_dispatch=rec.t_dispatch)
            if finish:
                self._finish(i)

    def _credit_spec(self, rec: _InFlight, arr: np.ndarray,
                     accs: np.ndarray):
        """Drain-side bookkeeping for one verify step: reconcile the
        pessimistic dispatch booking to the device's ACTUAL advance, feed
        the acceptance-rate controller, and credit each slot its accepted
        tokens with the same (slot, gen) masking and EOS/budget/cache-edge
        stops as the plain drain. An EOS landing INSIDE an accepted draft
        block cuts the credit loop there — the device ran ahead past it,
        exactly like a trailing run-ahead step, and the leftover tokens
        are dropped, never surfaced."""
        import time

        now = time.perf_counter()
        for i, gen in rec.snapshot:
            slot = self._slots[i]
            if not slot.active or slot.gen != gen:
                # the occupant this step decoded for is gone; the new
                # occupant's disp_new/controller state were reset at
                # admission, so there is nothing to reconcile either
                continue
            adv = int(accs[i])
            booked = rec.booked.get(i, 1)
            # dispatch booked the all-accepted maximum (cap+1); the device
            # actually advanced next_pos by adv — restore the invariant
            # dispatched_pos() == device next_pos + later in-flight maxima
            slot.disp_new -= booked - adv
            offered = booked - 1
            self._spec.observe(i, max(adv - 1, 0), offered, adv)
            self.server._spec_accepted.append(adv)
            if slot.n_new >= slot.max_new:
                continue  # budget-exhausted slot riding along
            credited = 0
            finish = False
            for j in range(adv):
                tok = int(arr[i, j])
                slot.tokens.append(tok)
                slot.n_new += 1
                credited += 1
                # inter-token gap (an accepted block surfaces as a burst:
                # its trailing tokens record ~0 gaps — the block's real
                # cadence is the first token's gap)
                if slot.t_last is not None:
                    self.server._inter_token_times.append(now - slot.t_last)
                slot.t_last = now
                if slot.on_token is not None and tok != self.eos_id:
                    slot.on_token(tok)
                if (tok == self.eos_id or slot.n_new >= slot.max_new
                        or slot.host_pos() >= self.max_len):
                    finish = True
                    break
            if credited:
                self._pending.count_tokens(slot.tenant, slot.slo_class,
                                           credited)
            if self._flight is not None and credited:
                # per-verify-step event: tokens surfaced, drafts offered,
                # device-accepted count — the speculative half of the
                # timeline's token accounting (recorded before any finish)
                self._flight.record(i, EV_STEP, tokens=credited,
                                    offered=offered, accepted=adv,
                                    t_dispatch=rec.t_dispatch)
            if finish:
                self._finish(i)

    async def _run(self):
        self.crashed = None  # a restarted loop is a recovered loop
        try:
            while True:
                # liveness heartbeat + deterministic chaos injection: both
                # happen in the loop's own serialized context, so a raising
                # chaos hook dies exactly like a device fault mid-turn
                self.heartbeat = self.clock()
                if self._chaos is not None:
                    self._chaos(self)
                # admit as many pending requests as there are free slots
                # (FIFO, peek-then-pop so a failed admit keeps the request);
                # device work runs in a worker thread so the event loop (and
                # co-hosted HTTP handlers) stays responsive during decode.
                # Admission happens while earlier steps are STILL IN FLIGHT
                # — the insert/set_slot queue behind them in device program
                # order, and the gen counter masks their stale tokens.
                while True:
                    req = self._pending.next_request()
                    if req is None:
                        break
                    if self._prefill is not None:
                        # one local chunked prefill stages at a time. An
                        # interactive head may preempt a staged BATCH-class
                        # one (the preemption contract: staged jobs only,
                        # never active slots, at most once per request) —
                        # otherwise wait for its chunks to finish
                        if (req.slo_class == "interactive"
                                and await asyncio.to_thread(
                                    self._preempt_for_interactive)):
                            continue
                        break
                    if self._remote is not None:
                        # disaggregated: stage the job on the prefill
                        # slice — host-side only, so MULTIPLE admissions
                        # can be in flight while decode keeps dispatching
                        admitted = await asyncio.to_thread(
                            self._admit_remote, req)
                    elif self.paged:
                        admitted = await asyncio.to_thread(
                            self._admit_begin, req)
                    else:
                        admitted = await asyncio.to_thread(self._admit, req)
                    if not admitted:
                        # deadline-aware preemption: an interactive head
                        # blocked on occupied slots may push ONE staged
                        # batch-class job (local chunked prefill / staged
                        # remote admission) back into the queue — never
                        # an active slot — then retry the same head
                        if (req.slo_class == "interactive"
                                and await asyncio.to_thread(
                                    self._preempt_for_interactive)):
                            continue
                        break  # no free slot/pages — decode frees them
                    # an _admit_* shed path already removed req from the
                    # scheduler (counting the shed); commit is a no-op then
                    self._pending.commit(req)
                # disaggregated: activate every finished handoff (import +
                # commit — one jitted scatter each, no prefill compute on
                # this slice)
                if self._transfer is not None and self._transfer.ready_depth():
                    await asyncio.to_thread(self._consume_handoffs)
                # producer: keep the device pipeline_depth steps ahead of
                # the host — dispatch is enqueue-only, no sync
                while (len(self._inflight) < self.pipeline_depth
                       and self._dispatch_eligible()):
                    await asyncio.to_thread(self._dispatch)
                # chunked prefill interleaves: ONE chunk per loop turn, so a
                # long admission prefill shares the device with the decode
                # dispatches above instead of stalling them for its whole
                # compile bucket (only the last chunk syncs)
                if self._prefill is not None:
                    await asyncio.to_thread(self._prefill_step)
                    if self._inflight:
                        await asyncio.to_thread(self._drain_one)
                    # never fall through to the idle wait on a prefill turn:
                    # the chunk either advanced the job or ACTIVATED the
                    # slot (now dispatch-eligible) — loop back to dispatch
                    continue
                # consumer: drain the oldest step one (or more) behind
                if self._inflight:
                    await asyncio.to_thread(self._drain_one)
                    continue
                if self._closed:
                    # staged remote jobs would leave futures hanging past
                    # the loop's death — fail them before returning
                    # (to_thread like every other _release_slot caller:
                    # page/block-table writers stay single-context)
                    if self._remote_jobs:
                        await asyncio.to_thread(
                            self._fail_remote_jobs,
                            RuntimeError("batcher closed"))
                    return
                if self._dispatch_eligible():
                    # a slot became runnable without a wakeup signal (e.g.
                    # activation landed on the final loop turn) — sleeping
                    # 0.5s here would stall its whole decode
                    continue
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    if self._closed:
                        return
        except BaseException as e:
            # device/compile failure: fail every in-flight and queued request
            # instead of leaving their futures hanging. The crash flag goes
            # up FIRST so fleet health checks eject this replica before any
            # failed future routes its client back through dispatch.
            self.crashed = e
            logger.exception("batcher loop died: %s", e)
            self._inflight.clear()
            self._prefill = None
            if self._remote_jobs:
                # cancel staged handoffs first: their slots then read as
                # released, so the slot sweep below cannot double-resolve
                # (to_thread keeps every _release_slot caller in the same
                # offload context the page/block-table state is guarded by)
                await asyncio.to_thread(self._fail_remote_jobs, e)
            for slot in self._slots:
                if slot.active or slot.prefilling:
                    if slot.on_token is not None:
                        try:
                            slot.on_token(None)  # unblock streaming consumers
                        except Exception:
                            pass
                        slot.on_token = None
                    if slot.future is not None:
                        self._resolve(slot.future, exc=e)
                    slot.active = False
                    slot.prefilling = False
                    slot.future = None
            for req in self._pending.drain_all():
                try:
                    self._unpin_request(req)
                except ValueError:
                    pass  # teardown must not mask the original error
                if req.on_token is not None:
                    try:
                        req.on_token(None)
                    except Exception:
                        pass
                self._resolve(req.fut, exc=e)
            raise
