"""Prometheus metrics with the reference's tag scheme.

Mirrors the engine's micrometer setup (`engine/src/main/java/io/seldon/engine/
metrics/SeldonRestTemplateExchangeTagsProvider.java:40-119`: deployment/
predictor/model tags on every series) and its registration of user metrics
carried in-band in ``meta.metrics`` (`PredictiveUnitBean.java:314-340`), plus
the feedback/reward counters (`:309-312`). Exposed at /metrics and /prometheus
(`ENGINE_PROMETHEUS_PATH` in the reference operator).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from seldon_core_tpu.contracts.payload import Feedback, SeldonMessage

LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


class MetricsRegistry:
    def __init__(self, deployment: str = "", predictor: str = ""):
        self.deployment = deployment or os.environ.get("DEPLOYMENT_NAME", "")
        self.predictor = predictor or os.environ.get("PREDICTOR_ID", "")
        self.registry = CollectorRegistry()
        base = ["deployment_name", "predictor_name"]
        self._api = Counter(
            "seldon_api_executor_server_requests_total",
            "API requests by method and code",
            base + ["method", "code"],
            registry=self.registry,
        )
        self._latency = Histogram(
            "seldon_api_executor_server_requests_seconds",
            "API latency",
            base + ["method"],
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._feedback = Counter(
            "seldon_api_model_feedback_total",
            "Feedback events",
            base,
            registry=self.registry,
        )
        self._feedback_reward = Counter(
            "seldon_api_model_feedback_reward_total",
            "Cumulative feedback reward",
            base,
            registry=self.registry,
        )
        self._custom_counters: Dict[str, Counter] = {}
        self._custom_gauges: Dict[str, Gauge] = {}
        self._custom_timers: Dict[str, Histogram] = {}
        # resilience layer (runtime/resilience.py): shed + deadline counters,
        # breaker state gauges/transition counters, admission occupancy
        self._shed = Counter(
            "seldon_resilience_shed_total",
            "Requests shed at admission (503 + Retry-After / RESOURCE_EXHAUSTED)",
            base + ["transport"],
            registry=self.registry,
        )
        self._deadline_exceeded = Counter(
            "seldon_resilience_deadline_exceeded_total",
            "Requests that exhausted their deadline budget",
            base + ["transport"],
            registry=self.registry,
        )
        self._breaker_state = Gauge(
            "seldon_resilience_breaker_state",
            "Per-node circuit breaker state (0 closed, 1 half-open, 2 open)",
            base + ["node"],
            registry=self.registry,
        )
        self._breaker_transitions = Counter(
            "seldon_resilience_breaker_transitions_total",
            "Per-node circuit breaker transitions by target state",
            base + ["node", "to"],
            registry=self.registry,
        )
        self._breaker_rejected = Counter(
            "seldon_resilience_breaker_rejected_total",
            "Calls rejected by an open circuit breaker",
            base + ["node"],
            registry=self.registry,
        )
        self._inflight = Gauge(
            "seldon_resilience_inflight",
            "Admitted requests currently in flight",
            base + ["transport"],
            registry=self.registry,
        )
        self._queue_depth = Gauge(
            "seldon_resilience_queue_depth",
            "Requests waiting in the admission queue",
            base + ["transport"],
            registry=self.registry,
        )
        self._remaining_budget = Histogram(
            "seldon_resilience_remaining_budget_seconds",
            "Deadline budget remaining at response time",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        # LLM decode-bandwidth observability (servers/llmserver.py
        # llm_stats): resident KV bytes, slot occupancy, per-step KV read
        # bytes, and a decode step-time histogram — the knobs the
        # kv_cache_dtype / fused_norm optimizations move, exposed so the
        # bandwidth win is visible at /metrics (benchmarks/DECODE_NOTES.md)
        self._kv_cache_bytes = Gauge(
            "seldon_llm_kv_cache_bytes",
            "Resident KV-cache bytes (continuous-batching slot caches + "
            "pinned prefix-cache entries)",
            base,
            registry=self.registry,
        )
        self._kv_occupancy = Gauge(
            "seldon_llm_kv_cache_occupancy",
            "Fraction of continuous-batching cache slots occupied (0-1)",
            base,
            registry=self.registry,
        )
        self._kv_bytes_per_step = Gauge(
            "seldon_llm_kv_bytes_per_step",
            "KV-cache bytes streamed from HBM per decode step (dense "
            "attention reads the whole static cache every step)",
            base,
            registry=self.registry,
        )
        # Paged KV pool (runtime/batcher.py PageAllocator): the in-use/total
        # page pair is the oversubscription headroom gauge — in_use nearing
        # total means admissions queue and the exhaustion shed path is about
        # to bite; fragmentation is the slack between tokens written and
        # page tokens held (the page-size knob's overhead term) —
        # docs/performance.md "Paged KV cache"
        self._kv_pages_in_use = Gauge(
            "seldon_llm_kv_pages_in_use",
            "KV pages currently allocated to slots (paged layout)",
            base,
            registry=self.registry,
        )
        self._kv_pages_total = Gauge(
            "seldon_llm_kv_pages_total",
            "Total KV pages in the global pool (incl. the 2 reserved pages)",
            base,
            registry=self.registry,
        )
        self._kv_page_fragmentation = Gauge(
            "seldon_llm_kv_page_fragmentation",
            "Internal fragmentation of allocated KV pages "
            "(1 - tokens written / page tokens held, 0-1)",
            base,
            registry=self.registry,
        )
        # Page-exhaustion sheds 503 from INSIDE the serving loop (LIFO
        # victim / unservable admission, runtime/batcher.py PageAllocator),
        # a path that never touches the AdmissionController — without its
        # own counter these sheds are invisible to an operator alerting on
        # seldon_resilience_shed_total while clients see RESOURCE_EXHAUSTED
        self._kv_page_sheds = Counter(
            "seldon_llm_kv_page_sheds_total",
            "Requests shed (503 + Retry-After / RESOURCE_EXHAUSTED) by KV "
            "page-pool exhaustion",
            base,
            registry=self.registry,
        )
        # Radix prefix cache (runtime/radix.py, docs/performance.md "Radix
        # prefix cache"): hit blocks are block-table entries a request did
        # NOT re-prefill (the FLOPs-saved signal), shared pages the live
        # trie<->slot sharing right now, cow copies the one-page price of
        # partial-block continuations, evictions the LRU churn, and
        # bytes-saved the KV bytes neither copied nor recomputed on hits
        self._prefix_hit_blocks = Counter(
            "seldon_llm_prefix_hit_blocks",
            "Cached KV blocks served by radix prefix-cache hits (block-"
            "table entries written instead of prefilled)",
            base,
            registry=self.registry,
        )
        self._prefix_shared_pages = Gauge(
            "seldon_llm_prefix_shared_pages",
            "Cached pages currently referenced by at least one live slot "
            "(refcount > 1; sampled at scrape)",
            base,
            registry=self.registry,
        )
        self._prefix_cached_blocks = Gauge(
            "seldon_llm_prefix_cached_blocks",
            "Token blocks resident in the radix prefix trie (sampled at "
            "scrape)",
            base,
            registry=self.registry,
        )
        self._prefix_cow_copies = Counter(
            "seldon_llm_prefix_cow_copies_total",
            "Copy-on-write page copies (a slot continuing part-way into a "
            "shared block pays one page copy)",
            base,
            registry=self.registry,
        )
        self._prefix_evicted_blocks = Counter(
            "seldon_llm_prefix_evicted_blocks_total",
            "Trie blocks evicted (LRU-by-leaf on pool pressure, plus "
            "in-place upgrades/clears)",
            base,
            registry=self.registry,
        )
        self._prefix_bytes_saved = Counter(
            "seldon_llm_prefix_bytes_saved",
            "KV bytes radix hits served by sharing pages in place "
            "(bytes neither recomputed by prefill nor copied)",
            base,
            registry=self.registry,
        )
        self._decode_step = Histogram(
            "seldon_llm_decode_step_seconds",
            "LLM decode step latency",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Streaming latency (runtime/batcher.py on_token path): TTFT is
        # the admission-side headline (what chunked prefill and
        # disaggregation move for the ARRIVING request), the inter-token
        # gap is the decode-side one (what they move for the VICTIMS —
        # every already-streaming request sharing the slice). Multi-token
        # drains (fused/speculative steps) surface a block in one burst,
        # so a block's trailing tokens observe ~0 gaps by construction.
        self._ttft = Histogram(
            "seldon_llm_ttft_seconds",
            "Time from request submission to its first generated token "
            "(batcher path)",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._inter_token = Histogram(
            "seldon_llm_inter_token_seconds",
            "Gap before each surfaced token (batcher on_token path; "
            "fused/speculative blocks surface as bursts)",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Disaggregated prefill/decode (runtime/disagg.py): per-handoff
        # wall (prefill-slice compute + device-to-device transfer +
        # decode-side import), handoffs delivered, and the staged+ready
        # backlog — the prefill-side congestion signal replica routing
        # steers by (docs/performance.md "Disaggregated serving")
        self._handoff = Histogram(
            "seldon_llm_handoff_seconds",
            "Per-admission prefill handoff wall: prefill-slice compute + "
            "D2D transfer + decode-side page import",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._handoffs_total = Counter(
            "seldon_llm_handoffs_total",
            "Prefill->decode KV handoffs delivered (disaggregated serving)",
            base,
            registry=self.registry,
        )
        self._handoff_queue_depth = Gauge(
            "seldon_llm_handoff_queue_depth",
            "Admissions staged on the prefill slice or awaiting import "
            "(sampled at scrape)",
            base,
            registry=self.registry,
        )
        self._handoff_network_bytes = Counter(
            "seldon_llm_handoff_network_bytes_total",
            "KV handoff frame bytes received over the network transport "
            "(handoff_transport='network'; 0 on the device_put fast path)",
            base,
            registry=self.registry,
        )
        # Wire framing (codec/framing.py): encode/decode walls and bytes
        # moved per egress path (rest / grpc / handoff) — the serialization
        # share of end-to-end latency the frame format exists to shrink
        # (docs/performance.md "Wire framing")
        self._frame_encode = Histogram(
            "seldon_frame_encode_seconds",
            "Frame encode wall (metadata pack + single bulk device->host "
            "transfer + buffer concat)",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._frame_decode = Histogram(
            "seldon_frame_decode_seconds",
            "Frame decode wall (header/table validation + zero-copy "
            "ndarray views)",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._frame_bytes = Counter(
            "seldon_frame_bytes_total",
            "Frame bytes encoded+decoded, by egress path",
            base + ["path"],
            registry=self.registry,
        )
        # Pipelined decode (runtime/batcher.py): the per-step wall above
        # splits into dispatch (enqueue the compiled step, no sync) vs sync
        # (host blocked on the oldest in-flight step's tokens); the gauge +
        # lag histogram prove the host actually trails the device (depth
        # >=2) instead of re-serializing — docs/performance.md
        self._decode_dispatch = Histogram(
            "seldon_llm_decode_dispatch_seconds",
            "Decode step dispatch wall (enqueue-only; the host does not "
            "wait for tokens)",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._decode_sync = Histogram(
            "seldon_llm_decode_sync_seconds",
            "Host sync wall per drain (blocked reading the oldest "
            "in-flight step's tokens)",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._decode_steps_in_flight = Gauge(
            "seldon_llm_decode_steps_in_flight",
            "Decode steps currently dispatched ahead of the host (sampled "
            "at scrape)",
            base,
            registry=self.registry,
        )
        self._decode_host_lag = Histogram(
            "seldon_llm_decode_host_lag_steps",
            "Steps the host trailed the device at each drain (>=2 means "
            "the pipeline is actually ahead)",
            base,
            buckets=(0, 1, 2, 3, 4, 6, 8, 16, 32),
            registry=self.registry,
        )
        # Speculative decoding (runtime/batcher.py + runtime/spec.py): the
        # accept rate and tokens-per-forward pair is the whole story —
        # tokens/forward > 1 is the >1-accepted-token-per-KV-read
        # multiplier speculation exists to buy, and the accept rate is why
        # it moves (benchmarks/DECODE_NOTES.md "PR 8"). The per-slot gauge
        # mirrors the draft-length controller's steering EMA; the overhead
        # fraction is the verify-forward compute share wasted on drafts
        # that lost verification (what speculation COSTS when text is
        # un-draftable).
        self._spec_accept_rate = Gauge(
            "seldon_llm_spec_accept_rate",
            "Aggregate draft-token acceptance rate (accepted drafts / "
            "offered drafts, 0-1)",
            base,
            registry=self.registry,
        )
        self._spec_accept_rate_slot = Gauge(
            "seldon_llm_spec_accept_rate_per_slot",
            "Per-slot draft acceptance-rate EMA (the draft-length "
            "controller's steering signal)",
            base + ["slot"],
            registry=self.registry,
        )
        self._spec_tokens_per_forward = Gauge(
            "seldon_llm_spec_tokens_per_forward",
            "Accepted tokens per verify forward (>1 = more than one token "
            "per KV-cache read)",
            base,
            registry=self.registry,
        )
        self._spec_accepted_per_step = Histogram(
            "seldon_llm_spec_accepted_tokens_per_step",
            "Tokens emitted by each drained verify step (1..K+1)",
            base,
            buckets=(1, 2, 3, 4, 5, 6, 8, 12, 16),
            registry=self.registry,
        )
        self._spec_draft_overhead = Gauge(
            "seldon_llm_spec_draft_overhead_fraction",
            "Fraction of verify-forward token columns wasted on drafts "
            "that lost verification (0-1)",
            base,
            registry=self.registry,
        )
        self._spec_slot_steps = Counter(
            "seldon_llm_spec_slot_verify_steps_total",
            "Per-slot verify steps drained: each verify forward "
            "contributes one per active slot (divide by the active-slot "
            "count for the forward/program count)",
            base,
            registry=self.registry,
        )
        # Multi-tenant serving (runtime/adapters.py + runtime/scheduler.py;
        # docs/multitenancy.md): the adapter pool's occupancy/churn/bytes,
        # per-(tenant, SLO-class) admission/shed/token tallies — the quota
        # and fairness audit trail — and per-class TTFT so the interactive
        # SLO is observable separately from the batch class it shares the
        # slots with.
        self._adapter_loaded = Gauge(
            "seldon_llm_adapter_loaded",
            "LoRA adapters currently resident in the dense pool "
            "(identity row excluded)",
            base,
            registry=self.registry,
        )
        self._adapter_evictions = Counter(
            "seldon_llm_adapter_evictions_total",
            "Adapters evicted from the pool (refcount-zero rows freed "
            "for reuse)",
            base,
            registry=self.registry,
        )
        self._adapter_pool_bytes = Gauge(
            "seldon_llm_adapter_pool_bytes",
            "HBM bytes held by the dense LoRA adapter pool (all rows, "
            "loaded or free)",
            base,
            registry=self.registry,
        )
        self._tenant_admitted = Counter(
            "seldon_tenant_admitted_total",
            "Requests admitted into the continuous batch, by tenant and "
            "SLO class",
            base + ["tenant", "slo_class"],
            registry=self.registry,
        )
        self._tenant_shed = Counter(
            "seldon_tenant_shed_total",
            "Requests shed (quota breach at push, page-exhaustion victim, "
            "staged-job shed), by tenant and SLO class",
            base + ["tenant", "slo_class"],
            registry=self.registry,
        )
        self._tenant_tokens = Counter(
            "seldon_tenant_tokens_total",
            "Tokens generated and credited, by tenant and SLO class",
            base + ["tenant", "slo_class"],
            registry=self.registry,
        )
        self._tenant_ttft = Histogram(
            "seldon_llm_tenant_ttft_seconds",
            "Time to first token by SLO class (the interactive-isolation "
            "signal bench phase L gates on)",
            base + ["slo_class"],
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        # Fleet fault tolerance (runtime/engine.py ReplicaSet,
        # docs/resilience.md): unplanned-death ejections and the
        # deterministic-recovery machinery. Counters ride the llm_stats ->
        # sync_llm catch-up idiom like every other fleet tally; the
        # journal-depth gauge is the live count of fleet generations whose
        # recovery record is still open (in flight, not yet resolved).
        self._fleet_ejections = Counter(
            "seldon_fleet_ejections_total",
            "Replicas ejected from fleet dispatch after an unplanned "
            "death (crashed or wedged batcher loop, consecutive dispatch "
            "failures)",
            base,
            registry=self.registry,
        )
        self._fleet_reinstatements = Counter(
            "seldon_fleet_reinstatements_total",
            "Ejected replicas reinstated into fleet dispatch after a "
            "successful half-open probe",
            base,
            registry=self.registry,
        )
        self._fleet_resumes = Counter(
            "seldon_fleet_resumes_total",
            "In-flight generations resumed bit-exactly on a surviving "
            "replica after their replica died mid-stream",
            base,
            registry=self.registry,
        )
        self._fleet_resumed_tokens = Counter(
            "seldon_fleet_resumed_tokens_total",
            "Tokens already delivered at resume time (skipped, never "
            "re-sent: the at-most-once streaming contract)",
            base,
            registry=self.registry,
        )
        self._fleet_budget_exhausted = Counter(
            "seldon_fleet_retry_budget_exhausted_total",
            "Recoveries refused because the fleet retry budget was "
            "exhausted (degraded to 503 + Retry-After instead of "
            "amplifying load)",
            base,
            registry=self.registry,
        )
        self._fleet_journal_depth = Gauge(
            "seldon_fleet_resume_journal_depth",
            "Fleet resume-journal entries currently open (fleet "
            "generations in flight with recovery records)",
            base,
            registry=self.registry,
        )
        # Tracing/flight-recorder observability (tracing/__init__.py +
        # runtime/flight.py): spans lost to export failures (a batch is
        # re-enqueued once; the second failure drops it — without this
        # counter a dead collector silently eats every trace), per-flush
        # OTLP export latency, and request traces retained by sampling
        # mode ('head' = the inbound traceparent flag said keep, 'tail' =
        # retained past an unsampled flag because TTFT / worst inter-token
        # gap crossed the tail thresholds) — docs/observability.md
        self._trace_spans_dropped = Counter(
            "seldon_trace_spans_dropped_total",
            "Trace spans dropped after a failed OTLP export's single "
            "bounded re-enqueue",
            base,
            registry=self.registry,
        )
        self._trace_export = Histogram(
            "seldon_trace_export_seconds",
            "OTLP trace export latency per flush (success or failure)",
            base,
            buckets=LATENCY_BUCKETS,
            registry=self.registry,
        )
        self._traces_retained = Counter(
            "seldon_llm_traces_retained_total",
            "Request traces materialized and exported, by sampling mode "
            "(head = inbound sampled flag; tail = latency-threshold "
            "retention of an unsampled request)",
            base + ["mode"],
            registry=self.registry,
        )
        # Elastic control plane (controlplane/autoscaler.py +
        # analytics/canary.py; docs/control-plane.md): fleet shape the
        # autoscaler drives (replicas serving vs draining, scale/rebalance
        # events), the canary rollout state machine, and the shadow
        # divergence record — the loop's own observability, synced at
        # scrape time by sync_controlplane (same catch-up idiom as the
        # resilience counters).
        self._autoscaler_replicas = Gauge(
            "seldon_autoscaler_replicas",
            "Replicas currently attached to the autoscaled ReplicaSet "
            "(draining included until detach)",
            base,
            registry=self.registry,
        )
        self._autoscaler_draining = Gauge(
            "seldon_autoscaler_draining_replicas",
            "Replicas draining toward detach (no fleet traffic; in-flight "
            "work completing)",
            base,
            registry=self.registry,
        )
        self._autoscaler_events = Counter(
            "seldon_autoscaler_scale_events_total",
            "Autoscaler actions applied, by kind (scale_up / scale_down / "
            "rebalance / collect)",
            base + ["action"],
            registry=self.registry,
        )
        self._canary_phase = Gauge(
            "seldon_canary_phase",
            "Canary rollout phase per router node (0 canary, 1 promoted, "
            "2 rolled back)",
            base + ["node"],
            registry=self.registry,
        )
        self._canary_rollbacks = Counter(
            "seldon_canary_rollbacks_total",
            "Automatic or manual canary rollbacks",
            base + ["node"],
            registry=self.registry,
        )
        self._canary_error_rate = Gauge(
            "seldon_canary_error_rate",
            "Windowed error rate per canary branch (baseline / candidate)",
            base + ["node", "branch"],
            registry=self.registry,
        )
        self._shadow_mirrors = Counter(
            "seldon_shadow_mirrors_total",
            "Requests mirrored to a shadow candidate (responses discarded)",
            base + ["node"],
            registry=self.registry,
        )
        self._shadow_divergences = Counter(
            "seldon_shadow_divergences_total",
            "Mirrored requests whose shadow output diverged from the "
            "primary's",
            base + ["node"],
            registry=self.registry,
        )
        self._shadow_errors = Counter(
            "seldon_shadow_errors_total",
            "Shadow-side failures (swallowed — the client never sees them)",
            base + ["node"],
            registry=self.registry,
        )
        self._shadow_max_diff = Gauge(
            "seldon_shadow_max_abs_diff",
            "Largest absolute output divergence observed on the shadow "
            "path",
            base + ["node"],
            registry=self.registry,
        )
        # breakers publish transitions through on_transition; remember which
        # are wired so scrape-time syncs are idempotent
        self._bound_breakers: set = set()

    # ------------------------------------------------------------------
    def _base(self) -> Dict[str, str]:
        return {"deployment_name": self.deployment, "predictor_name": self.predictor}

    def observe_api_call(self, method: str, code: str, seconds: float) -> None:
        self._api.labels(**self._base(), method=method, code=code).inc()
        self._latency.labels(**self._base(), method=method).observe(seconds)

    def observe_prediction(self, engine: Any, response: SeldonMessage, seconds: float) -> None:
        self.observe_api_call("predictions", "200", seconds)
        self.register_custom(response)

    def observe_feedback(self, feedback: Feedback) -> None:
        self._feedback.labels(**self._base()).inc()
        if feedback.reward:
            self._feedback_reward.labels(**self._base()).inc(abs(feedback.reward))

    # ------------------------------------------------------------------
    # Resilience observability (runtime/resilience.py)
    # ------------------------------------------------------------------
    def observe_deadline_exceeded(self, transport: str) -> None:
        self._deadline_exceeded.labels(**self._base(), transport=transport).inc()

    def observe_remaining_budget(self, seconds: float) -> None:
        self._remaining_budget.labels(**self._base()).observe(max(seconds, 0.0))

    def sync_resilience(
        self,
        engine: Any = None,
        admission: Any = None,
        transport: str = "rest",
    ) -> None:
        """Refresh breaker/admission gauges at scrape time; wires each
        breaker's transition callback to the transitions counter on first
        sight (idempotent — scraped every /metrics hit)."""
        if engine is not None and hasattr(engine, "breakers"):
            for node, breaker in engine.breakers():
                if id(breaker) not in self._bound_breakers:
                    self._bound_breakers.add(id(breaker))
                    counter = self._breaker_transitions

                    def on_transition(name, to, _c=counter):
                        _c.labels(**self._base(), node=name, to=to).inc()

                    breaker.on_transition = on_transition
                self._breaker_state.labels(**self._base(), node=node).set(breaker.state_code())
                rejected = self._breaker_rejected.labels(**self._base(), node=node)
                # counter catch-up from the breaker's own tally (breaker
                # rejections happen on the engine hot path, counted locally
                # to avoid a labels() lookup per call)
                delta = breaker.rejected_total - rejected._value.get()
                if delta > 0:
                    rejected.inc(delta)
        if admission is not None:
            self._inflight.labels(**self._base(), transport=transport).set(admission.inflight)
            self._queue_depth.labels(**self._base(), transport=transport).set(
                admission.queue_depth()
            )
            shed = self._shed.labels(**self._base(), transport=transport)
            delta = admission.shed_total - shed._value.get()
            if delta > 0:
                shed.inc(delta)

    # ------------------------------------------------------------------
    # Tracing observability (tracing/__init__.py Tracer.export_stats)
    # ------------------------------------------------------------------
    def sync_tracing(self, tracer: Any = None) -> None:
        """Refresh the trace export/retention series from the (global)
        tracer at /metrics scrape time — same drain/catch-up idiom as
        sync_llm: latencies are drained (observed exactly once), counters
        catch up from the tracer's own lifetime tallies."""
        if tracer is None:
            from seldon_core_tpu.tracing import get_tracer

            tracer = get_tracer()
        stats = tracer.export_stats()
        hist = self._trace_export.labels(**self._base())
        for seconds in stats.get("export_times_s", ()):
            hist.observe(seconds)
        dropped = self._trace_spans_dropped.labels(**self._base())
        delta = stats.get("spans_dropped_total", 0) - dropped._value.get()
        if delta > 0:
            dropped.inc(delta)
        for mode, total in (stats.get("retained_total") or {}).items():
            retained = self._traces_retained.labels(**self._base(), mode=mode)
            delta = total - retained._value.get()
            if delta > 0:
                retained.inc(delta)

    # ------------------------------------------------------------------
    # Elastic control plane observability (controlplane/autoscaler.py +
    # analytics/canary.py)
    # ------------------------------------------------------------------
    def _counter_catch_up(self, counter, value: float, **labels) -> None:
        """Counter catch-up from a component's lifetime tally (the
        sync_resilience idiom: events are counted locally on the hot/loop
        path; the scrape raises the Prometheus counter to match)."""
        bound = counter.labels(**self._base(), **labels)
        delta = value - bound._value.get()
        if delta > 0:
            bound.inc(delta)

    def sync_controlplane(self, source: Any = None) -> None:
        """Refresh autoscaler / canary / shadow series at scrape time.
        ``source`` is an engine (its graph nodes are walked for canary and
        shadow components, ``engine.autoscaler`` for the loop), a bare
        component, or an Autoscaler; anything without the stats surfaces
        is a no-op — the handler never needs to know what is deployed."""
        if source is None:
            return
        named = []  # (node label, object)
        autoscalers = []
        state = getattr(source, "state", None)
        if state is not None and hasattr(state, "walk"):
            for unit in state.walk():
                if unit.component is not None:
                    named.append((unit.name, unit.component))
        else:
            named.append((getattr(source, "name", "") or "", source))
        for obj in (source, getattr(source, "autoscaler", None)):
            if obj is not None and hasattr(obj, "autoscaler_stats"):
                autoscalers.append(obj)
        for a in autoscalers:
            stats = a.autoscaler_stats()
            self._autoscaler_replicas.labels(**self._base()).set(
                stats.get("autoscaler_replicas", 0))
            self._autoscaler_draining.labels(**self._base()).set(
                stats.get("autoscaler_draining", 0))
            for action, key in (
                ("scale_up", "autoscaler_scale_ups_total"),
                ("scale_down", "autoscaler_scale_downs_total"),
                ("rebalance", "autoscaler_rebalances_total"),
                ("collect", "autoscaler_collected_total"),
            ):
                self._counter_catch_up(self._autoscaler_events,
                                       stats.get(key, 0), action=action)
        for node, comp in named:
            canary_fn = getattr(comp, "canary_stats", None)
            if canary_fn is not None:
                stats = canary_fn()
                self._canary_phase.labels(**self._base(), node=node).set(
                    stats.get("canary_phase_code", 0))
                self._counter_catch_up(
                    self._canary_rollbacks,
                    stats.get("canary_rollbacks_total", 0), node=node)
                for branch, key in (
                    ("baseline", "canary_baseline_error_rate"),
                    ("candidate", "canary_candidate_error_rate"),
                ):
                    self._canary_error_rate.labels(
                        **self._base(), node=node, branch=branch).set(
                        stats.get(key, 0.0))
            shadow_fn = getattr(comp, "shadow_stats", None)
            if shadow_fn is not None:
                stats = shadow_fn()
                self._counter_catch_up(
                    self._shadow_mirrors,
                    stats.get("shadow_mirrors_total", 0), node=node)
                self._counter_catch_up(
                    self._shadow_divergences,
                    stats.get("shadow_divergences_total", 0), node=node)
                self._counter_catch_up(
                    self._shadow_errors,
                    stats.get("shadow_errors_total", 0), node=node)
                self._shadow_max_diff.labels(**self._base(), node=node).set(
                    stats.get("shadow_max_abs_diff", 0.0))

    # ------------------------------------------------------------------
    # LLM decode observability (servers/llmserver.py)
    # ------------------------------------------------------------------
    def sync_llm(self, component: Any) -> None:
        """Refresh the KV-cache gauges from the component's ``llm_stats()``
        snapshot and drain its pending decode step-time observations into
        the histogram. Called at /metrics scrape time (like
        sync_resilience); components without the surface are a no-op."""
        stats_fn = getattr(component, "llm_stats", None)
        if stats_fn is None:
            return
        stats = stats_fn()
        self._kv_cache_bytes.labels(**self._base()).set(stats.get("kv_cache_bytes", 0))
        self._kv_occupancy.labels(**self._base()).set(stats.get("kv_occupancy", 0.0))
        self._kv_bytes_per_step.labels(**self._base()).set(
            stats.get("kv_bytes_per_step", 0)
        )
        self._kv_pages_in_use.labels(**self._base()).set(
            stats.get("kv_pages_in_use", 0)
        )
        self._kv_pages_total.labels(**self._base()).set(
            stats.get("kv_pages_total", 0)
        )
        self._kv_page_fragmentation.labels(**self._base()).set(
            stats.get("kv_page_fragmentation", 0.0)
        )
        # counter catch-up from the allocator's own tally (sheds happen on
        # the decode hot path, counted locally — same idiom as
        # seldon_resilience_shed_total)
        page_sheds = self._kv_page_sheds.labels(**self._base())
        delta = stats.get("kv_page_sheds", 0) - page_sheds._value.get()
        if delta > 0:
            page_sheds.inc(delta)
        # radix prefix cache: gauges refresh from the snapshot, counters
        # catch up from the trie's lifetime tallies (hits/copies/evictions
        # happen on the admission path, counted locally — same idiom as
        # the page-shed counter above)
        self._prefix_shared_pages.labels(**self._base()).set(
            stats.get("prefix_shared_pages", 0)
        )
        self._prefix_cached_blocks.labels(**self._base()).set(
            stats.get("prefix_cached_blocks", 0)
        )
        for counter, key in (
            (self._prefix_hit_blocks, "prefix_hit_blocks"),
            (self._prefix_cow_copies, "prefix_cow_copies"),
            (self._prefix_evicted_blocks, "prefix_evicted_blocks"),
            (self._prefix_bytes_saved, "prefix_bytes_saved"),
        ):
            bound = counter.labels(**self._base())
            delta = stats.get(key, 0) - bound._value.get()
            if delta > 0:
                bound.inc(delta)
        hist = self._decode_step.labels(**self._base())
        for seconds in stats.get("decode_step_times_s", ()):
            hist.observe(seconds)
        ttft = self._ttft.labels(**self._base())
        for seconds in stats.get("ttft_s", ()):
            ttft.observe(seconds)
        gap = self._inter_token.labels(**self._base())
        for seconds in stats.get("inter_token_s", ()):
            gap.observe(seconds)
        handoff = self._handoff.labels(**self._base())
        for seconds in stats.get("handoff_times_s", ()):
            handoff.observe(seconds)
        # counter catch-up from the transfer queue's own tally (handoffs
        # land on the batcher loop, counted locally — same idiom as the
        # page-shed counter above)
        handoffs = self._handoffs_total.labels(**self._base())
        delta = stats.get("handoffs_total", 0) - handoffs._value.get()
        if delta > 0:
            handoffs.inc(delta)
        self._handoff_queue_depth.labels(**self._base()).set(
            stats.get("handoff_queue_depth", 0)
        )
        # wire bytes received by the network KV transport (the receiver's
        # lifetime tally — same catch-up idiom as handoffs_total)
        self._counter_catch_up(self._handoff_network_bytes,
                               stats.get("handoff_network_bytes_total", 0))
        disp = self._decode_dispatch.labels(**self._base())
        for seconds in stats.get("decode_dispatch_times_s", ()):
            disp.observe(seconds)
        sync = self._decode_sync.labels(**self._base())
        for seconds in stats.get("decode_sync_times_s", ()):
            sync.observe(seconds)
        lag = self._decode_host_lag.labels(**self._base())
        for steps in stats.get("decode_host_lag_steps", ()):
            lag.observe(steps)
        self._decode_steps_in_flight.labels(**self._base()).set(
            stats.get("decode_steps_in_flight", 0)
        )
        # speculative decoding: gauges refresh from the controller's
        # lifetime aggregates; the accepted-tokens histogram drains the
        # per-step observations accumulated since the last scrape, and the
        # slot-step counter catches up from the controller tally (same
        # idiom as the page-shed counter above)
        self._spec_accept_rate.labels(**self._base()).set(
            stats.get("spec_accept_rate", 0.0)
        )
        self._spec_tokens_per_forward.labels(**self._base()).set(
            stats.get("spec_tokens_per_forward", 0.0)
        )
        self._spec_draft_overhead.labels(**self._base()).set(
            stats.get("spec_draft_overhead_fraction", 0.0)
        )
        for slot, rate in enumerate(stats.get("spec_accept_rate_per_slot", ())):
            self._spec_accept_rate_slot.labels(
                **self._base(), slot=str(slot)).set(rate)
        acc_hist = self._spec_accepted_per_step.labels(**self._base())
        for tokens in stats.get("spec_accepted_per_step", ()):
            acc_hist.observe(tokens)
        steps = self._spec_slot_steps.labels(**self._base())
        delta = stats.get("spec_slot_steps_total", 0) - steps._value.get()
        if delta > 0:
            steps.inc(delta)
        # multi-tenant serving: adapter-pool gauges refresh from the
        # registry snapshot; per-(tenant, class) counters catch up from
        # the scheduler's lifetime tallies (admissions/sheds/tokens are
        # counted on the batcher loop — same idiom as the page-shed
        # counter), and per-class TTFT observations drain into the
        # labelled histogram
        self._adapter_loaded.labels(**self._base()).set(
            stats.get("adapter_loaded", 0))
        self._adapter_pool_bytes.labels(**self._base()).set(
            stats.get("adapter_pool_bytes", 0))
        self._counter_catch_up(self._adapter_evictions,
                               stats.get("adapter_evictions_total", 0))
        for row in stats.get("tenant_counters", ()):
            labels = {"tenant": row.get("tenant", ""),
                      "slo_class": row.get("slo_class", "")}
            self._counter_catch_up(self._tenant_admitted,
                                   row.get("admitted", 0), **labels)
            self._counter_catch_up(self._tenant_shed,
                                   row.get("shed", 0), **labels)
            self._counter_catch_up(self._tenant_tokens,
                                   row.get("tokens", 0), **labels)
        for cls, seconds in stats.get("ttft_by_class", ()):
            self._tenant_ttft.labels(
                **self._base(), slo_class=cls).observe(seconds)
        # fleet fault tolerance (ReplicaSet.llm_stats — solo components
        # carry none of these keys, so every line is a no-op for them)
        self._counter_catch_up(self._fleet_ejections,
                               stats.get("fleet_ejections_total", 0))
        self._counter_catch_up(self._fleet_reinstatements,
                               stats.get("fleet_reinstatements_total", 0))
        self._counter_catch_up(self._fleet_resumes,
                               stats.get("fleet_resumes_total", 0))
        self._counter_catch_up(self._fleet_resumed_tokens,
                               stats.get("fleet_resumed_tokens_total", 0))
        self._counter_catch_up(self._fleet_budget_exhausted,
                               stats.get("fleet_retry_budget_exhausted_total",
                                         0))
        self._fleet_journal_depth.labels(**self._base()).set(
            stats.get("fleet_resume_journal_depth", 0))

    def sync_framing(self) -> None:
        """Drain the frame codec's module-level tallies (codec/framing.py
        ``frame_stats``) into the frame histograms and per-path byte
        counter. Process-wide, not per-component — every egress path
        (remote-hop REST, gRPC binData, KV handoff) funnels through the
        one codec, so both /metrics handlers call this once per scrape."""
        from seldon_core_tpu.codec.framing import frame_stats

        stats = frame_stats()
        enc = self._frame_encode.labels(**self._base())
        for seconds in stats.get("frame_encode_times_s", ()):
            enc.observe(seconds)
        dec = self._frame_decode.labels(**self._base())
        for seconds in stats.get("frame_decode_times_s", ()):
            dec.observe(seconds)
        for path, nbytes in stats.get("frame_bytes_total", {}).items():
            self._counter_catch_up(self._frame_bytes, nbytes, path=path)

    # ------------------------------------------------------------------
    def register_custom(self, response: SeldonMessage) -> None:
        """Register COUNTER/GAUGE/TIMER metrics carried in response meta."""
        for m in response.meta.metrics:
            tags = dict(sorted(m.tags.items()))
            key = m.key + "|" + ",".join(f"{k}={v}" for k in tags for v in [tags[k]])
            label_names = list(tags)
            if m.type == "COUNTER":
                c = self._custom_counters.get(key)
                if c is None:
                    c = Counter(m.key, "custom counter", label_names, registry=self.registry)
                    self._custom_counters[key] = c
                (c.labels(**tags) if tags else c).inc(m.value)
            elif m.type == "GAUGE":
                g = self._custom_gauges.get(key)
                if g is None:
                    g = Gauge(m.key, "custom gauge", label_names, registry=self.registry)
                    self._custom_gauges[key] = g
                (g.labels(**tags) if tags else g).set(m.value)
            elif m.type == "TIMER":
                h = self._custom_timers.get(key)
                if h is None:
                    h = Histogram(m.key, "custom timer", label_names, registry=self.registry)
                    self._custom_timers[key] = h
                # reference timers arrive in milliseconds (`metrics.py` docs)
                (h.labels(**tags) if tags else h).observe(m.value / 1000.0)

    def expose(self) -> bytes:
        return generate_latest(self.registry)
