from seldon_core_tpu.metrics.registry import MetricsRegistry

__all__ = ["MetricsRegistry"]
