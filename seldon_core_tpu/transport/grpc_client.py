"""gRPC client: channel cache + typed unary calls.

Role of the reference's `engine/.../grpc/GrpcChannelHandler.java` (channel
cache) and the stub calls in `InternalPredictionService.java:261-283`; also
backs the SDK's gRPC paths.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional, Tuple

import grpc

from seldon_core_tpu.contracts.payload import Feedback, SeldonMessage, SeldonMessageList
from seldon_core_tpu.transport import proto_convert as pc
from seldon_core_tpu.transport.proto import prediction_pb2 as pb

_channels: Dict[Tuple[str, tuple], grpc.Channel] = {}
_lock = threading.Lock()

# method -> (service owning it for the Generic path, request serializer, from-dataclass)
_METHODS = {
    "Predict": ("Model", pb.SeldonMessage),
    "TransformInput": ("Generic", pb.SeldonMessage),
    "TransformOutput": ("Generic", pb.SeldonMessage),
    "Route": ("Router", pb.SeldonMessage),
    "Aggregate": ("Combiner", pb.SeldonMessageList),
    "SendFeedback": ("Model", pb.Feedback),
}


def get_channel(target: str, options: Optional[list] = None) -> grpc.Channel:
    key = (target, tuple(options or ()))
    with _lock:
        ch = _channels.get(key)
        if ch is None:
            ch = grpc.insecure_channel(target, options=options)
            _channels[key] = ch
        return ch


def _to_proto(msg: Any):
    if isinstance(msg, SeldonMessage):
        return pc.message_to_proto(msg)
    if isinstance(msg, SeldonMessageList):
        return pc.list_to_proto(msg)
    if isinstance(msg, Feedback):
        return pc.feedback_to_proto(msg)
    return msg  # already a proto


def call_sync(
    target: str,
    method: str,
    msg: Any,
    service: Optional[str] = None,
    timeout_s: float = 5.0,
    options: Optional[list] = None,
) -> SeldonMessage:
    if method not in _METHODS:
        raise ValueError(f"Unknown gRPC method {method}")
    default_service, _req_cls = _METHODS[method]
    service = service or default_service
    channel = get_channel(target, options)
    rpc = channel.unary_unary(
        f"/seldon.protos.{service}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.SeldonMessage.FromString,
    )
    out = rpc(_to_proto(msg), timeout=timeout_s)
    return pc.message_from_proto(out)


async def unary_call(
    target: str, method: str, msg: Any, service: Optional[str] = None, timeout_s: float = 5.0
) -> SeldonMessage:
    """Async wrapper used by RemoteComponent (runs the blocking stub in a thread)."""
    return await asyncio.to_thread(call_sync, target, method, msg, service, timeout_s)
