"""gRPC client: channel cache + typed unary calls.

Role of the reference's `engine/.../grpc/GrpcChannelHandler.java` (channel
cache) and the stub calls in `InternalPredictionService.java:261-283`; also
backs the SDK's gRPC paths.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Dict, Optional, Tuple

import grpc

from seldon_core_tpu.contracts.payload import Feedback, SeldonMessage, SeldonMessageList
from seldon_core_tpu.transport import proto_convert as pc
from seldon_core_tpu.transport.proto import prediction_pb2 as pb

# Cache entries hold (channel, credentials): keeping a strong reference to
# the credentials object pins its id() for the life of the entry, so a
# recycled id can never alias a dead credential's cached channel (the cache
# would otherwise hand a channel built with different TLS material to a new
# credentials object allocated at the same address).
_channels: Dict[Tuple[str, tuple, Optional[int]], Tuple[grpc.Channel, Any]] = {}
_lock = threading.Lock()

# method -> (service owning it for the Generic path, request serializer, from-dataclass)
_METHODS = {
    "Predict": ("Model", pb.SeldonMessage),
    "TransformInput": ("Generic", pb.SeldonMessage),
    "TransformOutput": ("Generic", pb.SeldonMessage),
    "Route": ("Router", pb.SeldonMessage),
    "Aggregate": ("Combiner", pb.SeldonMessageList),
    "SendFeedback": ("Model", pb.Feedback),
    "DebugTimeline": ("Model", pb.SeldonMessage),
}


def make_channel_credentials(
    ca_cert: Optional[str] = None,
    client_cert: Optional[str] = None,
    client_key: Optional[str] = None,
) -> grpc.ChannelCredentials:
    """TLS channel credentials from PEM file paths (reference parity:
    `seldon_client.py` channel_credentials for grpc gateway calls). With no
    paths, system roots are used; cert+key enable mutual TLS."""

    def read(path: Optional[str]) -> Optional[bytes]:
        if path is None:
            return None
        with open(path, "rb") as f:
            return f.read()

    return grpc.ssl_channel_credentials(
        root_certificates=read(ca_cert),
        private_key=read(client_key),
        certificate_chain=read(client_cert),
    )


def get_channel(
    target: str,
    options: Optional[list] = None,
    credentials: Optional[grpc.ChannelCredentials] = None,
) -> grpc.Channel:
    # key on the credentials object identity: two clients with different TLS
    # material to the same target must not share a channel. The entry pins the
    # credentials object so its id() stays unique while the key is live.
    key = (target, tuple(options or ()), id(credentials) if credentials is not None else None)
    with _lock:
        entry = _channels.get(key)
        if entry is None:
            if credentials is not None:
                ch = grpc.secure_channel(target, credentials, options=options)
            else:
                ch = grpc.insecure_channel(target, options=options)
            _channels[key] = entry = (ch, credentials)
        return entry[0]


def _to_proto(msg: Any):
    if isinstance(msg, SeldonMessage):
        return pc.message_to_proto(msg)
    if isinstance(msg, SeldonMessageList):
        return pc.list_to_proto(msg)
    if isinstance(msg, Feedback):
        return pc.feedback_to_proto(msg)
    return msg  # already a proto


def call_sync(
    target: str,
    method: str,
    msg: Any,
    service: Optional[str] = None,
    timeout_s: float = 5.0,
    options: Optional[list] = None,
    credentials: Optional[grpc.ChannelCredentials] = None,
    metadata: Optional[list] = None,
) -> SeldonMessage:
    if method not in _METHODS:
        raise ValueError(f"Unknown gRPC method {method}")
    default_service, _req_cls = _METHODS[method]
    service = service or default_service
    channel = get_channel(target, options, credentials)
    rpc = channel.unary_unary(
        f"/seldon.protos.{service}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.SeldonMessage.FromString,
    )
    out = rpc(_to_proto(msg), timeout=timeout_s, metadata=metadata)
    return pc.message_from_proto(out)


def call_stream(
    target: str,
    method: str,
    msg: Any,
    service: str = "Model",
    timeout_s: float = 120.0,
    options: Optional[list] = None,
    credentials: Optional[grpc.ChannelCredentials] = None,
    metadata: Optional[list] = None,
):
    """Server-streaming call (e.g. Model/GenerateStream): yields
    SeldonMessages as the server emits them — the gRPC mirror of the REST
    SSE event stream."""
    channel = get_channel(target, options, credentials)
    rpc = channel.unary_stream(
        f"/seldon.protos.{service}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.SeldonMessage.FromString,
    )
    for out in rpc(_to_proto(msg), timeout=timeout_s, metadata=metadata):
        yield pc.message_from_proto(out)


async def unary_call(
    target: str, method: str, msg: Any, service: Optional[str] = None, timeout_s: float = 5.0,
    metadata: Optional[list] = None,
) -> SeldonMessage:
    """Async wrapper used by RemoteComponent (runs the blocking stub in a
    thread); ``metadata`` carries cross-cutting keys like ``traceparent``."""
    return await asyncio.to_thread(
        call_sync, target, method, msg, service, timeout_s, None, None, metadata)
