"""CLI entrypoints.

``python -m seldon_core_tpu.transport.cli microservice <Interface> [REST|GRPC]``
mirrors the reference wrapper CLI (`python/seldon_core/microservice.py:177-322`):
import the user class, typed params from PREDICTIVE_UNIT_PARAMETERS, optional
state restore (--persistence), annotations file, log level, tracing, then serve.

``... engine`` boots a whole predictor graph from ENGINE_PREDICTOR (base64
JSON spec), the role of the reference's JVM engine bootstrap
(`engine/.../EnginePredictor.java:58-108`) — but serving the graph in-process.
"""

from __future__ import annotations

import argparse
import importlib
import json
import logging
import os
import sys
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

ANNOTATIONS_FILE = "/etc/podinfo/annotations"


def load_annotations(path: str = ANNOTATIONS_FILE) -> Dict[str, str]:
    """k8s downward-API annotations file: `key="value"` lines
    (`python/seldon_core/microservice.py:90-113`)."""
    annotations: Dict[str, str] = {}
    if not os.path.exists(path):
        return annotations
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or "=" not in line:
                continue
            key, _, value = line.partition("=")
            annotations[key.strip()] = value.strip().strip('"')
    return annotations


def setup_logging() -> None:
    level = os.environ.get("SELDON_LOG_LEVEL", "INFO").upper()
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )


def import_interface(name: str):
    """Import `Name` from module `Name`, or `pkg.mod.Class` dotted form."""
    sys.path.insert(0, os.getcwd())
    if "." in name:
        module_name, _, class_name = name.rpartition(".")
    else:
        module_name = class_name = name
    module = importlib.import_module(module_name)
    return getattr(module, class_name)


def build_component(interface_name: str, persistence: bool = False):
    from seldon_core_tpu.contracts.parameters import parse_parameters
    from seldon_core_tpu.runtime.persistence import (
        PersistenceThread,
        ReplicaSync,
        restore_component,
    )

    klass = import_interface(interface_name)
    parameters = parse_parameters()
    component = None
    restored_shared = False
    if persistence:
        component = restore_component(klass)
        restored_shared = component is not None
    if component is None:
        component = klass(**parameters)
    if hasattr(component, "load"):
        component.load()
    threads = []
    if persistence:
        thread = PersistenceThread(component)
        thread.start()
        threads.append(thread)
        # stateful routers under replicated serving additionally share their
        # feedback counters across replicas (G-counter ReplicaSync)
        if hasattr(component, "stats_snapshot"):
            sync = ReplicaSync(component, store=thread.store)
            if not sync.restore_own() and restored_shared and hasattr(component, "reset_local_stats"):
                # The shared-key snapshot predates replica-keyed sync (legacy
                # single-key persistence). Exactly ONE replica may adopt those
                # counts as its own — an exclusive claim decides which; the
                # rest zero their counters and learn the history as peers.
                if thread.store.save_if_absent(f"{sync.key}:legacy-claim", sync.rid):
                    logger.info("adopted legacy persisted counters as replica %s", sync.rid)
                else:
                    component.reset_local_stats()
            sync.sync()  # publish + pull peers NOW, not after one period
            sync.start()
            threads.append(sync)
            import atexit

            atexit.register(sync.stop)  # final publish on shutdown
    return component, threads


def run_microservice(args: argparse.Namespace) -> None:
    setup_logging()
    _bootstrap_multihost()
    component, _ = build_component(args.interface_name, persistence=args.persistence)
    port = args.port or int(os.environ.get("PREDICTIVE_UNIT_SERVICE_PORT", "5000"))
    unit_id = os.environ.get("PREDICTIVE_UNIT_ID", "")
    api = (args.api or os.environ.get("API_TYPE", "REST")).upper()
    logger.info("serving %s as %s on port %d", args.interface_name, api, port)
    annotations = load_annotations()
    if api == "REST":
        from seldon_core_tpu.transport.rest import make_component_app, serve

        serve(make_component_app(component, unit_id=unit_id, annotations=annotations),
              host=args.host, port=port)
    elif api == "GRPC":
        from seldon_core_tpu.transport.grpc_server import serve_component

        serve_component(component, host=args.host, port=port, unit_id=unit_id,
                        annotations=annotations)
    else:
        raise SystemExit(f"Unknown API type {api} (use REST or GRPC)")


def _bootstrap_multihost() -> None:
    """Join the multi-host device world when the environment describes one
    (JAX_COORDINATOR_ADDRESS etc.) — must run before any component load in
    every serving entrypoint; single-host is a no-op."""
    from seldon_core_tpu.parallel.multihost import initialize as multihost_init

    multihost_init()


def run_engine(args: argparse.Namespace) -> None:
    setup_logging()
    _bootstrap_multihost()
    from seldon_core_tpu.metrics.registry import MetricsRegistry
    from seldon_core_tpu.runtime.engine import GraphEngine
    from seldon_core_tpu.transport.rest import make_engine_app, serve

    # Spec from file, ENGINE_PREDICTOR env, or the default SIMPLE_MODEL the
    # reference engine uses when unconfigured (`EnginePredictor.java:122-141`).
    spec = _load_spec(args.spec)
    annotations = load_annotations()
    engine = GraphEngine(spec, annotations=annotations)
    metrics = MetricsRegistry(predictor=spec.name)
    port = args.port or int(os.environ.get("ENGINE_SERVER_PORT", "8000"))
    logger.info("engine serving predictor %r on port %d", spec.name, port)
    api = (args.api or "REST").upper()
    if api == "GRPC":
        from seldon_core_tpu.transport.grpc_server import serve_engine

        serve_engine(engine, host=args.host, port=port, metrics=metrics,
                     annotations=annotations)
    elif api == "IPC":
        # native shared-memory data plane: N frontend processes attach as
        # IPCClient workers, this process owns the device (transport/ipc.py)
        import asyncio

        from seldon_core_tpu.transport.ipc import IPCEngineServer

        if not args.ipc_base:
            raise SystemExit("--api IPC needs --ipc-base <path>")
        server = IPCEngineServer(engine, args.ipc_base, n_workers=args.ipc_workers)
        logger.info("engine serving over IPC at %s (%d workers)", args.ipc_base, args.ipc_workers)
        asyncio.run(server.serve_forever())
    else:
        serve(make_engine_app(engine, metrics=metrics, annotations=annotations),
              host=args.host, port=port)


def _load_spec(path: Optional[str]):
    from seldon_core_tpu.contracts.graph import PredictorSpec, load_predictor_spec_from_env

    if path:
        with open(path) as f:
            return PredictorSpec.from_dict(json.load(f))
    spec = load_predictor_spec_from_env()
    if spec is None:
        spec = PredictorSpec.from_dict(
            {"name": "default", "graph": {"name": "simple", "type": "MODEL", "implementation": "SIMPLE_MODEL"}}
        )
    return spec


def run_edge(args: argparse.Namespace) -> None:
    """Serve a predictor graph behind the native edge (native/edge.cc).

    All-builtin graphs compile to an edge program and execute entirely in the
    compiled edge process; anything else keeps the edge as the HTTP frontend
    with this process running the Python/XLA engine behind the shared-memory
    ring (the reference's engine-pod split, collapsed onto one host)."""
    import subprocess
    import tempfile

    setup_logging()
    from seldon_core_tpu.runtime.edgeprogram import (
        EDGE_BINARY,
        build_edge_binaries,
        compile_edge_program,
        fallback_program,
        write_program,
    )

    if not build_edge_binaries():
        raise SystemExit("native toolchain unavailable; use `engine` instead")
    spec = _load_spec(args.spec)
    deployment = os.environ.get("DEPLOYMENT_NAME", "")
    program = compile_edge_program(spec, deployment=deployment)
    port = args.port or int(os.environ.get("ENGINE_SERVER_PORT", "8000"))
    tmp = tempfile.mkdtemp(prefix="seldon-edge-")
    openapi_path = os.path.join(tmp, "openapi.json")
    from seldon_core_tpu.transport.openapi import engine_spec

    with open(openapi_path, "w") as f:
        json.dump(engine_spec(), f)

    grpc_port = args.grpc_port or int(os.environ.get("ENGINE_SERVER_GRPC_PORT", "0"))
    if program is not None:
        # pure-builtin graph: the edge process needs no Python at all
        # (native gRPC included when a gRPC port is configured)
        prog_path = write_program(program, os.path.join(tmp, "program.json"))
        logger.info("graph compiled natively; edge serving on port %d", port)
        argv = [
            EDGE_BINARY, "--program", prog_path, "--port", str(port),
            "--openapi", openapi_path, "--workers", str(args.workers),
            "--max-inflight", str(args.max_inflight),
        ]
        if grpc_port:
            argv += ["--grpc-port", str(grpc_port)]
        os.execv(EDGE_BINARY, argv)

    # The graph needs Python — build the engine, then try the DEVICE_MODEL
    # compile: graphs of builtins + real model leaves still execute natively
    # in the edge, which ships only packed tensors (ring kind 2) to this
    # process's ModelExecutor. Anything else (remote nodes, seeded routers,
    # custom transformers) keeps full-graph ring fallback (kind 0).
    import asyncio

    from seldon_core_tpu.runtime.engine import GraphEngine
    from seldon_core_tpu.runtime.remote import RemoteComponent
    from seldon_core_tpu.transport.ipc import (
        IPCEngineServer,
        ModelExecutor,
        cleanup_rings,
        default_ring_dir,
    )

    engine = GraphEngine(spec, annotations=load_annotations())
    # the compiler owns device eligibility (unit type/children/method
    # checks live in compile_edge_program); pass every in-process component
    eligible = {
        st.unit.name: st.component
        for st in engine.state.walk()
        if st.component is not None
        and not isinstance(st.component, RemoteComponent)
    }
    program = compile_edge_program(spec, deployment=deployment,
                                   device_components=eligible)
    executor = None
    if program is not None and program.get("deviceModels"):
        executor = ModelExecutor(
            [eligible[name] for name in program["deviceModels"]])
        logger.info("warming device-model compile caches (all batch buckets)")
        executor.warm()
        prog_path = write_program(program, os.path.join(tmp, "program.json"))
        logger.info(
            "graph compiled natively with %d device model(s): %s",
            len(program["deviceModels"]), ", ".join(program["deviceModels"]),
        )
    else:
        prog_path = write_program(
            fallback_program(spec, deployment=deployment),
            os.path.join(tmp, "program.json"),
        )
    # rings live on tmpfs (default_ring_dir docstring: disk-backed MAP_SHARED
    # pays a journal fault per cleaned page — ~20x ping-pong latency)
    ring_dir = None if args.ipc_base else default_ring_dir()
    base = args.ipc_base or os.path.join(ring_dir, "ring")
    # One edge process per worker, each with its own response ring (an edge's
    # internal fork cannot be used here: forked loops would race on one ring).
    n_workers = max(1, args.workers)
    # drain up to 256 frames per FFI crossing: under a 512-stream gRPC load
    # one cycle then feeds the micro-batcher a full compile bucket instead
    # of four 64-frame nibbles (pop_many is one C call either way)
    server = IPCEngineServer(engine, base, n_workers=n_workers,
                             model_executor=executor, batch=256)
    edge_argv_tail = []
    if grpc_port:
        # the edge serves gRPC on every plane: native for builtin/device
        # tensor traffic, full-proto ring frames (kind 3/4) into this
        # engine process for everything else — one port, every graph
        edge_argv_tail = ["--grpc-port", str(grpc_port)]
    edges = [
        subprocess.Popen(
            [
                EDGE_BINARY, "--program", prog_path, "--port", str(port),
                "--ring", base, "--ring-worker", str(w), "--openapi", openapi_path,
                "--max-inflight", str(args.max_inflight),
            ] + edge_argv_tail
        )
        for w in range(n_workers)
    ]
    logger.info(
        "graph needs the Python engine; %d edge frontend(s) on port %d, ring %s",
        n_workers, port, base,
    )

    async def run():
        serve_task = asyncio.ensure_future(server.serve_forever())
        try:
            while all(e.poll() is None for e in edges):
                await asyncio.sleep(0.2)
        finally:
            server.stop()
            await serve_task

    try:
        asyncio.run(run())
    finally:
        for e in edges:
            if e.poll() is None:
                e.terminate()
        cleanup_rings(base, n_workers)
        if ring_dir is not None:
            import shutil

            shutil.rmtree(ring_dir, ignore_errors=True)


def run_loadtest_native(args: argparse.Namespace) -> None:
    """Drive the native closed-loop loadgen and (optionally) write the
    benchmark report the driver/judge reads."""
    import subprocess

    from seldon_core_tpu.runtime.edgeprogram import LOADGEN_BINARY, build_edge_binaries

    if not build_edge_binaries():
        raise SystemExit("native toolchain unavailable")
    cmd = [
        LOADGEN_BINARY, "--host", args.host, "--port", str(args.port),
        "--connections", str(args.connections), "--duration", str(args.duration),
        "--warmup", str(args.warmup), "--label", args.label,
    ]
    if args.body:
        cmd += ["--body", args.body]
    if args.path:
        cmd += ["--path", args.path]
    out = subprocess.run(cmd, capture_output=True, text=True)
    sys.stdout.write(out.stdout)
    sys.stderr.write(out.stderr)
    if out.returncode not in (0, 3):
        raise SystemExit(out.returncode)
    if args.report:
        report = json.loads(out.stdout.strip().splitlines()[-1])
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2)


def run_render(args: argparse.Namespace) -> None:
    import yaml

    from seldon_core_tpu.contracts.graph import SeldonDeploymentSpec
    from seldon_core_tpu.controlplane import render_manifests
    from seldon_core_tpu.controlplane.render import DEFAULT_ENGINE_IMAGE

    with open(args.file) as f:
        raw = yaml.safe_load(f)
    sdep = SeldonDeploymentSpec.from_dict(raw)
    manifests = render_manifests(
        sdep,
        namespace=args.namespace,
        engine_image=args.engine_image or DEFAULT_ENGINE_IMAGE,
        tpu_chips=args.tpu_chips,
        tpu_topology=args.tpu_topology,
    )
    if args.format == "json":
        print(json.dumps(manifests, indent=2))
    else:
        print(yaml.safe_dump_all(manifests, sort_keys=False))


def run_request_logger(args: argparse.Namespace) -> None:
    setup_logging()
    from seldon_core_tpu.observability.request_logger import make_logger_app
    from seldon_core_tpu.transport.rest import serve

    serve(make_logger_app(), host=args.host, port=args.port)


def run_loadtest(args: argparse.Namespace) -> None:
    from seldon_core_tpu.benchmarks import loadgen

    loadgen.main(args)


def run_convert(args: argparse.Namespace) -> None:
    setup_logging()
    from seldon_core_tpu.models.convert import convert_checkpoint

    out = convert_checkpoint(args.hf_path, args.out_dir, dtype=args.dtype)
    print(out)


def run_render_chart(args: argparse.Namespace) -> None:
    """Render a deploy/charts chart without the helm binary (the in-repo
    subset renderer; `helm template` produces the same output)."""
    from seldon_core_tpu.controlplane.charts import render_chart

    values = {}
    if args.values:
        import yaml

        with open(args.values) as f:
            values = yaml.safe_load(f) or {}
    for name, text in render_chart(args.chart, values, namespace=args.namespace):
        print(f"---\n# Source: {os.path.basename(args.chart)}/templates/{name}")
        print(text)


def run_analytics(args: argparse.Namespace) -> None:
    from seldon_core_tpu.observability.dashboards import write_artifacts

    for path in write_artifacts(args.out):
        print(path)


def run_loadtest_worker(args: argparse.Namespace) -> None:
    from seldon_core_tpu.benchmarks.fleet import worker_serve

    worker_serve(args.listen, host=args.host, once=args.once, token=args.token)


def run_loadtest_fleet(args: argparse.Namespace) -> None:
    from seldon_core_tpu.benchmarks.fleet import run_distributed, run_local_fleet

    workers = [w.strip() for w in args.workers.split(",") if w.strip()]
    n_workers = len(workers) or max(args.local_workers, 1)

    per_worker = None
    if args.contract:
        if args.grpc:
            raise SystemExit("--contract payloads are REST-only (the native gRPC "
                             "generator uses its fixed proto request)")
        # contract-conforming payloads, a distinct draw per worker — the
        # fleet analogue of the reference's locust drivers sampling the
        # contract's feature ranges (predict_rest_locust.py:17-53); the
        # native generator replays its body, so variety is per worker
        from seldon_core_tpu.client.contract import generate_batch, load_contract

        contract = load_contract(args.contract)
        per_worker = [
            {"body": json.dumps({"data": {"ndarray": generate_batch(
                contract, max(args.batch, 1), seed=i).tolist()}})}
            for i in range(n_workers)
        ]
    job = {
        "host": args.host,
        "port": args.port,
        "connections": args.connections,
        "duration": args.duration,
        "grpc": args.grpc,
        "body": args.body,
        "path": args.path,
    }
    if workers:
        report = run_distributed(workers, job, per_worker=per_worker, token=args.token)
    else:
        report = run_local_fleet(job, n_workers, per_worker=per_worker)
    out = json.dumps(report, indent=2)
    print(out)
    if args.report:
        with open(args.report, "w") as f:
            f.write(out)


def run_operator(args: argparse.Namespace) -> None:
    setup_logging()
    from seldon_core_tpu.controlplane.operator import (
        FileCluster,
        KubectlCluster,
        Operator,
        Reconciler,
    )

    if args.kubectl:
        cluster: Any = KubectlCluster()
    else:
        cluster = FileCluster(args.cluster)
    reconciler = Reconciler(
        cluster,
        namespace=args.namespace,
        engine_image=args.engine_image,
        tpu_chips=args.tpu_chips,
        tpu_topology=args.tpu_topology,
    )
    op = Operator(args.crs, reconciler, interval=args.interval, status_dir=args.status_dir)
    if args.once:
        op.run_once()
    else:
        op.run_forever()


def main(argv: Optional[list] = None) -> None:
    parser = argparse.ArgumentParser(prog="seldon-core-tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    ms = sub.add_parser("microservice", help="serve one component")
    ms.add_argument("interface_name")
    ms.add_argument("api", nargs="?", default=None, help="REST or GRPC")
    ms.add_argument("--port", type=int, default=None)
    ms.add_argument("--host", default="0.0.0.0")
    ms.add_argument("--persistence", action="store_true")
    ms.set_defaults(func=run_microservice)

    eng = sub.add_parser("engine", help="serve a predictor graph in-process")
    eng.add_argument("--spec", default=None, help="path to PredictorSpec JSON")
    eng.add_argument("--api", default="REST")
    eng.add_argument("--port", type=int, default=None)
    eng.add_argument("--host", default="0.0.0.0")
    eng.add_argument("--ipc-base", default=None, help="ring path base for --api IPC")
    eng.add_argument("--ipc-workers", type=int, default=4)
    eng.set_defaults(func=run_engine)

    from seldon_core_tpu.client.testers import add_tester_args, tester_main

    tester = sub.add_parser(
        "tester", help="contract-fuzz a microservice (seldon-core-tester equivalent)"
    )
    add_tester_args(tester, endpoint_kind="microservice")
    tester.set_defaults(func=tester_main)

    api_tester = sub.add_parser(
        "api-tester", help="contract-fuzz an engine/gateway (seldon-core-api-tester equivalent)"
    )
    add_tester_args(api_tester, endpoint_kind="engine")
    api_tester.set_defaults(func=tester_main)

    render = sub.add_parser("render", help="SeldonDeployment CR -> k8s manifests (operator logic)")
    render.add_argument("file", help="CR or spec JSON/YAML file")
    render.add_argument("--namespace", default="default")
    render.add_argument("--engine-image", default=None)
    render.add_argument("--tpu-chips", type=int, default=1)
    render.add_argument("--tpu-topology", default=None)
    render.add_argument("--format", default="yaml", choices=["yaml", "json"])
    render.set_defaults(func=run_render)

    op = sub.add_parser(
        "operator", help="watch SeldonDeployment CRs and reconcile the cluster"
    )
    op.add_argument("--crs", required=True, help="directory of CR JSON/YAML files")
    op.add_argument("--cluster", default="./cluster", help="FileCluster root dir")
    op.add_argument("--kubectl", action="store_true", help="apply via kubectl instead")
    op.add_argument("--namespace", default="default")
    op.add_argument("--engine-image", default=None)
    op.add_argument("--tpu-chips", type=int, default=1)
    op.add_argument("--tpu-topology", default=None)
    op.add_argument("--interval", type=float, default=2.0)
    op.add_argument("--status-dir", default=None,
                    help="status output dir (default <crs>/.status; set when --crs is read-only)")
    op.add_argument("--once", action="store_true", help="single reconcile pass")
    op.set_defaults(func=run_operator)

    cv = sub.add_parser(
        "convert-llama", help="HF Llama checkpoint -> servable native checkpoint"
    )
    cv.add_argument("hf_path", help="local HF snapshot directory (or hub id if cached)")
    cv.add_argument("out_dir")
    cv.add_argument("--dtype", default="bfloat16")
    cv.set_defaults(func=run_convert)

    an = sub.add_parser(
        "analytics", help="write Prometheus rules + Grafana dashboard artifacts"
    )
    an.add_argument("--out", default="deploy/analytics")
    an.set_defaults(func=run_analytics)

    rl = sub.add_parser("request-logger", help="CloudEvents message-pair logger service")
    rl.add_argument("--port", type=int, default=2222)
    rl.add_argument("--host", default="0.0.0.0")
    rl.set_defaults(func=run_request_logger)

    edge = sub.add_parser("edge", help="serve a graph behind the native C++ edge")
    edge.add_argument("--spec", default=None, help="path to PredictorSpec JSON")
    edge.add_argument("--port", type=int, default=None)
    edge.add_argument("--grpc-port", type=int, default=None,
                      help="gRPC port (default env ENGINE_SERVER_GRPC_PORT; "
                           "native for builtin graphs, Python engine otherwise)")
    edge.add_argument("--workers", type=int, default=1, help="SO_REUSEPORT event loops")
    edge.add_argument("--max-inflight", type=int, default=4096,
                      help="overload-shed threshold: parked in-flight predictions "
                           "beyond this get HTTP 429 / gRPC RESOURCE_EXHAUSTED")
    edge.add_argument("--ipc-base", default=None, help="ring path base for fallback mode")
    edge.set_defaults(func=run_edge)

    ltn = sub.add_parser("loadtest-native", help="native closed-loop load generator")
    ltn.add_argument("host")
    ltn.add_argument("port", type=int)
    ltn.add_argument("--connections", type=int, default=32)
    ltn.add_argument("--duration", type=float, default=10.0)
    ltn.add_argument("--warmup", type=float, default=1.0)
    ltn.add_argument("--body", default=None)
    ltn.add_argument("--path", default=None)
    ltn.add_argument("--label", default="rest")
    ltn.add_argument("--report", default=None, help="write JSON report to this file")
    ltn.set_defaults(func=run_loadtest_native)

    rc = sub.add_parser("render-chart", help="render a deploy/charts helm chart (no helm needed)")
    rc.add_argument("chart", help="chart directory, e.g. deploy/charts/seldon-mab")
    rc.add_argument("--values", default=None, help="values override YAML file")
    rc.add_argument("--namespace", default="seldon-system")
    rc.set_defaults(func=run_render_chart)

    ltw = sub.add_parser("loadtest-worker", help="fleet slave: run loadgen jobs sent over TCP")
    ltw.add_argument("--listen", type=int, required=True)
    ltw.add_argument("--host", default="127.0.0.1",
                     help="bind address; non-loopback requires --token")
    ltw.add_argument("--token", default=None,
                     help="shared secret jobs must carry (required off-loopback)")
    ltw.add_argument("--once", action="store_true")
    ltw.set_defaults(func=run_loadtest_worker)

    ltf = sub.add_parser(
        "loadtest-fleet",
        help="fleet master: local multi-process or remote-worker load generation",
    )
    ltf.add_argument("host")
    ltf.add_argument("port", type=int)
    ltf.add_argument("--local-workers", type=int, default=0,
                     help="spawn N generator processes on this host")
    ltf.add_argument("--workers", default="",
                     help="comma-separated host:port loadtest-worker addresses")
    ltf.add_argument("--connections", type=int, default=32, help="per worker")
    ltf.add_argument("--duration", type=float, default=10.0)
    ltf.add_argument("--grpc", action="store_true")
    ltf.add_argument("--body", default=None)
    ltf.add_argument("--contract", default=None,
                     help="contract.json: each worker replays a distinct payload "
                          "drawn from the feature ranges (REST only)")
    ltf.add_argument("--batch", type=int, default=1, help="rows per contract payload")
    ltf.add_argument("--path", default=None)
    ltf.add_argument("--token", default=None,
                     help="shared secret for remote workers bound off-loopback")
    ltf.add_argument("--report", default=None, help="write merged JSON report here")
    ltf.set_defaults(func=run_loadtest_fleet)

    lt = sub.add_parser("loadtest", help="async load generator (locust equivalent)")
    lt.add_argument("host")
    lt.add_argument("port", type=int)
    lt.add_argument("--clients", type=int, default=16)
    lt.add_argument("--duration", type=float, default=10.0)
    lt.add_argument("--batch", type=int, default=1)
    lt.add_argument("--contract", default=None)
    lt.add_argument("--grpc", action="store_true")
    lt.set_defaults(func=run_loadtest)

    args = parser.parse_args(argv)
    args.func(args)


if __name__ == "__main__":
    main()
