"""OpenAPI specs served at /seldon.json, the capability of the reference's
`openapi/{engine.oas3.json,wrapper.oas3.json}` (assembled by
`openapi/create_openapis.py`); generated programmatically here."""

from __future__ import annotations

from typing import Any, Dict

from seldon_core_tpu.version import __version__

_SELDON_MESSAGE_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "properties": {
        "status": {
            "type": "object",
            "properties": {
                "code": {"type": "integer"},
                "info": {"type": "string"},
                "reason": {"type": "string"},
                "status": {"type": "string", "enum": ["SUCCESS", "FAILURE"]},
            },
        },
        "meta": {
            "type": "object",
            "properties": {
                "puid": {"type": "string"},
                "tags": {"type": "object"},
                "routing": {"type": "object", "additionalProperties": {"type": "integer"}},
                "requestPath": {"type": "object", "additionalProperties": {"type": "string"}},
                "metrics": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "properties": {
                            "key": {"type": "string"},
                            "type": {"type": "string", "enum": ["COUNTER", "GAUGE", "TIMER"]},
                            "value": {"type": "number"},
                            "tags": {"type": "object"},
                        },
                    },
                },
            },
        },
        "data": {
            "type": "object",
            "properties": {
                "names": {"type": "array", "items": {"type": "string"}},
                "tensor": {
                    "type": "object",
                    "properties": {
                        "shape": {"type": "array", "items": {"type": "integer"}},
                        "values": {"type": "array", "items": {"type": "number"}},
                    },
                },
                "ndarray": {"type": "array"},
            },
        },
        "binData": {"type": "string", "format": "byte"},
        "strData": {"type": "string"},
        "jsonData": {},
    },
}

_FEEDBACK_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "properties": {
        "request": {"$ref": "#/components/schemas/SeldonMessage"},
        "response": {"$ref": "#/components/schemas/SeldonMessage"},
        "reward": {"type": "number"},
        "truth": {"$ref": "#/components/schemas/SeldonMessage"},
    },
}


def _base(title: str) -> Dict[str, Any]:
    return {
        "openapi": "3.0.0",
        "info": {"title": title, "version": __version__},
        "components": {
            "schemas": {
                "SeldonMessage": _SELDON_MESSAGE_SCHEMA,
                "Feedback": _FEEDBACK_SCHEMA,
                "SeldonMessageList": {
                    "type": "object",
                    "properties": {
                        "seldonMessages": {
                            "type": "array",
                            "items": {"$ref": "#/components/schemas/SeldonMessage"},
                        }
                    },
                },
            }
        },
        "paths": {},
    }


def _op(request_schema: str, summary: str) -> Dict[str, Any]:
    return {
        "post": {
            "summary": summary,
            "requestBody": {
                "content": {
                    "application/json": {"schema": {"$ref": f"#/components/schemas/{request_schema}"}}
                }
            },
            "responses": {
                "200": {
                    "description": "SeldonMessage response",
                    "content": {
                        "application/json": {"schema": {"$ref": "#/components/schemas/SeldonMessage"}}
                    },
                }
            },
        }
    }


def wrapper_spec() -> Dict[str, Any]:
    spec = _base("seldon-core-tpu microservice API")
    spec["paths"] = {
        "/predict": _op("SeldonMessage", "Model predict"),
        "/transform-input": _op("SeldonMessage", "Transform input"),
        "/transform-output": _op("SeldonMessage", "Transform output"),
        "/route": _op("SeldonMessage", "Route"),
        "/aggregate": _op("SeldonMessageList", "Aggregate"),
        "/send-feedback": _op("Feedback", "Send feedback"),
    }
    return spec


def engine_spec() -> Dict[str, Any]:
    spec = _base("seldon-core-tpu engine API")
    spec["paths"] = {
        "/api/v0.1/predictions": _op("SeldonMessage", "Predict through the graph"),
        "/api/v0.1/feedback": _op("Feedback", "Send feedback through the graph"),
    }
    return spec
