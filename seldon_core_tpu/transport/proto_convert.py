"""Converters between the in-memory payload dataclasses and the wire protos.

This is the single proto<->array codec in the system; it runs only at the gRPC
edge. (The reference runs its equivalent — `python/seldon_core/utils.py:
147-278` — on every graph hop.)
"""

from __future__ import annotations

from typing import Any

import numpy as np
from google.protobuf import json_format
from google.protobuf.struct_pb2 import ListValue, Value

from seldon_core_tpu.contracts.payload import (
    ENC_NDARRAY,
    ENC_TENSOR,
    DefaultData,
    Feedback,
    Meta,
    Metric,
    SeldonError,
    SeldonMessage,
    SeldonMessageList,
    Status,
)
from seldon_core_tpu.transport.proto import prediction_pb2 as pb


# ---------------------------------------------------------------------------
# to proto
# ---------------------------------------------------------------------------

def meta_to_proto(meta: Meta) -> pb.Meta:
    out = pb.Meta()
    out.puid = meta.puid
    for k, v in meta.tags.items():
        json_format.ParseDict(v, out.tags[k]) if isinstance(v, (dict, list)) else _set_value(out.tags[k], v)
    for k, v in meta.routing.items():
        out.routing[k] = v
    for k, v in meta.request_path.items():
        out.requestPath[k] = v
    for m in meta.metrics:
        pm = out.metrics.add()
        pm.key = m.key
        pm.type = pb.Metric.MetricType.Value(m.type)
        pm.value = m.value
        for tk, tv in m.tags.items():
            pm.tags[tk] = str(tv)
    return out


def _set_value(value: Value, v: Any) -> None:
    if v is None:
        value.null_value = 0
    elif isinstance(v, bool):
        value.bool_value = v
    elif isinstance(v, (int, float)):
        value.number_value = float(v)
    elif isinstance(v, str):
        value.string_value = v
    else:
        json_format.ParseDict(v, value)


def message_to_proto(msg: SeldonMessage) -> pb.SeldonMessage:
    out = pb.SeldonMessage()
    if msg.status is not None:
        out.status.code = msg.status.code
        out.status.info = msg.status.info
        out.status.reason = msg.status.reason
        out.status.status = pb.Status.StatusFlag.Value(msg.status.status)
    out.meta.CopyFrom(meta_to_proto(msg.meta))
    if msg.which == "data" and msg.data is not None:
        d = msg.data
        out.data.names.extend(d.names)
        if d.encoding == ENC_TENSOR:
            arr = np.asarray(d.array, dtype=np.float64)
            out.data.tensor.shape.extend(arr.shape)
            out.data.tensor.values.extend(arr.ravel().tolist())
        else:
            raw = d.raw_ndarray if (d.raw_ndarray is not None and d.array is None) else np.asarray(d.array).tolist()
            out.data.ndarray.CopyFrom(json_format.ParseDict(raw, ListValue()))
    elif msg.which == "binData":
        out.binData = msg.bin_data or b""
    elif msg.which == "strData":
        out.strData = msg.str_data or ""
    elif msg.which == "jsonData":
        json_format.ParseDict(msg.json_data, out.jsonData) if isinstance(
            msg.json_data, (dict, list)
        ) else _set_value(out.jsonData, msg.json_data)
    return out


def list_to_proto(lst: SeldonMessageList) -> pb.SeldonMessageList:
    out = pb.SeldonMessageList()
    for m in lst.messages:
        out.seldonMessages.add().CopyFrom(message_to_proto(m))
    return out


def feedback_to_proto(fb: Feedback) -> pb.Feedback:
    out = pb.Feedback()
    if fb.request is not None:
        out.request.CopyFrom(message_to_proto(fb.request))
    if fb.response is not None:
        out.response.CopyFrom(message_to_proto(fb.response))
    out.reward = fb.reward
    if fb.truth is not None:
        out.truth.CopyFrom(message_to_proto(fb.truth))
    return out


# ---------------------------------------------------------------------------
# from proto
# ---------------------------------------------------------------------------

def meta_from_proto(meta: pb.Meta) -> Meta:
    return Meta(
        puid=meta.puid,
        tags={k: json_format.MessageToDict(v) for k, v in meta.tags.items()},
        routing=dict(meta.routing),
        request_path=dict(meta.requestPath),
        metrics=[
            Metric(
                key=m.key,
                type=pb.Metric.MetricType.Name(m.type),
                value=m.value,
                tags=dict(m.tags),
            )
            for m in meta.metrics
        ],
    )


def message_from_proto(msg: pb.SeldonMessage) -> SeldonMessage:
    out = SeldonMessage(meta=meta_from_proto(msg.meta))
    if msg.HasField("status"):
        out.status = Status(
            code=msg.status.code,
            info=msg.status.info,
            reason=msg.status.reason,
            status=pb.Status.StatusFlag.Name(msg.status.status),
        )
    which = msg.WhichOneof("data_oneof")
    if which == "data":
        d = msg.data
        names = list(d.names)
        inner = d.WhichOneof("data_oneof")
        if inner == "tensor":
            # packed float64: frombuffer-equivalent fast path
            values = np.array(d.tensor.values, dtype=np.float64)
            shape = tuple(d.tensor.shape) or (values.size,)
            try:
                arr = values.reshape(shape)
            except ValueError as e:
                raise SeldonError(f"tensor values do not fit shape {shape}: {e}")
            out.data = DefaultData(names=names, array=arr, encoding=ENC_TENSOR)
        elif inner == "ndarray":
            raw = json_format.MessageToDict(d.ndarray)
            arr = None
            try:
                a = np.asarray(raw)
                arr = a if a.dtype != object else None
            except Exception:
                arr = None
            out.data = DefaultData(names=names, array=arr, encoding=ENC_NDARRAY, raw_ndarray=raw)
        else:
            raise SeldonError("DefaultData proto carries no tensor/ndarray")
        out.which = "data"
    elif which == "binData":
        out.bin_data = msg.binData
        out.which = "binData"
    elif which == "strData":
        out.str_data = msg.strData
        out.which = "strData"
    elif which == "jsonData":
        out.json_data = json_format.MessageToDict(msg.jsonData)
        out.which = "jsonData"
    return out


def list_from_proto(lst: pb.SeldonMessageList) -> SeldonMessageList:
    return SeldonMessageList(messages=[message_from_proto(m) for m in lst.seldonMessages])


def feedback_from_proto(fb: pb.Feedback) -> Feedback:
    return Feedback(
        request=message_from_proto(fb.request) if fb.HasField("request") else None,
        response=message_from_proto(fb.response) if fb.HasField("response") else None,
        reward=fb.reward,
        truth=message_from_proto(fb.truth) if fb.HasField("truth") else None,
    )
