import os
import sys

# Generated code does a top-level `import prediction_pb2`-style resolution of
# google.protobuf only; make this package importable both as a package module
# and for regeneration via `protoc --python_out=seldon_core_tpu/transport/proto`.
sys.path.insert(0, os.path.dirname(__file__))

from seldon_core_tpu.transport.proto import prediction_pb2  # noqa: E402,F401
