"""REST transport (aiohttp).

Two route families, matching the reference:

- **Microservice routes** (`python/seldon_core/wrapper.py:37-94`): /predict,
  /transform-input, /transform-output, /route, /aggregate, /send-feedback,
  plus GET /seldon.json (OpenAPI) and /health. Serves ONE component.
- **Engine routes** (`engine/.../api/rest/RestClientController.java:76-245`):
  /api/v0.1/predictions, /api/v0.1/feedback, /ready, /live, /pause, /unpause,
  /ping, /metrics (Prometheus). Serves a whole predictor GRAPH via the
  in-process engine — the reference needs a separate JVM pod for this; here it
  is the same process, so a single-model deployment is one process total.

Request parsing accepts raw JSON bodies, form field ``json=``, and multipart
(binData/strData parts) like the reference (`python/seldon_core/flask_utils.py:
6-65`).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Any, Callable, Optional

from aiohttp import web

from seldon_core_tpu.codec.framing import (
    CONTENT_TYPE_FRAME,
    decode_message,
    encode_message,
    frameable,
)
from seldon_core_tpu.components import dispatch
from seldon_core_tpu.contracts.payload import (
    Feedback,
    SeldonError,
    SeldonMessage,
    SeldonMessageList,
)
from seldon_core_tpu.metrics.registry import MetricsRegistry
from seldon_core_tpu.runtime.resilience import (
    DEADLINE_HEADER,
    AdmissionController,
    Deadline,
    ResumeMarker,
    ShedError,
    current_deadline,
    deadline_scope,
)
from seldon_core_tpu.tracing import get_tracer

logger = logging.getLogger(__name__)


def deadline_from_headers(request: web.Request) -> Optional[Deadline]:
    """``Seldon-Deadline-Ms: <float>`` — the client's total budget for this
    request. Missing/garbage headers mean no deadline (the engine may still
    apply the deployment's ``seldon.io/deadline-default-ms``)."""
    raw = request.headers.get(DEADLINE_HEADER)
    if raw is None:
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError):
        return None
    if ms <= 0:
        return None
    return Deadline.from_ms(ms)


def shed_response(e: ShedError) -> web.Response:
    return web.json_response(
        {"status": e.to_status().to_dict()},
        status=503,
        headers={"Retry-After": str(max(int(e.retry_after_s), 1))},
    )


async def parse_request(request: web.Request) -> dict:
    """JSON body, ?json= query param, form json= field, or multipart parts."""
    ctype = request.content_type or ""
    if ctype.startswith("multipart/"):
        data = await request.post()
        out: dict = {}
        for key, value in data.items():
            if hasattr(value, "file"):
                raw = value.file.read()
                if key == "binData":
                    import base64

                    out[key] = base64.b64encode(raw).decode()
                elif key == "strData":
                    out[key] = raw.decode()
                else:
                    out[key] = json.loads(raw)
            else:
                out[key] = json.loads(value) if key not in ("strData",) else value
        return out
    body = await request.text()
    if ctype == "application/x-www-form-urlencoded" and body:
        from urllib.parse import parse_qs

        qs = parse_qs(body)
        if "json" in qs:
            return json.loads(qs["json"][0])
        # fall through: clients (curl -d) often send raw JSON under the
        # default form content type
    if body:
        try:
            return json.loads(body)
        except json.JSONDecodeError as e:
            raise SeldonError(f"Invalid JSON body: {e}")
    if "json" in request.query:
        return json.loads(request.query["json"])
    raise SeldonError("Empty request body")


def error_response(e: Exception) -> web.Response:
    if isinstance(e, SeldonError):
        status = e.to_status()
        code = e.status_code
    else:
        logger.exception("unhandled error")
        from seldon_core_tpu.contracts.payload import Status

        status = Status(code=500, info=str(e), reason="INTERNAL_ERROR", status="FAILURE")
        code = 500
    return web.json_response({"status": status.to_dict()}, status=code)


def _json(msg: SeldonMessage) -> web.Response:
    return web.json_response(msg.to_dict())


def _wants_frame(request: web.Request) -> bool:
    return CONTENT_TYPE_FRAME in request.headers.get("Accept", "")


def _respond(request: web.Request, msg: SeldonMessage) -> web.Response:
    """Frame the response only when the client ASKED for frames (Accept)
    and the payload actually benefits (tensor/binData); everything else —
    including every error path — stays JSON, so clients that never opted
    in see byte-identical behavior."""
    if _wants_frame(request) and frameable(msg):
        return web.Response(body=encode_message(msg, path="rest"),
                            content_type=CONTENT_TYPE_FRAME)
    return _json(msg)


async def parse_framed_message(request: web.Request) -> SeldonMessage:
    """Decode an ``application/x-seldon-frame`` request body. Frames carry
    SeldonMessage only — aggregate lists and feedback stay JSON."""
    return decode_message(await request.read(), path="rest")


# ---------------------------------------------------------------------------
# Microservice app: one component
# ---------------------------------------------------------------------------

def make_component_app(
    component: Any,
    unit_id: str = "",
    metrics: Optional[MetricsRegistry] = None,
    admission: Optional[AdmissionController] = None,
    annotations: Optional[dict] = None,
) -> web.Application:
    app = web.Application(client_max_size=1 << 30)
    metrics = metrics or MetricsRegistry()
    admission = admission or AdmissionController.from_annotations(annotations)
    # dynamic Retry-After: shed backoff derived from the component's live
    # backlog instead of the fixed constant (docs/resilience.md)
    from seldon_core_tpu.observability.timeline import wire_retry_after

    wire_retry_after(admission, component=component)
    tracer = get_tracer()

    def handler(fn: Callable, parser: Callable, method_name: str):
        async def handle(request: web.Request) -> web.Response:
            t0 = time.perf_counter()
            try:
                await admission.acquire()
            except ShedError as e:
                metrics.observe_api_call(method_name, "503", time.perf_counter() - t0)
                return shed_response(e)
            try:
                deadline = deadline_from_headers(request)
                if request.content_type == CONTENT_TYPE_FRAME:
                    if getattr(parser, "__func__", parser) \
                            is not SeldonMessage.from_dict.__func__:
                        raise SeldonError(
                            f"{method_name} does not accept framed bodies "
                            "(frames carry SeldonMessage only)",
                            status_code=415)
                    payload = await parse_framed_message(request)
                else:
                    payload = parser(await parse_request(request))
                with deadline_scope(deadline):
                    # inbound W3C traceparent roots this request's server
                    # span in the caller's trace (sampled flag honored)
                    with tracer.span(method_name,
                                     traceparent=request.headers.get(
                                         "traceparent")):
                        result = fn(component, payload)
                        if asyncio.iscoroutine(result):
                            result = await result
                metrics.observe_api_call(method_name, "200", time.perf_counter() - t0)
                return _respond(request, result)
            except Exception as e:
                code = str(getattr(e, "status_code", 500))
                metrics.observe_api_call(method_name, code, time.perf_counter() - t0)
                return error_response(e)
            finally:
                admission.release()

        return handle

    msg = SeldonMessage.from_dict
    lst = SeldonMessageList.from_dict
    fbk = Feedback.from_dict

    def fb_with_unit(comp, f):
        return dispatch.send_feedback(comp, f, unit_id=unit_id or None)

    for path, fn, parser, name in [
        ("/predict", dispatch.predict, msg, "predict"),
        ("/api/v0.1/predictions", dispatch.predict, msg, "predict"),
        ("/transform-input", dispatch.transform_input, msg, "transform_input"),
        ("/transform-output", dispatch.transform_output, msg, "transform_output"),
        ("/route", dispatch.route, msg, "route"),
        ("/aggregate", dispatch.aggregate, lst, "aggregate"),
        ("/send-feedback", fb_with_unit, fbk, "send_feedback"),
        ("/api/v0.1/feedback", fb_with_unit, fbk, "send_feedback"),
    ]:
        h = handler(fn, parser, name)
        app.router.add_post(path, h)
        app.router.add_get(path, h)

    async def health(request):
        return web.json_response({"status": "ok"})

    async def openapi(request):
        from seldon_core_tpu.transport.openapi import wrapper_spec

        return web.json_response(wrapper_spec())

    async def prom(request):
        metrics.sync_resilience(admission=admission, transport="rest")
        metrics.sync_llm(component)
        metrics.sync_controlplane(component)
        metrics.sync_framing()
        metrics.sync_tracing()
        return web.Response(body=metrics.expose(), content_type="text/plain")

    async def debug_timeline(request):
        """Recent per-request flight-recorder timelines + the scaling
        snapshot (docs/observability.md); mirrored by the gRPC
        ``Model/DebugTimeline`` rpc."""
        from seldon_core_tpu.observability.timeline import (
            parse_n, timeline_report)

        return web.json_response(
            timeline_report(component, n=parse_n(request.query.get("n"))))

    app.router.add_get("/health/status", health)
    app.router.add_get("/ready", health)
    app.router.add_get("/live", health)
    app.router.add_get("/seldon.json", openapi)
    app.router.add_get("/metrics", prom)
    app.router.add_get("/prometheus", prom)
    app.router.add_get("/debug/timeline", debug_timeline)

    if hasattr(component, "generate"):
        _add_generate_routes(app, component, metrics)
    return app


def _add_generate_routes(app: web.Application, component: Any,
                         metrics: MetricsRegistry) -> None:
    """LLM generation endpoint (POST /v1/generate). Body:
      {"prompt": str|[ids], "max_new_tokens": N, "stream": bool}  — single
          prompt; with the component's continuous_batching on, concurrent
          requests JOIN the in-flight decode batch (runtime/batcher.py)
          instead of each running a private generate(); "stream": true
          sends tokens as SSE events as they decode.
      {"prompts": [...], ...} — explicit batch, served by one generate().
    No reference counterpart (its servers are request/response classifiers);
    this is the BASELINE.json LLM stretch surface."""
    from seldon_core_tpu.runtime.batcher import get_batcher_service

    async def generate(request: web.Request) -> web.Response:
        t0 = time.perf_counter()
        # request-scoped tracing (runtime/flight.py): the inbound W3C
        # traceparent (or a fresh trace) rides into the batcher, which
        # roots the request's span tree at this ingress; the trace id is
        # stamped on the response/stream so the client can correlate
        from seldon_core_tpu.tracing import ingress_trace

        trace = ingress_trace(get_tracer(),
                              request.headers.get("traceparent"),
                              "rest:/v1/generate")
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise SeldonError("body must be a JSON object", status_code=400)
            max_new = body.get("max_new_tokens")
            # multi-tenant identity (docs/multitenancy.md): tenant + SLO
            # class ride headers (body fields win when both are present,
            # for clients that cannot set headers); the LoRA adapter name
            # is a body field like the sampling knobs. The deadline header
            # doubles as the scheduler's EDF key.
            tenant = body.get("tenant") or request.headers.get("Seldon-Tenant")
            slo_class = (body.get("slo_class")
                         or request.headers.get("Seldon-SLO-Class"))
            # a typo'd class fails loudly on EVERY path — the non-batched
            # branches below (prompts batch, per-request temperature)
            # never reach the batcher's own validation
            from seldon_core_tpu.runtime.scheduler import normalize_slo_class

            try:
                normalize_slo_class(slo_class)
            except ValueError as e:
                raise SeldonError(str(e), status_code=400)
            adapter = body.get("adapter")
            dl = deadline_from_headers(request)
            deadline_s = dl.remaining_s() if dl is not None else None
            if "prompts" in body:
                if adapter:
                    raise SeldonError(
                        "adapters serve through the continuous batch; use "
                        "single-prompt requests (the 'prompts' batch runs "
                        "a private base-model generate())", status_code=400)
                out = await asyncio.to_thread(
                    component.generate, body["prompts"], max_new_tokens=max_new,
                    temperature=body.get("temperature"), seed=body.get("seed"))
                metrics.observe_api_call("generate", "200", time.perf_counter() - t0)
                return web.json_response(out)
            prompt = body.get("prompt")
            if prompt is None:
                raise SeldonError("body needs 'prompt' or 'prompts'", status_code=400)
            # A per-request TEMPERATURE can't join a shared batch (the
            # batcher decodes every slot with the server's temperature), so
            # those requests get a private generate() — same output as with
            # batching disabled, never silently different. A per-request
            # SEED now joins fine: each slot carries its own device rng on
            # the exact generate(seed=...) chain (runtime/batcher.py,
            # parity-tested in tests/test_batcher_pipeline.py) — UNLESS the
            # request would not fit the fixed slot cache (truncated prompt /
            # clipped budget), where only the private per-request-sized
            # generate() can honor the seeded-reproducibility contract.
            custom_sampling = "temperature" in body
            if adapter and custom_sampling:
                raise SeldonError(
                    "per-request temperature cannot join the shared batch, "
                    "and adapters only serve through it — drop one",
                    status_code=400)
            svc = None if custom_sampling else get_batcher_service(component)
            if svc is None and adapter:
                # adapters serve ONLY through a batcher (the adapted
                # compiled programs live there); a component without
                # continuous batching still serves them via the shared
                # 1-slot streaming service
                from seldon_core_tpu.runtime.batcher import ensure_stream_service

                svc = await asyncio.to_thread(ensure_stream_service, component)
            if svc is not None and "seed" in body and not await asyncio.to_thread(
                    svc.batcher.accommodates, prompt, max_new):
                if adapter:
                    raise SeldonError(
                        "seeded adapted prompt exceeds the batcher slot "
                        "cache and would not reproduce; raise "
                        "continuous_batching_max_len", status_code=400)
                svc = None
            stream = bool(body.get("stream"))
            decode = getattr(component, "_tokenizer", None)

            info: dict = {}
            if not stream:
                if svc is not None:
                    toks = await svc.submit(prompt, max_new, info=info,
                                            seed=body.get("seed"),
                                            trace=trace, tenant=tenant,
                                            slo_class=slo_class,
                                            adapter=adapter,
                                            deadline_s=deadline_s)
                else:
                    out = await asyncio.to_thread(
                        component.generate, [prompt], max_new_tokens=max_new,
                        temperature=body.get("temperature"), seed=body.get("seed"))
                    metrics.observe_api_call("generate", "200",
                                             time.perf_counter() - t0)
                    resp_body = {"tokens": out["tokens"][0],
                                 "text": out["texts"][0]}
                    if trace is not None:
                        # private-generate fallback: no flight recorder ran,
                        # but the client still gets a stable correlation id
                        resp_body["trace_id"] = trace.trace_id
                    return web.json_response(resp_body)
                text = decode.decode(toks) if (decode is not None
                                               and isinstance(prompt, str)) else None
                metrics.observe_api_call("generate", "200", time.perf_counter() - t0)
                out = {"tokens": toks, "text": text}
                if trace is not None:
                    out["trace_id"] = trace.trace_id
                if info.get("truncated_prompt"):
                    out["truncated_prompt"] = info["truncated_prompt"]
                return web.json_response(out)

            if custom_sampling:
                raise SeldonError(
                    "streaming with per-request temperature is not "
                    "supported; set it on the server", status_code=400)
            if "seed" in body:
                # streaming has no generate() fallback, so a seeded prompt
                # that exceeds the slot cache (truncation / budget clip)
                # cannot honor the reproducibility contract — reject before
                # the SSE response starts
                from seldon_core_tpu.runtime.batcher import ensure_stream_service

                s_svc = svc if svc is not None else await asyncio.to_thread(
                    ensure_stream_service, component)
                if not await asyncio.to_thread(
                        s_svc.batcher.accommodates, prompt, max_new):
                    raise SeldonError(
                        "seeded streaming prompt exceeds the batcher slot "
                        "cache and would not reproduce generate(seed=...); "
                        "raise continuous_batching_max_len or drop stream",
                        status_code=400)
                svc = s_svc

            # SSE streaming: one event per token as the shared batch decodes
            headers = {
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache"}
            if trace is not None:
                # the stream's trace id, visible BEFORE the first token:
                # a client filing "this stream stalled" hands the operator
                # the exact /debug/timeline + Jaeger key
                headers["X-Trace-Id"] = trace.trace_id
            resp = web.StreamResponse(headers=headers)
            await resp.prepare(request)
            loop = asyncio.get_running_loop()
            q: asyncio.Queue = asyncio.Queue()

            def on_token(tok):
                loop.call_soon_threadsafe(q.put_nowait, tok)

            if svc is None:
                # no batcher configured: stream via a shared 1-slot service
                from seldon_core_tpu.runtime.batcher import ensure_stream_service

                svc = await asyncio.to_thread(ensure_stream_service, component)
            fut = asyncio.ensure_future(svc.submit(prompt, max_new,
                                                   on_token=on_token,
                                                   info=info,
                                                   seed=body.get("seed"),
                                                   trace=trace,
                                                   tenant=tenant,
                                                   slo_class=slo_class,
                                                   adapter=adapter,
                                                   deadline_s=deadline_s))
            try:
                # Wait on the queue AND the future: a submit that fails before
                # any token (closed batcher, bad prompt) never sends the None
                # sentinel, and waiting only on the queue would hang the
                # connection forever.
                async def write_tok(tok):
                    if isinstance(tok, ResumeMarker):
                        # fleet recovery re-attached this stream after a
                        # replica death: an in-band marker, never a token
                        # (at-most-once contract, docs/resilience.md)
                        await resp.write(
                            f"data: {json.dumps({'resumed': True, 'tokens_delivered': tok.tokens_delivered})}\n\n".encode())
                        return
                    piece = (decode.decode([tok]) if decode is not None
                             and isinstance(prompt, str) else None)
                    await resp.write(
                        f"data: {json.dumps({'token': tok, 'text': piece})}\n\n".encode())

                while True:
                    getter = asyncio.ensure_future(q.get())
                    done, _ = await asyncio.wait(
                        {getter, fut}, return_when=asyncio.FIRST_COMPLETED)
                    if getter in done:
                        tok = getter.result()
                        if tok is None:
                            break
                        await write_tok(tok)
                        continue
                    # fut resolved first. The old code took AT MOST ONE
                    # leftover token here, so tokens enqueued between the
                    # future resolving and the next loop turn were silently
                    # dropped from the stream (they only reappeared in the
                    # done event's full token list) — and cancelling the
                    # getter could swallow a token it had already claimed.
                    # Recover the getter's claim, then drain the queue FULLY
                    # (the None sentinel, if queued, still terminates).
                    getter.cancel()
                    try:
                        tok = await getter
                    except asyncio.CancelledError:
                        tok = False  # cancelled clean: claimed nothing
                    leftovers = [] if tok is False else [tok]
                    while True:
                        try:
                            leftovers.append(q.get_nowait())
                        except asyncio.QueueEmpty:
                            break
                    for tok in leftovers:
                        if tok is None:
                            break
                        await write_tok(tok)
                    break
                toks = await fut
                text = decode.decode(toks) if (decode is not None
                                               and isinstance(prompt, str)) else None
                done_evt = {"done": True, "tokens": toks, "text": text}
                if trace is not None:
                    done_evt["trace_id"] = trace.trace_id
                if info.get("truncated_prompt"):
                    done_evt["truncated_prompt"] = info["truncated_prompt"]
                await resp.write(
                    f"data: {json.dumps(done_evt)}\n\n".encode())
                await resp.write_eof()
                metrics.observe_api_call("generate", "200", time.perf_counter() - t0)
                return resp
            except (ConnectionError, ConnectionResetError, asyncio.CancelledError):
                # client went away mid-stream: stop awaiting (the admitted
                # slot still decodes out its bounded max_new tokens)
                fut.cancel()
                raise
            except Exception as e:
                # response already prepared: a fresh error response can't be
                # sent; log via metrics, surface what we can, stop decoding
                fut.cancel()
                metrics.observe_api_call(
                    "generate", str(getattr(e, "status_code", 500)),
                    time.perf_counter() - t0)
                try:
                    await resp.write(
                        f"data: {json.dumps({'error': str(e)})}\n\n".encode())
                    await resp.write_eof()
                except Exception:
                    pass
                return resp
        except Exception as e:
            code = str(getattr(e, "status_code", 500))
            metrics.observe_api_call("generate", code, time.perf_counter() - t0)
            if isinstance(e, ShedError):
                # page-exhaustion sheds surface here (the batcher's own
                # 503 path): render the Retry-After header so clients see
                # the backlog-derived backoff, not just the status body
                return shed_response(e)
            return error_response(e)

    app.router.add_post("/v1/generate", generate)


# ---------------------------------------------------------------------------
# Engine app: whole predictor graph in-process
# ---------------------------------------------------------------------------

def make_engine_app(
    engine: Any,
    metrics: Optional[MetricsRegistry] = None,
    admission: Optional[AdmissionController] = None,
    annotations: Optional[dict] = None,
) -> web.Application:
    """engine: seldon_core_tpu.runtime.engine.GraphEngine (or compatible,
    e.g. the batched engine wrapper).

    ``admission`` bounds concurrent predictions (overflow sheds with 503 +
    Retry-After); defaults from annotations/env via
    AdmissionController.from_annotations — disabled unless configured."""
    app = web.Application(client_max_size=1 << 30)
    metrics = metrics or MetricsRegistry()
    admission = admission or AdmissionController.from_annotations(annotations)
    from seldon_core_tpu.observability.timeline import wire_retry_after

    wire_retry_after(admission, engine=engine)
    tracer = get_tracer()
    state = {"paused": False, "ready": True}
    app[web.AppKey("state", dict)] = state

    # request/response pair logging — the reference's stdout logging
    # (log.requests/log.responses, PredictionService.java:62-76,122-128) and
    # CloudEvents POST to the request logger (:162-191)
    log_requests = os.environ.get("SELDON_LOG_REQUESTS", "") == "1"
    log_responses = os.environ.get("SELDON_LOG_RESPONSES", "") == "1"
    logger_url = os.environ.get("REQUEST_LOGGER_URL", "")
    # strong refs so fire-and-forget log tasks can't be GC'd mid-flight
    log_tasks: set = set()
    logger_session: list = [None]  # lazily-created shared ClientSession

    async def _log_pair(req_dict, resp_dict):
        if log_requests:
            print(json.dumps({"request": req_dict}), flush=True)
        if log_responses:
            print(json.dumps({"response": resp_dict}), flush=True)
        if logger_url:
            try:
                import aiohttp

                if logger_session[0] is None or logger_session[0].closed:
                    logger_session[0] = aiohttp.ClientSession(
                        timeout=aiohttp.ClientTimeout(total=2)
                    )
                headers = {
                    "CE-Type": "seldon.message.pair",
                    "CE-Source": "seldon-engine-tpu",
                    "CE-SDep": os.environ.get("DEPLOYMENT_NAME", ""),
                    "CE-RequestId": (resp_dict.get("meta") or {}).get("puid", ""),
                }
                async with logger_session[0].post(
                    logger_url,
                    json={"request": req_dict, "response": resp_dict},
                    headers=headers,
                ) as resp:
                    await resp.read()
            except Exception as e:  # logging must never fail the request
                logging.getLogger(__name__).warning("request-logger post failed: %s", e)

    def _spawn_log(req_dict, resp_dict):
        task = asyncio.ensure_future(_log_pair(req_dict, resp_dict))
        log_tasks.add(task)
        task.add_done_callback(log_tasks.discard)

    async def predictions(request: web.Request) -> web.Response:
        if state["paused"]:
            return web.json_response(
                {"status": {"code": 503, "info": "paused", "status": "FAILURE"}}, status=503
            )
        t0 = time.perf_counter()
        try:
            # admission BEFORE parsing: shedding must stay cheap when the
            # server is already saturated
            await admission.acquire()
        except ShedError as e:
            metrics.observe_api_call("predictions", "503", time.perf_counter() - t0)
            return shed_response(e)
        try:
            deadline = deadline_from_headers(request)
            if request.content_type == CONTENT_TYPE_FRAME:
                body = None
                msg = await parse_framed_message(request)
            else:
                body = await parse_request(request)
                msg = SeldonMessage.from_dict(body)
            with deadline_scope(deadline):
                with tracer.span("predictions",
                                 traceparent=request.headers.get(
                                     "traceparent")):
                    out = await engine.predict(msg)
                d = current_deadline()
                if d is not None:
                    metrics.observe_remaining_budget(d.remaining_s())
            metrics.observe_prediction(engine, out, time.perf_counter() - t0)
            if log_requests or log_responses or logger_url:
                # framed requests have no JSON body; the logger pair pays
                # the to_dict() tax only when logging is actually on
                _spawn_log(body if body is not None else msg.to_dict(),
                           out.to_dict())
            return _respond(request, out)
        except Exception as e:
            code = getattr(e, "status_code", 500)
            if code == 504:
                metrics.observe_deadline_exceeded("rest")
            metrics.observe_api_call("predictions", str(code), time.perf_counter() - t0)
            if isinstance(e, ShedError):
                return shed_response(e)
            return error_response(e)
        finally:
            admission.release()

    async def feedback(request: web.Request) -> web.Response:
        t0 = time.perf_counter()
        try:
            body = await parse_request(request)
            fb = Feedback.from_dict(body)
            with tracer.span("feedback"):
                out = await engine.send_feedback(fb)
            metrics.observe_feedback(fb)
            metrics.observe_api_call("feedback", "200", time.perf_counter() - t0)
            return _json(out)
        except Exception as e:
            metrics.observe_api_call("feedback", str(getattr(e, "status_code", 500)), time.perf_counter() - t0)
            return error_response(e)

    async def ready(request):
        if state["ready"] and not state["paused"]:
            return web.Response(text="ready")
        return web.Response(status=503, text="not ready")

    async def live(request):
        return web.Response(text="live")

    async def ping(request):
        return web.Response(text="pong")

    async def pause(request):
        state["paused"] = True
        return web.Response(text="paused")

    async def unpause(request):
        state["paused"] = False
        return web.Response(text="unpaused")

    async def prom(request):
        metrics.sync_resilience(engine=engine, admission=admission, transport="rest")
        for comp in getattr(engine, "_components", {}).values():
            metrics.sync_llm(comp)
        metrics.sync_controlplane(engine)
        metrics.sync_framing()
        metrics.sync_tracing()
        return web.Response(body=metrics.expose(), content_type="text/plain")

    async def debug_timeline(request):
        """Per-component flight-recorder timelines + scaling snapshots for
        the whole graph (docs/observability.md)."""
        from seldon_core_tpu.observability.timeline import (
            parse_n, timeline_report)

        n = parse_n(request.query.get("n"))
        return web.json_response({
            name: timeline_report(comp, n=n)
            for name, comp in getattr(engine, "_components", {}).items()
        })

    async def openapi(request):
        from seldon_core_tpu.transport.openapi import engine_spec

        return web.json_response(engine_spec())

    profile_state = {"active": False}

    async def profile(request):
        """Device-level profiling (SURVEY.md §5: the XLA/jax-profiler half of
        the tracing story): capture a jax.profiler trace for ?seconds=N and
        write it under SELDON_PROFILE_DIR. Gated by that env var — profiling
        allocates and serializes device state, so it is opt-in."""
        base = os.environ.get("SELDON_PROFILE_DIR", "")
        if not base:
            return web.json_response(
                {"status": {"code": 403, "info": "set SELDON_PROFILE_DIR to enable"}},
                status=403,
            )
        if profile_state["active"]:
            return web.json_response(
                {"status": {"code": 409, "info": "profile already running"}}, status=409
            )
        import math

        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            seconds = 2.0
        if not (math.isfinite(seconds) and 0 < seconds <= 60):
            seconds = 2.0

        import jax

        out_dir = os.path.join(base, f"trace_{int(time.time())}")
        profile_state["active"] = True
        started = False
        try:
            jax.profiler.start_trace(out_dir)
            started = True
            await asyncio.sleep(seconds)
        finally:
            profile_state["active"] = False
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception:  # double-stop on teardown races
                    logger.exception("stop_trace failed")
        return web.json_response({"trace_dir": out_dir, "seconds": seconds})

    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/predict", predictions)
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_post("/send-feedback", feedback)
    app.router.add_get("/ready", ready)
    app.router.add_get("/live", live)
    app.router.add_get("/ping", ping)
    app.router.add_post("/pause", pause)
    app.router.add_post("/unpause", unpause)
    app.router.add_get("/pause", pause)
    app.router.add_get("/unpause", unpause)
    app.router.add_get("/metrics", prom)
    app.router.add_get("/prometheus", prom)
    app.router.add_get("/seldon.json", openapi)
    app.router.add_get("/debug/timeline", debug_timeline)
    app.router.add_post("/profile", profile)
    return app


def serve(app: web.Application, host: str = "0.0.0.0", port: int = 5000) -> None:
    web.run_app(app, host=host, port=port, print=None)
