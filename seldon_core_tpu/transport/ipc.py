"""IPC serving: multi-process frontends over the native staging ring.

The reference scales its Python wrapper with gunicorn workers, each paying
full JSON->proto->ndarray codec plus a socket hop to the engine pod
(SURVEY.md §3.1). Here transport workers (REST/gRPC frontends, or any client
process) stage requests into the shared-memory ring (native/ring.cc) and the
single device-owning engine process drains them in batches — the TPU-native
layout, since exactly one process should own the TPU chip while N CPU-bound
frontends decode payloads.

Frame format (bytes, little-endian):
    u16 worker_id | u32 request_id | u8 kind | JSON payload
kind: 0 = predict(SeldonMessage), 1 = feedback(Feedback).
Responses travel back on a per-worker ring as
    u32 request_id | u8 status | JSON payload   (status 0 = ok, 1 = error)
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import struct
import time
from typing import Any, Dict, Optional

from seldon_core_tpu.contracts.payload import Feedback, SeldonError, SeldonMessage
from seldon_core_tpu.native import PayloadTooLarge, SharedRing

logger = logging.getLogger(__name__)

_REQ_HEADER = struct.Struct("<HIB")
_RESP_HEADER = struct.Struct("<IB")

KIND_PREDICT = 0
KIND_FEEDBACK = 1


def _error_body(info: str, reason: str, code: int = 500) -> bytes:
    """Error frame body (Status contract shape, contracts/payload.py Status):
    clients parse status.info/status.reason; HTTP frontends use status.code."""
    return json.dumps(
        {"status": {"code": code, "info": info, "reason": reason, "status": "FAILURE"}}
    ).encode()


def request_ring_path(base: str) -> str:
    return base + ".req"


def response_ring_path(base: str, worker_id: int) -> str:
    return f"{base}.resp.{worker_id}"


class IPCEngineServer:
    """Drains the request ring into the in-process GraphEngine."""

    def __init__(
        self,
        engine: Any,
        base_path: str,
        n_workers: int,
        capacity: int = 1024,
        slot_size: int = 1 << 20,
        batch: int = 64,
    ):
        self.engine = engine
        self.base_path = base_path
        self.batch = batch
        # sweep temp files orphaned by a previous creator killed mid-create;
        # glob per exact ring path so a sibling base sharing this prefix
        # (e.g. "<base>2") is never touched mid-create
        import glob

        ring_paths = [request_ring_path(base_path)] + [
            response_ring_path(base_path, w) for w in range(n_workers)
        ]
        for stale in (t for p in ring_paths for t in glob.glob(p + ".tmp.*")):
            try:
                os.unlink(stale)
            except OSError:
                pass
        self.req_ring = SharedRing(
            request_ring_path(base_path), capacity=capacity, slot_size=slot_size, create=True
        )
        self.resp_rings = {
            w: SharedRing(
                response_ring_path(base_path, w), capacity=capacity, slot_size=slot_size,
                create=True,
            )
            for w in range(n_workers)
        }
        self._stop = False

    async def serve_forever(self, poll_wait_s: float = 0.05) -> None:
        while not self._stop:
            frames = await asyncio.to_thread(self.req_ring.pop_batch, self.batch, poll_wait_s)
            if not frames:
                continue
            await asyncio.gather(*[self._handle(f) for f in frames])

    def stop(self) -> None:
        self._stop = True

    async def _handle(self, frame: bytes) -> None:
        # No failure below may escape: serve_forever gathers these, so one bad
        # frame / oversized body / stalled worker would kill serving for all
        # workers.
        try:
            worker_id, req_id, kind = _REQ_HEADER.unpack_from(frame)
        except struct.error:
            logger.error("dropping malformed IPC frame (%d bytes)", len(frame))
            return
        try:
            payload = json.loads(frame[_REQ_HEADER.size:])
            if kind == KIND_PREDICT:
                out = await self.engine.predict(SeldonMessage.from_dict(payload))
            elif kind == KIND_FEEDBACK:
                out = await self.engine.send_feedback(Feedback.from_dict(payload))
            else:
                raise SeldonError(f"unknown IPC kind {kind}")
            body = json.dumps(out.to_dict()).encode()
            status = 0
        except Exception as e:
            body = _error_body(
                str(e),
                getattr(e, "reason", "ENGINE_ERROR"),
                int(getattr(e, "status_code", 500)),
            )
            status = 1
        ring = self.resp_rings.get(worker_id)
        if ring is None:
            logger.error("response for unknown worker %d dropped", worker_id)
            return
        try:
            await asyncio.to_thread(
                ring.push_wait, _RESP_HEADER.pack(req_id, status) + body, 5.0
            )
        except PayloadTooLarge:
            err = _error_body(
                f"response too large for IPC slot "
                f"({len(body)} bytes > {ring.slot_size - _RESP_HEADER.size})",
                "RESPONSE_TOO_LARGE",
                500,
            )
            try:
                await asyncio.to_thread(ring.push_wait, _RESP_HEADER.pack(req_id, 1) + err, 5.0)
            except Exception:
                logger.exception("dropping oversized response %d for worker %d", req_id, worker_id)
        except Exception:
            logger.exception("dropping response %d for stalled worker %d", req_id, worker_id)


class IPCClient:
    """Worker-side handle: send a request frame, wait for the matching
    response (out-of-order safe — responses for other requests from this
    worker are parked)."""

    _PARKED_MAX = 1024

    def __init__(self, base_path: str, worker_id: int, timeout_s: float = 30.0):
        self.worker_id = int(worker_id)
        self.timeout_s = timeout_s
        self.req_ring = SharedRing(request_ring_path(base_path), create=False)
        self.resp_ring = SharedRing(response_ring_path(base_path, worker_id), create=False)
        self._next_id = 0
        # rid -> (arrival time, frame). Bounded: late responses to requests
        # that already timed out would otherwise accumulate forever, and after
        # u32 request-id wraparound a stale frame could match a live request.
        self._parked: Dict[int, tuple] = {}

    def _prune_parked(self) -> None:
        now = time.monotonic()
        stale = [rid for rid, (t, _) in self._parked.items() if now - t > self.timeout_s]
        for rid in stale:
            del self._parked[rid]
        while len(self._parked) > self._PARKED_MAX:
            oldest = min(self._parked, key=lambda rid: self._parked[rid][0])
            del self._parked[oldest]

    def _call(self, kind: int, payload: Dict[str, Any]) -> Dict[str, Any]:
        req_id = self._next_id
        self._next_id = (self._next_id + 1) & 0xFFFFFFFF
        frame = _REQ_HEADER.pack(self.worker_id, req_id, kind) + json.dumps(payload).encode()
        self.req_ring.push_wait(frame, timeout_s=self.timeout_s)

        deadline = time.monotonic() + self.timeout_s
        while True:
            if req_id in self._parked:
                raw = self._parked.pop(req_id)[1]
            else:
                raw = self.resp_ring.pop()
                if raw is None:
                    if time.monotonic() > deadline:
                        self._prune_parked()
                        raise TimeoutError(f"IPC response {req_id} timed out")
                    time.sleep(0.0002)
                    continue
            rid, status = _RESP_HEADER.unpack_from(raw)
            body = json.loads(raw[_RESP_HEADER.size:])
            if rid != req_id:
                self._parked[rid] = (time.monotonic(), raw)
                self._prune_parked()
                continue
            if status != 0:
                raise SeldonError(
                    body.get("status", {}).get("info", "IPC engine error"),
                    reason=body.get("status", {}).get("reason", "ENGINE_ERROR"),
                    status_code=500,
                )
            return body

    def predict(self, message: SeldonMessage) -> SeldonMessage:
        return SeldonMessage.from_dict(self._call(KIND_PREDICT, message.to_dict()))

    def send_feedback(self, feedback: Feedback) -> SeldonMessage:
        return SeldonMessage.from_dict(self._call(KIND_FEEDBACK, feedback.to_dict()))

    def close(self) -> None:
        self.req_ring.close()
        self.resp_ring.close()


def cleanup_rings(base_path: str, n_workers: int) -> None:
    import glob

    paths = [request_ring_path(base_path)] + [
        response_ring_path(base_path, w) for w in range(n_workers)
    ]
    # stale .tmp.<pid> files left by a creator killed between open and rename
    paths += [t for p in paths for t in glob.glob(p + ".tmp.*")]
    for p in paths:
        try:
            os.unlink(p)
        except OSError:
            pass
