"""IPC serving: multi-process frontends over the native staging ring.

The reference scales its Python wrapper with gunicorn workers, each paying
full JSON->proto->ndarray codec plus a socket hop to the engine pod
(SURVEY.md §3.1). Here transport workers (REST/gRPC frontends, or any client
process) stage requests into the shared-memory ring (native/ring.cc) and the
single device-owning engine process drains them in batches — the TPU-native
layout, since exactly one process should own the TPU chip while N CPU-bound
frontends decode payloads.

Frame format (bytes, little-endian):
    u16 worker_id | u32 request_id | u8 kind | payload
kind: 0 = predict(SeldonMessage JSON), 1 = feedback(Feedback JSON),
      2 = device-model call (binary tensor, no JSON):
          u16 model_id | u8 method (0=predict, 1=transform_input)
          | u8 n_chain_extra | n_chain_extra x (u16 model, u8 method)
          | u8 ndim | u32 dims[ndim] | f64 data
          (chained stages run sequentially in one round-trip; the response
          fragment is then a JSON array, one fragment per stage).
Responses travel back on a per-worker ring as
    u32 request_id | u8 status | body
status 0 JSON kinds: JSON payload. status 0 model kind:
    u8 dtype (0=f32,1=f64 — the model's output dtype, data itself is f64)
    | u8 ndim | u32 dims[ndim] | u32 json_len
    | json ({"names": [...], "tags": {...}, "metrics": [...]}) | f64 data.
status 1 (any kind): JSON Status body.

The kind-2 path is how the native edge serves graphs with real models at
native speed (runtime/edgeprogram.py DEVICE_MODEL): the edge executes the
graph — routing, combining, meta — in C++ and ships ONLY the tensor here;
this process owns the device and micro-batches concurrent requests into one
jitted call (requests for the same model with the same feature shape are
stacked along axis 0 — the serving-side continuous batching the reference's
replica fan-out can't do).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import struct
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from seldon_core_tpu.contracts.payload import Feedback, SeldonError, SeldonMessage
from seldon_core_tpu.native import PayloadTooLarge, RingFull, SharedRing

logger = logging.getLogger(__name__)

_REQ_HEADER = struct.Struct("<HIB")
_RESP_HEADER = struct.Struct("<IB")
# model_id, method, n_chain_extra, then n_chain_extra x (u16 model, u8
# method) chained stages, then u8 ndim + u32 dims. A chained frame runs its
# stages sequentially (stage i+1 consumes stage i's output) in ONE ring
# round-trip — the transform->model hot path costs one RTT, not one per hop.
_MODEL_REQ = struct.Struct("<HBB")
_CHAIN_STAGE = struct.Struct("<HB")

METHOD_PREDICT = 0
METHOD_TRANSFORM_INPUT = 1

KIND_PREDICT = 0
KIND_FEEDBACK = 1
KIND_MODEL = 2
# Full-proto frames from the edge's gRPC listener: payload is the raw
# SeldonMessage/Feedback proto; ok responses carry proto bytes back, error
# responses carry u8 grpc-status-code + utf8 message.
KIND_PROTO_PREDICT = 3
KIND_PROTO_FEEDBACK = 4


class ModelExecutor:
    """Executes kind-2 device-model frames for the native edge.

    Holds the graph's resolvable model components (modelId order from
    compile_edge_program). Frames arriving in one drain batch for the same
    model with the same feature shape are stacked into ONE predict call —
    the device sees large batches even when every client sends batch-1."""

    def __init__(self, models):
        self.models = list(models)
        self.batched_calls = 0
        self.batched_rows = 0
        # cap stacking at the largest compiled bucket so a burst can never
        # trigger an unseen-batch-shape XLA compile mid-traffic
        self.max_rows = [
            int(max(getattr(m, "batch_buckets", ()) or (256,))) for m in self.models
        ]
        # Response meta fragments (names/tags/metrics JSON) depend only on
        # the output shape for components that don't override tags()/
        # metrics() — cache the encoded bytes per (model, ndim, cols)
        # instead of re-deriving + json.dumps-ing on every request.
        from seldon_core_tpu.components.component import _has_impl

        self._frag_static = [
            not (_has_impl(m, "tags") or _has_impl(m, "metrics"))
            for m in self.models
        ]
        # Dynamic-fragment components that can attribute tags/metrics to a
        # row range (row_slice protocol, e.g. outlier detectors): stacked
        # into one scoring call with per-frame row attribution instead of
        # running solo per request.
        self._row_sliceable = [
            callable(getattr(m, "row_slice", None)) for m in self.models
        ]
        self._frag_cache: Dict[tuple, bytes] = {}

    def warm(self) -> None:
        """Compile every (bucket, feature-shape) pair up front. Without this
        a load burst walks the bucket ladder one compile at a time while
        requests queue behind each compile (measured: a 10s load window
        collapsed to ~94 rps from compile storms)."""
        for i, component in enumerate(self.models):
            shape = None
            cfg = getattr(component, "_config", None)
            if isinstance(cfg, dict):
                shape = cfg.get("input_shape")
            if shape is None:
                continue
            dtype = np.dtype(getattr(component, "input_dtype", "float32"))
            for b in sorted(set(getattr(component, "batch_buckets", ()) or (1,))):
                if b > self.max_rows[i]:
                    continue
                try:
                    component.predict(np.zeros((b, *shape), dtype), [], meta={})
                except Exception:
                    logger.exception("warmup failed for model %d bucket %d", i, b)
                    break

    # ---- frame codecs -------------------------------------------------
    @staticmethod
    def parse_frame(payload: bytes):
        """Returns (stages, arr): stages = ((model_id, method), ...) — one
        entry for plain frames, several for fused chains."""
        model_id, method, n_extra = _MODEL_REQ.unpack_from(payload)
        off = _MODEL_REQ.size
        stages = [(model_id, method)]
        for _ in range(n_extra):
            m, meth = _CHAIN_STAGE.unpack_from(payload, off)
            stages.append((m, meth))
            off += _CHAIN_STAGE.size
        ndim = payload[off]
        off += 1
        dims = struct.unpack_from(f"<{ndim}I", payload, off)
        off += 4 * ndim
        n = 1
        for d in dims:
            n *= d
        arr = np.frombuffer(payload, dtype="<f8", count=n, offset=off).reshape(dims)
        return tuple(stages), arr

    @staticmethod
    def _ok_response(req_id: int, arr: np.ndarray, frag: bytes) -> bytes:
        dtype_code = 1 if arr.dtype == np.float64 else 0
        out = arr.astype("<f8", copy=False)
        head = _RESP_HEADER.pack(req_id, 0) + bytes([dtype_code, out.ndim])
        head += struct.pack(f"<{out.ndim}I", *out.shape)
        head += struct.pack("<I", len(frag)) + frag
        return head + out.tobytes()

    def _fragment_for(self, model_id: int, method: int, component,
                      result: np.ndarray) -> bytes:
        key = (model_id, method, result.ndim,
               int(result.shape[1]) if result.ndim > 1 else -1)
        if self._frag_static[model_id]:
            cached = self._frag_cache.get(key)
            if cached is not None:
                return cached
        from seldon_core_tpu.components.component import (
            client_class_names,
            client_custom_metrics,
            client_custom_tags,
            client_feature_names,
        )

        fragment: Dict[str, Any] = {}
        if method == METHOD_TRANSFORM_INPUT:
            # request-flow response: engine construct_response(is_request=True)
            names = client_feature_names(component, [])
        else:
            names = client_class_names(component, result)
        if names:
            fragment["names"] = list(names)
        tags = client_custom_tags(component)
        if tags:
            fragment["tags"] = tags
        metrics = client_custom_metrics(component)
        if metrics:
            fragment["metrics"] = metrics
        frag = json.dumps(fragment).encode() if fragment else b""
        if self._frag_static[model_id]:
            self._frag_cache[key] = frag
        return frag

    def _row_fragment(self, method: int, component, result: np.ndarray,
                      lo: int, hi: int) -> bytes:
        """Fragment for rows [lo, hi) of a stacked call on a row-sliceable
        dynamic component — same encoded shape as _fragment_for, but tags/
        metrics come from the component's per-row attribution."""
        from seldon_core_tpu.components.component import (
            client_class_names,
            client_feature_names,
        )

        fragment: Dict[str, Any] = {}
        if method == METHOD_TRANSFORM_INPUT:
            names = client_feature_names(component, [])
        else:
            names = client_class_names(component, result)
        if names:
            fragment["names"] = list(names)
        tags, mets = component.row_slice(lo, hi)
        if tags:
            fragment["tags"] = tags
        if mets:
            fragment["metrics"] = mets
        return json.dumps(fragment).encode() if fragment else b""

    @staticmethod
    def _err_response(req_id: int, info: str, reason: str, code: int = 500) -> bytes:
        return _RESP_HEADER.pack(req_id, 1) + _error_body(info, reason, code)

    # ---- execution ----------------------------------------------------
    def _call_stacked(self, call, items, max_rows, finish, fail, finish_chunk=None,
                      set_segments=None):
        """Shared micro-batch machinery: ``items`` = [(key, arr)] with equal
        trailing shapes; concatenates into chunks of <= max_rows rows, one
        call per chunk, splits results back per key. Both the plain frame
        path and the fused-chain path use THIS loop so stacking policy,
        the row-split guard, and accounting can never diverge.

        ``finish_chunk(chunk, result)``, when given, may consume a whole
        stacked chunk at once (the C bulk-response path); returning False
        falls back to per-frame ``finish``, and returning a set of keys
        marks those frames as already answered (partial bulk push) so only
        the REMAINING frames take the per-frame path.

        ``set_segments(counts)``, when given, is told each chunk's
        per-frame row counts right before the stacked call — the windowed
        components' stack_segments protocol (window framing must not
        straddle request boundaries; analytics/outliers.py Seq2Seq)."""
        idx = 0
        while idx < len(items):
            chunk = []
            rows = 0
            while idx < len(items):
                _, a = items[idx]
                if chunk and rows + a.shape[0] > max_rows:
                    break
                chunk.append(items[idx])
                rows += a.shape[0]
                idx += 1
            answered: set = set()  # keys already responded to — a late
            # exception must not fail() these (duplicate responses)
            try:
                if len(chunk) == 1:
                    key, arr = chunk[0]
                    finish(key, np.asarray(call(arr)))
                    continue
                stacked = np.concatenate([a for _, a in chunk], axis=0)
                if set_segments is not None:
                    set_segments([a.shape[0] for _, a in chunk])
                result = np.asarray(call(stacked))
                if result.shape[:1] != stacked.shape[:1]:
                    raise SeldonError(
                        "device model output rows do not match stacked "
                        "input rows; cannot split a micro-batch")
                self.batched_calls += 1
                self.batched_rows += stacked.shape[0]
                handled = finish_chunk(chunk, result) if finish_chunk else False
                if handled is True:
                    continue
                if isinstance(handled, set):
                    answered |= handled
                offset = 0
                for key, a in chunk:
                    if key not in answered:
                        finish(key, result[offset:offset + a.shape[0]])
                        answered.add(key)
                    offset += a.shape[0]
            except Exception as e:
                for key, _ in chunk:
                    if key not in answered:
                        fail(key, e)

    def _chunk_pusher(self, model_id: int, method: int, component, rings):
        """finish_chunk callback for _call_stacked: pushes a whole stacked
        chunk's responses through scr_push_model_resps — the C side frames
        each response directly into its ring slot, replacing per-frame
        struct packs + bytes concats + one FFI push per frame. Returns None
        when the bulk path doesn't apply (no rings / dynamic fragment)."""
        if not rings or not self._frag_static[model_id]:
            return None

        def finish_chunk(chunk, result) -> bool:
            if result.ndim < 2 or not (
                np.issubdtype(result.dtype, np.number) or result.dtype == np.bool_
            ):
                return False  # per-frame path handles odd shapes/dtypes
            workers = {key[0] for key, _ in chunk}
            if any(w not in rings for w in workers):
                return False
            frag = self._fragment_for(model_id, method, component, result)
            dtype_code = 1 if result.dtype == np.float64 else 0
            data = np.ascontiguousarray(result, dtype="<f8")
            row_nvals = int(np.prod(result.shape[1:], dtype=np.int64))
            tail = result.shape[1:]
            by_worker: Dict[int, list] = {}
            off = 0
            for (worker_id, req_id), a in chunk:
                by_worker.setdefault(worker_id, []).append(
                    (req_id, off, a.shape[0]))
                off += a.shape[0]
            pushed: set = set()  # worker_ids whose batch fully pushed
            for worker_id, entries in by_worker.items():
                try:
                    rings[worker_id].push_model_resps(
                        [e[0] for e in entries], [e[1] for e in entries],
                        [e[2] for e in entries], data, row_nvals, tail, frag,
                        dtype_code)
                    pushed.add(worker_id)
                except PayloadTooLarge:
                    # Rings can have differing slot sizes, so one worker of
                    # a multi-worker chunk can overflow while the rest fit.
                    # push_model_resps pre-checks sizes per call, so the
                    # failing worker pushed NOTHING — its frames are safe to
                    # re-answer via the per-frame fallback, as are those of
                    # workers not yet attempted. Report only the
                    # already-pushed workers' frames as handled.
                    if not pushed:
                        return False  # nothing pushed: plain per-frame path
                    logger.warning(
                        "bulk response overflow on worker %d after partial "
                        "multi-worker push; remaining frames take the "
                        "per-frame fallback", worker_id)
                    return {key for key, _ in chunk if key[0] in pushed}
                except RingFull:
                    # Worker %d's ring jammed for the full timeout — a
                    # partial per-WORKER push is possible here, so answering
                    # its frames again would enqueue duplicates into the
                    # same jammed ring; its frames 504 at the edge. Other
                    # workers' rings are healthy: pushed ones are done,
                    # unattempted ones take the per-frame fallback.
                    logger.error(
                        "response ring full during bulk push to worker %d; "
                        "its frames will time out at the edge", worker_id)
                    return {key for key, _ in chunk
                            if key[0] in pushed or key[0] == worker_id}
            return True

        return finish_chunk

    def _predict_frames(self, model_id: int, method: int, frames,
                        rings=None) -> Dict[tuple, bytes]:
        """frames: [((worker_id, req_id), arr)]; one stacked call when shapes
        allow. Keys are (worker, req) pairs throughout: req_ids are
        per-edge-worker counters, so with multiple edge workers the bare
        req_id collides across workers."""
        out: Dict[tuple, bytes] = {}
        if model_id >= len(self.models):
            for key, _ in frames:
                out[key] = self._err_response(
                    key[1], f"unknown device model {model_id}", "BAD_GRAPH")
            return out
        component = self.models[model_id]
        if method == METHOD_TRANSFORM_INPUT:
            def call(arr):
                return component.transform_input(arr, [], meta={})
        elif method == METHOD_PREDICT:
            def call(arr):
                return component.predict(arr, [], meta={})
        else:
            for key, _ in frames:
                out[key] = self._err_response(
                    key[1], f"unknown device method {method}", "BAD_GRAPH")
            return out

        def finish(key: tuple, result: np.ndarray) -> None:
            if not (isinstance(result, np.ndarray)
                    and (np.issubdtype(result.dtype, np.number)
                         or result.dtype == np.bool_)):
                out[key] = self._err_response(
                    key[1],
                    "device model returned a non-numeric payload",
                    "ENGINE_ERROR")
                return
            out[key] = self._ok_response(
                key[1], result,
                self._fragment_for(model_id, method, component, result))

        # stack 2-D frames with equal feature shape into one call, chunked at
        # the largest compiled bucket (stacking must never out-shape the
        # warmed compile cache). Components with DYNAMIC tags/metrics (e.g.
        # outlier detectors scoring each request) must run solo: a stacked
        # call would compute one tags() for the whole batch and misattribute
        # per-request scores — UNLESS the component implements the row_slice
        # protocol, in which case the stacked call's tags/metrics are sliced
        # per frame from its own rows.
        max_rows = self.max_rows[model_id]
        row_sliced = self._row_sliceable[model_id] and not self._frag_static[model_id]
        if self._frag_static[model_id] or row_sliced:
            stackable = [(r, a) for r, a in frames if a.ndim >= 2]
            solo = [(r, a) for r, a in frames if a.ndim < 2]
        else:
            stackable = []
            solo = list(frames)
        by_shape: Dict[tuple, list] = {}
        for r, a in stackable:
            by_shape.setdefault(a.shape[1:], []).append((r, a))
        def fail(key, e):
            out[key] = self._err_response(
                key[1], str(e),
                getattr(e, "reason", "ENGINE_ERROR"),
                int(getattr(e, "status_code", 500)))

        if row_sliced:
            def finish_chunk(chunk, result):
                if not (np.issubdtype(result.dtype, np.number)
                        or result.dtype == np.bool_):
                    return False  # finish() errors each frame (non-numeric)
                if result.ndim < 2:
                    # falling to finish() would attach whole-batch tags to
                    # every frame — misattribution; fail the chunk instead
                    raise SeldonError(
                        "row-sliceable component returned <2-D output "
                        "from a stacked call")
                off = 0
                for key, a in chunk:
                    rows = a.shape[0]
                    out[key] = self._ok_response(
                        key[1], result[off:off + rows],
                        self._row_fragment(method, component,
                                           result[off:off + rows],
                                           off, off + rows))
                    off += rows
                return True
        else:
            finish_chunk = self._chunk_pusher(model_id, method, component, rings)
        seg_hook = (getattr(component, "stack_segments", None)
                    if row_sliced else None)
        for shape, group in by_shape.items():
            self._call_stacked(call, group, max_rows, finish, fail, finish_chunk,
                               set_segments=seg_hook)
        for key, arr in solo:
            try:
                # graftlint: allow-host-sync-in-hot-path(IPC worker must materialize the result to copy it into the shared-memory ring — the sync IS the response write)
                finish(key, np.asarray(call(arr)))
            except Exception as e:
                fail(key, e)
        return out

    def execute(self, frames, rings=None) -> Dict[int, Dict[int, bytes]]:
        """frames: [(worker_id, req_id, payload_bytes)] →
        {worker_id: {req_id: response_bytes}}.

        With ``rings`` ({worker_id: SharedRing}), stacked chunks with static
        fragments push their responses directly through the C bulk path and
        do NOT appear in the returned dict — only solo frames, errors, and
        fallback cases come back as bytes for the caller to push."""
        parsed: Dict[tuple, list] = {}
        responses: Dict[int, Dict[int, bytes]] = {}
        for worker_id, req_id, payload in frames:
            try:
                stages, arr = self.parse_frame(payload)
            except Exception:
                responses.setdefault(worker_id, {})[req_id] = self._err_response(
                    req_id, "malformed device-model frame", "MICROSERVICE_BAD_DATA", 400)
                continue
            if len(stages) > 1:
                parsed.setdefault(stages, []).append(((worker_id, req_id), arr))
                continue
            model_id, method = stages[0]
            parsed.setdefault((model_id, method), []).append(((worker_id, req_id), arr))
        for gkey, group in parsed.items():
            if isinstance(gkey[0], tuple):  # fused chain group
                results = self._run_chains(gkey, group)
            else:
                model_id, method = gkey
                results = self._predict_frames(model_id, method, group, rings)
            for (worker_id, req_id), resp in results.items():
                responses.setdefault(worker_id, {})[req_id] = resp
        return responses

    def _run_chains(self, stages, group) -> Dict[tuple, bytes]:
        """Fused chains, executed STAGE-WISE across all frames sharing the
        stage tuple: a dynamic-tags stage (outlier detector) runs solo per
        frame (per-request score attribution), while a static stage (the
        model) stacks every frame's rows into one jitted call — the chain
        costs one ring RTT and the model stage still micro-batches. The
        response fragment is a JSON array, one fragment per stage."""
        current: Dict[tuple, np.ndarray] = {key: arr for key, arr in group}
        frags: Dict[tuple, list] = {key: [] for key, _ in group}
        out: Dict[tuple, bytes] = {}

        def fail(key, e):
            out[key] = self._err_response(
                key[1], str(e), getattr(e, "reason", "ENGINE_ERROR"),
                int(getattr(e, "status_code", 500)))
            current.pop(key, None)

        for model_id, method in stages:
            if not current:
                break
            if model_id >= len(self.models):
                for key in list(current):
                    fail(key, SeldonError(f"unknown device model {model_id}",
                                          reason="BAD_GRAPH"))
                break
            component = self.models[model_id]
            if method == METHOD_TRANSFORM_INPUT:
                def call(a, _c=component):
                    return _c.transform_input(a, [], meta={})
            elif method == METHOD_PREDICT:
                def call(a, _c=component):
                    return _c.predict(a, [], meta={})
            else:
                for key in list(current):
                    fail(key, SeldonError(f"unknown device method {method}",
                                          reason="BAD_GRAPH"))
                break

            def finish_stage(key, result):
                result = np.asarray(result)
                if not (np.issubdtype(result.dtype, np.number)
                        or result.dtype == np.bool_):
                    fail(key, SeldonError(
                        "device model returned a non-numeric payload"))
                    return
                frags[key].append(self._fragment_for(
                    model_id, method, component, result).decode() or "{}")
                current[key] = result

            keys = list(current)
            row_sliced = (self._row_sliceable[model_id]
                          and not self._frag_static[model_id])
            if self._frag_static[model_id] or row_sliced:
                by_shape: Dict[tuple, list] = {}
                solo = []
                for k in keys:
                    a = current[k]
                    if a.ndim >= 2:
                        by_shape.setdefault(a.shape[1:], []).append((k, a))
                    else:
                        solo.append(k)
                finish_chunk = None
                if row_sliced:
                    # one scoring call for the whole chunk; each frame's
                    # stage fragment is sliced from its own rows
                    def finish_chunk(chunk, result,
                                     _m=model_id, _meth=method, _c=component):
                        if not (np.issubdtype(result.dtype, np.number)
                                or result.dtype == np.bool_):
                            return False  # finish_stage errors per frame
                        if result.ndim < 2:
                            raise SeldonError(
                                "row-sliceable component returned <2-D "
                                "output from a stacked call")
                        off = 0
                        for k, a in chunk:
                            rows = a.shape[0]
                            frag = self._row_fragment(
                                _meth, _c, result[off:off + rows],
                                off, off + rows)
                            frags[k].append(frag.decode() or "{}")
                            current[k] = result[off:off + rows]
                            off += rows
                        return True
                seg_hook = (getattr(component, "stack_segments", None)
                            if row_sliced else None)
                for shape, items in by_shape.items():
                    self._call_stacked(call, items, self.max_rows[model_id],
                                       finish_stage, fail, finish_chunk,
                                       set_segments=seg_hook)
                for k in solo:
                    try:
                        finish_stage(k, np.asarray(call(current[k])))
                    except Exception as e:
                        fail(k, e)
            else:
                # dynamic tags/metrics without row attribution: solo per
                # frame (per-request scores)
                for k in keys:
                    try:
                        finish_stage(k, call(current[k]))
                    except Exception as e:
                        fail(k, e)

        for key, arr in current.items():
            frag = ("[" + ",".join(frags[key]) + "]").encode()
            out[key] = self._ok_response(key[1], arr, frag)
        return out


def _error_body(info: str, reason: str, code: int = 500) -> bytes:
    """Error frame body (Status contract shape, contracts/payload.py Status):
    clients parse status.info/status.reason; HTTP frontends use status.code."""
    return json.dumps(
        {"status": {"code": code, "info": info, "reason": reason, "status": "FAILURE"}}
    ).encode()


def default_ring_dir(prefix: str = "seldon-ring-") -> str:
    """Ring files MUST live on tmpfs: a MAP_SHARED mapping over a disk-backed
    file re-faults through the filesystem (journal block allocation) every
    time writeback cleans a dirtied page — measured 8.8ms ping-pong RTT on
    /tmp (ext4) vs 0.45ms on /dev/shm for the identical ring."""
    import tempfile

    shm = "/dev/shm"
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        return tempfile.mkdtemp(prefix=prefix, dir=shm)
    return tempfile.mkdtemp(prefix=prefix)


def request_ring_path(base: str) -> str:
    return base + ".req"


def response_ring_path(base: str, worker_id: int) -> str:
    return f"{base}.resp.{worker_id}"


class IPCEngineServer:
    """Drains the request ring into the in-process GraphEngine."""

    def __init__(
        self,
        engine: Any,
        base_path: str,
        n_workers: int,
        capacity: int = 1024,
        slot_size: int = 1 << 20,
        batch: int = 64,
        model_executor: Optional[ModelExecutor] = None,
    ):
        self.engine = engine
        self.base_path = base_path
        self.batch = batch
        self.model_executor = model_executor
        # sweep temp files orphaned by a previous creator killed mid-create;
        # glob per exact ring path so a sibling base sharing this prefix
        # (e.g. "<base>2") is never touched mid-create
        import glob

        ring_paths = [request_ring_path(base_path)] + [
            response_ring_path(base_path, w) for w in range(n_workers)
        ]
        for stale in (t for p in ring_paths for t in glob.glob(p + ".tmp.*")):
            try:
                os.unlink(stale)
            except OSError:
                pass
        self.req_ring = SharedRing(
            request_ring_path(base_path), capacity=capacity, slot_size=slot_size, create=True
        )
        self.resp_rings = {
            w: SharedRing(
                response_ring_path(base_path, w), capacity=capacity, slot_size=slot_size,
                create=True,
            )
            for w in range(n_workers)
        }
        self._stop = False

    async def serve_forever(self, poll_wait_s: float = 0.05) -> None:
        """Drain loop. The hot path (kind-2 model frames) runs entirely on a
        dedicated thread — pop, stacked predict, response push — with zero
        event-loop hops; only JSON graph frames (kind 0/1) cross into the
        asyncio engine. (asyncio.to_thread cost ~1ms of scheduling per hop at
        exactly the moment throughput mattered.)"""
        loop = asyncio.get_running_loop()
        done = asyncio.Event()
        trace = bool(os.environ.get("SELDON_IPC_TRACE"))

        from collections import deque

        # Backpressure for JSON graph frames: cap in-flight engine coroutines
        # so a burst fills the ring (edge answers 503 ENGINE_BUSY) instead of
        # growing the event-loop queue without bound.
        inflight: Any = deque()
        max_inflight = max(4 * self.batch, 64)

        # Fully-local graph: plane-3 frames execute inline on the drain
        # thread (engine coroutines never suspend), skipping the
        # run_coroutine_threadsafe hop + to_thread response push that
        # dominated the old per-request cost. Async graphs (remote nodes,
        # async user components) keep the event-loop path.
        inline_plane3 = not getattr(self.engine, "has_async_nodes", True)

        def drain() -> None:
            try:
                while not self._stop:
                    t0 = time.perf_counter()
                    # one FFI call per drain; frames are zero-copy views into
                    # the ring's pop buffer, consumed before the next drain
                    # (model frames synchronously below; JSON frames copied
                    # into bytes before crossing to the event loop)
                    frames = self.req_ring.pop_many(self.batch, poll_wait_s)
                    if not frames:
                        continue
                    t1 = time.perf_counter()
                    model_frames = []
                    for f in frames:
                        try:
                            worker_id, req_id, kind = _REQ_HEADER.unpack_from(f)
                        except struct.error:
                            logger.error(
                                "dropping malformed IPC frame (%d bytes)", len(f))
                            continue
                        if kind == KIND_MODEL and self.model_executor is not None:
                            model_frames.append(
                                (worker_id, req_id, f[_REQ_HEADER.size:]))
                        elif inline_plane3:
                            self._handle_sync(f)
                        else:
                            f = bytes(f)
                            while inflight and inflight[0].done():
                                inflight.popleft()
                            if len(inflight) >= max_inflight:
                                inflight.popleft().result()  # block: backpressure
                            inflight.append(
                                asyncio.run_coroutine_threadsafe(self._handle(f), loop))
                    if model_frames:
                        self._handle_models_sync(model_frames)
                    if trace:
                        print(
                            f"ipc cycle: pop={1e3*(t1-t0):.2f}ms "
                            f"n={len(frames)} "
                            f"handle={1e3*(time.perf_counter()-t1):.2f}ms",
                            file=__import__('sys').stderr, flush=True)
            finally:
                loop.call_soon_threadsafe(done.set)

        threading.Thread(target=drain, name="ipc-drain", daemon=True).start()
        await done.wait()

    def _handle_models_sync(self, model_frames) -> None:
        try:
            responses = self.model_executor.execute(model_frames, rings=self.resp_rings)
        except Exception:
            logger.exception("model executor batch failed")
            responses = {}
            for w, r, _ in model_frames:
                responses.setdefault(w, {})[r] = ModelExecutor._err_response(
                    r, "model executor crashed", "ENGINE_ERROR")
        for worker_id, by_req in responses.items():
            ring = self.resp_rings.get(worker_id)
            if ring is None:
                logger.error("device responses for unknown worker %d dropped",
                             worker_id)
                continue
            for resp in by_req.values():
                try:
                    ring.push_wait(resp, 5.0)
                except PayloadTooLarge:
                    req_id = _RESP_HEADER.unpack_from(resp)[0]
                    err = ModelExecutor._err_response(
                        req_id,
                        f"device response too large for IPC slot "
                        f"({len(resp)} bytes)",
                        "RESPONSE_TOO_LARGE")
                    try:
                        ring.push_wait(err, 5.0)
                    except Exception:
                        logger.exception("dropping oversized device response")
                except Exception:
                    logger.exception(
                        "dropping device response for stalled worker %d",
                        worker_id)

    def stop(self) -> None:
        self._stop = True

    def _handle_sync(self, frame) -> None:
        """Plane-3 frame (JSON kind 0/1 or proto kind 3/4) executed INLINE on
        the drain thread — no event-loop hop, no to_thread push. Only valid
        when the graph has no async nodes (engine.has_async_nodes False), in
        which case predict()/send_feedback() never suspend; the serve loop
        picks between this and the coroutine path once at startup."""
        try:
            worker_id, req_id, kind = _REQ_HEADER.unpack_from(frame)
        except struct.error:
            logger.error("dropping malformed IPC frame (%d bytes)", len(frame))
            return
        try:
            if kind in (KIND_PROTO_PREDICT, KIND_PROTO_FEEDBACK):
                from seldon_core_tpu.transport import proto_convert as pc
                from seldon_core_tpu.transport.proto import prediction_pb2 as pb

                raw = bytes(frame[_REQ_HEADER.size:])
                if kind == KIND_PROTO_PREDICT:
                    out = self.engine.predict_sync(
                        pc.message_from_proto(pb.SeldonMessage.FromString(raw)))
                else:
                    out = self.engine.send_feedback_sync(
                        pc.feedback_from_proto(pb.Feedback.FromString(raw)))
                body = pc.message_to_proto(out).SerializeToString()
            else:
                payload = json.loads(bytes(frame[_REQ_HEADER.size:]))
                if kind == KIND_PREDICT:
                    out = self.engine.predict_sync(SeldonMessage.from_dict(payload))
                elif kind == KIND_FEEDBACK:
                    out = self.engine.send_feedback_sync(Feedback.from_dict(payload))
                else:
                    raise SeldonError(f"unknown IPC kind {kind}")
                body = json.dumps(out.to_dict()).encode()
            status = 0
        except Exception as e:
            if kind in (KIND_PROTO_PREDICT, KIND_PROTO_FEEDBACK):
                http = int(getattr(e, "status_code", 500))
                code = {400: 3, 503: 14, 504: 4}.get(http, 13)
                body = bytes([code]) + str(e).encode()
            else:
                body = _error_body(
                    str(e),
                    getattr(e, "reason", "ENGINE_ERROR"),
                    int(getattr(e, "status_code", 500)),
                )
            status = 1
        ring = self.resp_rings.get(worker_id)
        if ring is None:
            logger.error("response for unknown worker %d dropped", worker_id)
            return
        try:
            ring.push_wait(_RESP_HEADER.pack(req_id, status) + body, 5.0)
        except PayloadTooLarge:
            err = _error_body(
                f"response too large for IPC slot "
                f"({len(body)} bytes > {ring.slot_size - _RESP_HEADER.size})",
                "RESPONSE_TOO_LARGE",
                500,
            )
            try:
                ring.push_wait(_RESP_HEADER.pack(req_id, 1) + err, 5.0)
            except Exception:
                logger.exception(
                    "dropping oversized response %d for worker %d", req_id, worker_id)
        except RingFull:
            # jammed for the full timeout; the edge's deadline 504s this
            # request — do not kill the drain thread
            logger.error("response ring full; dropping response %d for worker %d",
                         req_id, worker_id)

    async def _handle(self, frame: bytes) -> None:
        # No failure below may escape: serve_forever gathers these, so one bad
        # frame / oversized body / stalled worker would kill serving for all
        # workers.
        try:
            worker_id, req_id, kind = _REQ_HEADER.unpack_from(frame)
        except struct.error:
            logger.error("dropping malformed IPC frame (%d bytes)", len(frame))
            return
        try:
            if kind in (KIND_PROTO_PREDICT, KIND_PROTO_FEEDBACK):
                from seldon_core_tpu.transport import proto_convert as pc
                from seldon_core_tpu.transport.proto import prediction_pb2 as pb

                raw = bytes(frame[_REQ_HEADER.size:])
                if kind == KIND_PROTO_PREDICT:
                    req = pb.SeldonMessage.FromString(raw)
                    out = await self.engine.predict(pc.message_from_proto(req))
                else:
                    req = pb.Feedback.FromString(raw)
                    out = await self.engine.send_feedback(pc.feedback_from_proto(req))
                body = pc.message_to_proto(out).SerializeToString()
                status = 0
            else:
                payload = json.loads(frame[_REQ_HEADER.size:])
                if kind == KIND_PREDICT:
                    out = await self.engine.predict(SeldonMessage.from_dict(payload))
                elif kind == KIND_FEEDBACK:
                    out = await self.engine.send_feedback(Feedback.from_dict(payload))
                else:
                    raise SeldonError(f"unknown IPC kind {kind}")
                body = json.dumps(out.to_dict()).encode()
                status = 0
        except Exception as e:
            if kind in (KIND_PROTO_PREDICT, KIND_PROTO_FEEDBACK):
                # edge expects u8 grpc-status + message for proto frames;
                # mapping mirrors edge.cc grpc_code_from_http
                http = int(getattr(e, "status_code", 500))
                code = {400: 3, 503: 14, 504: 4}.get(http, 13)
                body = bytes([code]) + str(e).encode()
            else:
                body = _error_body(
                    str(e),
                    getattr(e, "reason", "ENGINE_ERROR"),
                    int(getattr(e, "status_code", 500)),
                )
            status = 1
        ring = self.resp_rings.get(worker_id)
        if ring is None:
            logger.error("response for unknown worker %d dropped", worker_id)
            return
        try:
            await asyncio.to_thread(
                ring.push_wait, _RESP_HEADER.pack(req_id, status) + body, 5.0
            )
        except PayloadTooLarge:
            err = _error_body(
                f"response too large for IPC slot "
                f"({len(body)} bytes > {ring.slot_size - _RESP_HEADER.size})",
                "RESPONSE_TOO_LARGE",
                500,
            )
            try:
                await asyncio.to_thread(ring.push_wait, _RESP_HEADER.pack(req_id, 1) + err, 5.0)
            except Exception:
                logger.exception("dropping oversized response %d for worker %d", req_id, worker_id)
        except Exception:
            logger.exception("dropping response %d for stalled worker %d", req_id, worker_id)


class IPCClient:
    """Worker-side handle: send a request frame, wait for the matching
    response (out-of-order safe — responses for other requests from this
    worker are parked)."""

    _PARKED_MAX = 1024

    def __init__(self, base_path: str, worker_id: int, timeout_s: float = 30.0):
        self.worker_id = int(worker_id)
        self.timeout_s = timeout_s
        self.req_ring = SharedRing(request_ring_path(base_path), create=False)
        self.resp_ring = SharedRing(response_ring_path(base_path, worker_id), create=False)
        self._next_id = 0
        # rid -> (arrival time, frame). Bounded: late responses to requests
        # that already timed out would otherwise accumulate forever, and after
        # u32 request-id wraparound a stale frame could match a live request.
        self._parked: Dict[int, tuple] = {}

    def _prune_parked(self) -> None:
        now = time.monotonic()
        stale = [rid for rid, (t, _) in self._parked.items() if now - t > self.timeout_s]
        for rid in stale:
            del self._parked[rid]
        while len(self._parked) > self._PARKED_MAX:
            oldest = min(self._parked, key=lambda rid: self._parked[rid][0])
            del self._parked[oldest]

    def _call(self, kind: int, payload: Dict[str, Any]) -> Dict[str, Any]:
        req_id = self._next_id
        self._next_id = (self._next_id + 1) & 0xFFFFFFFF
        frame = _REQ_HEADER.pack(self.worker_id, req_id, kind) + json.dumps(payload).encode()
        self.req_ring.push_wait(frame, timeout_s=self.timeout_s)

        deadline = time.monotonic() + self.timeout_s
        while True:
            if req_id in self._parked:
                raw = self._parked.pop(req_id)[1]
            else:
                raw = self.resp_ring.pop()
                if raw is None:
                    if time.monotonic() > deadline:
                        self._prune_parked()
                        raise TimeoutError(f"IPC response {req_id} timed out")
                    time.sleep(0.0002)
                    continue
            rid, status = _RESP_HEADER.unpack_from(raw)
            body = json.loads(raw[_RESP_HEADER.size:])
            if rid != req_id:
                self._parked[rid] = (time.monotonic(), raw)
                self._prune_parked()
                continue
            if status != 0:
                raise SeldonError(
                    body.get("status", {}).get("info", "IPC engine error"),
                    reason=body.get("status", {}).get("reason", "ENGINE_ERROR"),
                    status_code=500,
                )
            return body

    def predict(self, message: SeldonMessage) -> SeldonMessage:
        return SeldonMessage.from_dict(self._call(KIND_PREDICT, message.to_dict()))

    def send_feedback(self, feedback: Feedback) -> SeldonMessage:
        return SeldonMessage.from_dict(self._call(KIND_FEEDBACK, feedback.to_dict()))

    def close(self) -> None:
        self.req_ring.close()
        self.resp_ring.close()


def cleanup_rings(base_path: str, n_workers: int) -> None:
    import glob

    paths = [request_ring_path(base_path)] + [
        response_ring_path(base_path, w) for w in range(n_workers)
    ]
    # stale .tmp.<pid> files left by a creator killed between open and rename
    paths += [t for p in paths for t in glob.glob(p + ".tmp.*")]
    for p in paths:
        try:
            os.unlink(p)
        except OSError:
            pass
