"""gRPC transport.

Exposes the same seven services as the reference (`proto/prediction.proto:
94-128`: Generic, Model, Router, Transformer, OutputTransformer, Combiner,
Seldon). grpc_tools is unavailable in this image, so the servicer glue the
generator would emit is written directly with ``grpc.method_handlers_generic_
handler`` — identical wire behavior, no generated *_pb2_grpc module.

- ``serve_component``: one component, microservice role
  (`python/seldon_core/wrapper.py:103-146`).
- ``serve_engine``: whole predictor graph, engine role
  (`engine/.../grpc/SeldonGrpcServer.java:34-143`).

Max message size honors the reference annotation
``seldon.io/grpc-max-message-size``.
"""

from __future__ import annotations

import asyncio
import logging
from concurrent import futures
from typing import Any, Callable, Dict, Optional

import grpc

from seldon_core_tpu.codec import framing
from seldon_core_tpu.components import dispatch
from seldon_core_tpu.contracts.payload import SeldonError
from seldon_core_tpu.metrics.registry import MetricsRegistry
from seldon_core_tpu.runtime.resilience import (
    DEADLINE_GRPC_METADATA,
    AdmissionController,
    Deadline,
    ResumeMarker,
    ShedError,
    deadline_scope,
)
from seldon_core_tpu.tracing import get_tracer
from seldon_core_tpu.transport import proto_convert as pc
from seldon_core_tpu.transport.proto import prediction_pb2 as pb

logger = logging.getLogger(__name__)

ANNOTATION_GRPC_MAX_MSG_SIZE = "seldon.io/grpc-max-message-size"
DEFAULT_MAX_MSG_BYTES = 4 * 1024 * 1024

_SERVICE_PACKAGE = "seldon.protos"


def _abort(context: grpc.ServicerContext, e: Exception):
    if isinstance(e, SeldonError):
        # resilience status mapping: budget exhaustion is DEADLINE_EXCEEDED,
        # admission sheds are RESOURCE_EXHAUSTED, breaker/unavailable 503s are
        # UNAVAILABLE (retryable), other 5xx INTERNAL, 4xx INVALID_ARGUMENT
        if e.status_code == 504 or e.reason == "DEADLINE_EXCEEDED":
            code = grpc.StatusCode.DEADLINE_EXCEEDED
        elif e.reason == "RESOURCE_EXHAUSTED":
            code = grpc.StatusCode.RESOURCE_EXHAUSTED
        elif e.status_code == 503:
            code = grpc.StatusCode.UNAVAILABLE
        elif e.status_code < 500:
            code = grpc.StatusCode.INVALID_ARGUMENT
        else:
            code = grpc.StatusCode.INTERNAL
        context.abort(code, e.message)
    logger.exception("grpc handler error")
    context.abort(grpc.StatusCode.INTERNAL, str(e))


def _traceparent_from_context(context: grpc.ServicerContext) -> Optional[str]:
    """The inbound W3C ``traceparent`` metadata key (the gRPC spelling of
    the REST header), or None."""
    for key, value in context.invocation_metadata() or ():
        if key == "traceparent":
            return value
    return None


def _metadata_value(context: grpc.ServicerContext, name: str) -> Optional[str]:
    """One invocation-metadata value by (lowercase) key, or None."""
    for key, value in context.invocation_metadata() or ():
        if key == name:
            return value
    return None


def _deadline_from_context(context: grpc.ServicerContext) -> Deadline | None:
    """The client's gRPC deadline (context.time_remaining()), else the
    ``seldon-deadline-ms`` metadata key for clients that cannot set one."""
    try:
        rem = context.time_remaining()
    except Exception:
        rem = None
    if rem is not None and rem < 1e9:  # grpc reports a huge value for "none"
        return Deadline(rem)
    for key, value in context.invocation_metadata() or ():
        if key == DEADLINE_GRPC_METADATA:
            try:
                ms = float(value)
            except (TypeError, ValueError):
                return None
            return Deadline.from_ms(ms) if ms > 0 else None
    return None


def _component_methods(
    component: Any, unit_id: str, admission: Optional[AdmissionController] = None
) -> Dict[str, Dict[str, Callable]]:
    """method table: service -> rpc name -> (deserializer applied by handler)."""
    admission = admission or AdmissionController()
    # dynamic Retry-After from the component's live backlog — the shared
    # wiring keeps REST and gRPC in agreement (docs/resilience.md)
    from seldon_core_tpu.observability.timeline import wire_retry_after

    wire_retry_after(admission, component=component)

    def wrap(fn, req_from, method_name):
        # frames ride gRPC as binData payloads tagged in meta — only the
        # SeldonMessage-parsered methods can carry them (aggregate/feedback
        # have list/feedback request types and skip the unwrap)
        frames = req_from is pc.message_from_proto

        def handler(request, context):
            tracer = get_tracer()
            try:
                admission.acquire_sync()
            except ShedError as e:
                _abort(context, e)
                return
            try:
                with deadline_scope(_deadline_from_context(context)):
                    with tracer.span("grpc:" + method_name,
                                     traceparent=_traceparent_from_context(
                                         context)):
                        inbound = req_from(request)
                        framed_in = frames and framing.grpc_is_framed(inbound)
                        if framed_in:
                            inbound = framing.grpc_unwrap(inbound)
                        result = fn(component, inbound)
                        if asyncio.iscoroutine(result):
                            result = asyncio.run(result)
                        if framed_in and framing.frameable(result):
                            result = framing.grpc_wrap(result)
                return pc.message_to_proto(result)
            except Exception as e:  # noqa: BLE001
                _abort(context, e)
            finally:
                admission.release()

        return handler

    def fb(comp, f):
        return dispatch.send_feedback(comp, f, unit_id=unit_id or None)

    # single-prompt continuous batching lives in dispatch.predict itself
    # (_maybe_continuous_batch), so every transport shares the one batch
    predict = wrap(dispatch.predict, pc.message_from_proto, "predict")
    tin = wrap(dispatch.transform_input, pc.message_from_proto, "transform_input")
    tout = wrap(dispatch.transform_output, pc.message_from_proto, "transform_output")
    route = wrap(dispatch.route, pc.message_from_proto, "route")
    aggregate = wrap(dispatch.aggregate, pc.list_from_proto, "aggregate")
    feedback = wrap(fb, pc.feedback_from_proto, "send_feedback")
    gen_stream = _make_generate_stream(component)
    timeline = _make_debug_timeline(component)

    return {
        "Model": {"Predict": (predict, pb.SeldonMessage), "SendFeedback": (feedback, pb.Feedback),
                  "GenerateStream": (gen_stream, pb.SeldonMessage, "unary_stream"),
                  "DebugTimeline": (timeline, pb.SeldonMessage)},
        "Generic": {
            "TransformInput": (tin, pb.SeldonMessage),
            "TransformOutput": (tout, pb.SeldonMessage),
            "Route": (route, pb.SeldonMessage),
            "Aggregate": (aggregate, pb.SeldonMessageList),
            "SendFeedback": (feedback, pb.Feedback),
        },
        "Router": {"Route": (route, pb.SeldonMessage), "SendFeedback": (feedback, pb.Feedback)},
        "Transformer": {"TransformInput": (tin, pb.SeldonMessage)},
        "OutputTransformer": {"TransformOutput": (tout, pb.SeldonMessage)},
        "Combiner": {"Aggregate": (aggregate, pb.SeldonMessageList)},
    }


def _make_debug_timeline(component: Any):
    """``Model/DebugTimeline``: the gRPC mirror of REST /debug/timeline —
    identical payload (observability/timeline.py timeline_report renders
    both), carried as SeldonMessage jsonData. Request jsonData may set
    ``{"n": K}`` to bound the timeline count."""
    from seldon_core_tpu.contracts.payload import SeldonMessage

    def debug_timeline(request, context):
        from seldon_core_tpu.observability.timeline import (
            parse_n, timeline_report)

        try:
            msg = pc.message_from_proto(request)
            body = msg.json_data if msg.which == "jsonData" else None
            n = parse_n(body.get("n") if isinstance(body, dict) else None)
            return pc.message_to_proto(
                SeldonMessage.from_json_data(timeline_report(component, n=n)))
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    return debug_timeline


def _make_generate_stream(component: Any):
    """Server-streaming LLM generation: the gRPC mirror of the REST SSE
    contract (transport/rest.py ``/v1/generate`` with ``"stream": true``).

    Request: SeldonMessage jsonData ``{"prompt": str|[ids],
    "max_new_tokens": N, "seed": S}``. Responses: one jsonData
    ``{"token": t, "text": piece}`` per generated token as the shared
    batch decodes, then one jsonData done event with the SAME payload
    shape as the SSE done event (``{"done": true, "tokens": [...],
    "text": ...}`` + ``truncated_prompt`` when admission clipped).
    Rejections mirror SSE too: per-request temperature and a seeded
    prompt that exceeds the batcher slot cache abort INVALID_ARGUMENT
    before the stream starts (the REST path 400s before the SSE
    response starts) — parity-tested event-for-event in
    tests/test_batcher_serving.py."""
    import queue as _queue

    from seldon_core_tpu.contracts.payload import SeldonMessage

    def generate_stream(request, context):
        if not hasattr(component, "generate"):
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "component has no generate() surface")
            return
        try:
            msg = pc.message_from_proto(request)
            body = msg.json_data if msg.which == "jsonData" else None
            if not isinstance(body, dict) or body.get("prompt") is None:
                raise SeldonError("jsonData needs 'prompt'", status_code=400)
            if "temperature" in body:
                raise SeldonError(
                    "streaming with per-request temperature is not "
                    "supported; set it on the server", status_code=400)
            prompt = body["prompt"]
            if isinstance(prompt, list):
                prompt = [int(t) for t in prompt]
            max_new = body.get("max_new_tokens")
            if max_new is not None:
                max_new = int(max_new)
            from seldon_core_tpu.runtime.batcher import ensure_stream_service

            svc = ensure_stream_service(component)
            if "seed" in body and not svc.batcher.accommodates(
                    prompt, max_new):
                # same contract as the SSE path: no generate() fallback
                # exists for a stream, so a seeded prompt the slot cache
                # would clip cannot reproduce generate(seed=...)
                raise SeldonError(
                    "seeded streaming prompt exceeds the batcher slot "
                    "cache and would not reproduce generate(seed=...); "
                    "raise continuous_batching_max_len or drop streaming",
                    status_code=400)
        except Exception as e:  # noqa: BLE001 — pre-stream rejection
            _abort(context, e)
            return

        # request-scoped tracing: the traceparent metadata key (the gRPC
        # spelling of the REST header) roots this stream's span tree at
        # this ingress; the trace id rides the done event like SSE's
        from seldon_core_tpu.tracing import ingress_trace

        trace = ingress_trace(get_tracer(),
                              _traceparent_from_context(context),
                              "grpc:GenerateStream")
        if trace is not None:
            # INITIAL metadata, like SSE's X-Trace-Id header: the id must
            # reach the client BEFORE the first token — a hung stream is
            # exactly when the operator needs the /debug/timeline key, and
            # trailing metadata never arrives on a cancelled RPC
            try:
                context.send_initial_metadata(
                    (("x-trace-id", trace.trace_id),))
            except Exception:  # transport already started the stream
                pass

        decode = getattr(component, "_tokenizer", None)
        text_mode = isinstance(body["prompt"], str)

        def tok_event(tok):
            if isinstance(tok, ResumeMarker):
                # fleet recovery re-attached this stream after a replica
                # death: an in-band meta chunk, never a token (at-most-once
                # contract, docs/resilience.md) — mirrors the SSE marker
                return pc.message_to_proto(SeldonMessage.from_json_data(
                    {"resumed": True,
                     "tokens_delivered": tok.tokens_delivered}))
            piece = decode.decode([tok]) if (decode is not None
                                             and text_mode) else None
            return pc.message_to_proto(SeldonMessage.from_json_data(
                {"token": tok, "text": piece}))

        # multi-tenant identity: the metadata spellings of the REST
        # headers (Seldon-Tenant / Seldon-SLO-Class), jsonData fields
        # winning when both are present; the adapter name is a jsonData
        # field and the gRPC deadline doubles as the scheduler's EDF key
        tenant = body.get("tenant") or _metadata_value(context,
                                                       "seldon-tenant")
        slo_class = body.get("slo_class") or _metadata_value(
            context, "seldon-slo-class")
        dl = _deadline_from_context(context)
        q: _queue.Queue = _queue.Queue()
        _DONE = object()
        info: dict = {}
        cfut = svc.submit_stream(prompt, max_new, on_token=q.put,
                                 info=info, seed=body.get("seed"),
                                 trace=trace, tenant=tenant,
                                 slo_class=slo_class,
                                 adapter=body.get("adapter"),
                                 deadline_s=(dl.remaining_s()
                                             if dl is not None else None))
        # a submit that fails before any token never sends the None
        # sentinel; the done-callback marker keeps the pump from hanging
        cfut.add_done_callback(lambda f: q.put(_DONE))
        try:
            while True:
                tok = q.get()
                if tok is None:
                    break
                if tok is _DONE:
                    # future resolved with no sentinel yet: drain the
                    # queue fully (the SSE drain contract) — a token
                    # enqueued around completion is never dropped
                    while True:
                        try:
                            tok = q.get_nowait()
                        except _queue.Empty:
                            break
                        if tok is None or tok is _DONE:
                            break
                        yield tok_event(tok)
                    break
                yield tok_event(tok)
            toks = cfut.result(timeout=600.0)
            text = decode.decode(toks) if (decode is not None
                                           and text_mode) else None
            done_evt = {"done": True, "tokens": toks, "text": text}
            if trace is not None:
                done_evt["trace_id"] = trace.trace_id
            if info.get("truncated_prompt"):
                done_evt["truncated_prompt"] = info["truncated_prompt"]
            yield pc.message_to_proto(SeldonMessage.from_json_data(done_evt))
        except Exception as e:  # noqa: BLE001
            _abort(context, e)
        finally:
            # a client disconnect unwinds the generator with GeneratorExit
            # (a BaseException the except above never sees): cancel here so
            # an abandoned stream's submit stops, matching the SSE path's
            # disconnect handling — on a completed future this is a no-op
            cfut.cancel()

    return generate_stream


def _generic_handlers(method_table: Dict[str, Dict[str, tuple]]):
    handlers = []
    for service, methods in method_table.items():
        rpc_handlers = {}
        for rpc_name, entry in methods.items():
            fn, req_cls = entry[0], entry[1]
            kind = entry[2] if len(entry) > 2 else "unary_unary"
            make = (grpc.unary_stream_rpc_method_handler
                    if kind == "unary_stream"
                    else grpc.unary_unary_rpc_method_handler)
            rpc_handlers[rpc_name] = make(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        handlers.append(
            grpc.method_handlers_generic_handler(f"{_SERVICE_PACKAGE}.{service}", rpc_handlers)
        )
    return handlers


def _server_options(annotations: Optional[Dict[str, str]]) -> list:
    max_size = DEFAULT_MAX_MSG_BYTES
    if annotations and ANNOTATION_GRPC_MAX_MSG_SIZE in annotations:
        max_size = int(annotations[ANNOTATION_GRPC_MAX_MSG_SIZE])
    return [
        ("grpc.max_send_message_length", max_size),
        ("grpc.max_receive_message_length", max_size),
    ]


def make_component_server(
    component: Any,
    port: Optional[int] = 5000,
    host: str = "0.0.0.0",
    unit_id: str = "",
    annotations: Optional[Dict[str, str]] = None,
    max_workers: int = 8,
    admission: Optional[AdmissionController] = None,
) -> grpc.Server:
    admission = admission or AdmissionController.from_annotations(annotations)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers), options=_server_options(annotations)
    )
    for h in _generic_handlers(_component_methods(component, unit_id, admission)):
        server.add_generic_rpc_handlers((h,))
    if port is not None:
        server.add_insecure_port(f"{host}:{port}")
    return server


def make_engine_server(
    engine: Any,
    port: Optional[int] = 5001,
    host: str = "0.0.0.0",
    metrics: Optional[MetricsRegistry] = None,
    annotations: Optional[Dict[str, str]] = None,
    max_workers: int = 8,
    loop: Optional[asyncio.AbstractEventLoop] = None,
    interceptors: Optional[Any] = None,
    server_credentials: Optional[grpc.ServerCredentials] = None,
    admission: Optional[AdmissionController] = None,
) -> grpc.Server:
    """Seldon external service over the in-process graph engine. The engine is
    async; handlers submit onto the engine's event loop (or a private one).
    ``server_credentials`` switches the listening port to TLS. ``admission``
    bounds concurrent predictions (overflow aborts RESOURCE_EXHAUSTED);
    defaults from annotations/env — disabled unless configured."""
    metrics = metrics or MetricsRegistry()
    admission = admission or AdmissionController.from_annotations(annotations)
    from seldon_core_tpu.observability.timeline import wire_retry_after

    wire_retry_after(admission, engine=engine)
    own_loop = loop
    if own_loop is None:
        own_loop = asyncio.new_event_loop()
        import threading

        t = threading.Thread(target=own_loop.run_forever, daemon=True, name="seldon-grpc-engine-loop")
        t.start()

    def run_coro(coro):
        return asyncio.run_coroutine_threadsafe(coro, own_loop).result()

    async def _predict_with_deadline(msg, deadline, traceparent=None):
        # scope INSIDE the engine-loop task: the deadline contextvar must be
        # visible to the engine (and its per-node spans / remote hops) on
        # that loop — same reason the server span opens here, not on the
        # gRPC worker thread
        with deadline_scope(deadline):
            with get_tracer().span("grpc:predictions",
                                   traceparent=traceparent):
                return await engine.predict(msg)

    def predict(request, context):
        import time

        t0 = time.perf_counter()
        try:
            admission.acquire_sync()
        except ShedError as e:
            _abort(context, e)
            return
        try:
            deadline = _deadline_from_context(context)
            msg = pc.message_from_proto(request)
            framed_in = framing.grpc_is_framed(msg)
            if framed_in:
                msg = framing.grpc_unwrap(msg)
            out = run_coro(_predict_with_deadline(
                msg, deadline, _traceparent_from_context(context)))
            metrics.observe_prediction(engine, out, time.perf_counter() - t0)
            if framed_in and framing.frameable(out):
                out = framing.grpc_wrap(out)
            return pc.message_to_proto(out)
        except Exception as e:  # noqa: BLE001
            if getattr(e, "status_code", None) == 504:
                metrics.observe_deadline_exceeded("grpc")
            _abort(context, e)
        finally:
            admission.release()

    def send_feedback(request, context):
        try:
            fb = pc.feedback_from_proto(request)
            out = run_coro(engine.send_feedback(fb))
            metrics.observe_feedback(fb)
            return pc.message_to_proto(out)
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_server_options(annotations),
        interceptors=tuple(interceptors or ()),
    )
    handler = grpc.method_handlers_generic_handler(
        f"{_SERVICE_PACKAGE}.Seldon",
        {
            "Predict": grpc.unary_unary_rpc_method_handler(
                predict,
                request_deserializer=pb.SeldonMessage.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
            "SendFeedback": grpc.unary_unary_rpc_method_handler(
                send_feedback,
                request_deserializer=pb.Feedback.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
        },
    )
    server.add_generic_rpc_handlers((handler,))
    if port is not None:
        if server_credentials is not None:
            server.add_secure_port(f"{host}:{port}", server_credentials)
        else:
            server.add_insecure_port(f"{host}:{port}")
    return server


def serve_component(component: Any, host: str = "0.0.0.0", port: int = 5000, unit_id: str = "",
                    annotations: Optional[Dict[str, str]] = None) -> None:
    server = make_component_server(component, port=port, host=host, unit_id=unit_id,
                                   annotations=annotations)
    server.start()
    logger.info("gRPC component server on %s:%d", host, port)
    server.wait_for_termination()


def serve_engine(engine: Any, host: str = "0.0.0.0", port: int = 5001, metrics=None,
                 annotations: Optional[Dict[str, str]] = None) -> None:
    server = make_engine_server(engine, port=port, host=host, metrics=metrics,
                                annotations=annotations)
    server.start()
    logger.info("gRPC engine server on %s:%d", host, port)
    server.wait_for_termination()
