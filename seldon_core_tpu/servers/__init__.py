"""Prepackaged model servers.

Parity with `servers/{sklearnserver,xgboostserver,mlflowserver,tfserving}` in
the reference, selected from the graph spec by ``implementation`` + ``modelUri``
(`proto/seldon_deployment.proto:102-113,130`). The native addition is
JAX_SERVER (seldon_core_tpu.servers.jaxserver): Flax/orbax checkpoints served
jit-compiled on TPU — the role TF-Serving/TensorRT play for the reference.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.graph import UnitImplementation
from seldon_core_tpu.contracts.payload import SeldonError


def make_prepackaged_server(
    implementation: UnitImplementation, model_uri: str, parameters: Optional[Dict[str, Any]] = None
) -> SeldonComponent:
    parameters = parameters or {}
    if implementation == UnitImplementation.JAX_SERVER:
        from seldon_core_tpu.servers.jaxserver import JAXServer

        return JAXServer(model_uri=model_uri, **parameters)
    if implementation == UnitImplementation.SKLEARN_SERVER:
        from seldon_core_tpu.servers.sklearnserver import SKLearnServer

        return SKLearnServer(model_uri=model_uri, **parameters)
    if implementation == UnitImplementation.XGBOOST_SERVER:
        from seldon_core_tpu.servers.xgboostserver import XGBoostServer

        return XGBoostServer(model_uri=model_uri, **parameters)
    if implementation == UnitImplementation.MLFLOW_SERVER:
        from seldon_core_tpu.servers.mlflowserver import MLFlowServer

        return MLFlowServer(model_uri=model_uri, **parameters)
    if implementation == UnitImplementation.TENSORFLOW_SERVER:
        from seldon_core_tpu.servers.tfproxy import TFServingProxy

        return TFServingProxy(model_uri=model_uri, **parameters)
    raise SeldonError(
        f"No prepackaged server for implementation {implementation}", reason="BAD_GRAPH"
    )
