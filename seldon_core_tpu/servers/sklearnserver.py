"""sklearn prepackaged server.

Parity with `servers/sklearnserver/sklearnserver/SKLearnServer.py:15-44`:
loads `model.joblib` from modelUri via storage, predicts with predict_proba
(default) or predict.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu import storage
from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError

logger = logging.getLogger(__name__)

JOBLIB_FILE = "model.joblib"


class SKLearnServer(SeldonComponent):
    def __init__(self, model_uri: str = "", method: str = "predict_proba", **kwargs):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.method = method
        self.ready = False
        self._model = None

    def load(self) -> None:
        if self.ready:
            return
        import joblib

        path = storage.download(self.model_uri)
        if os.path.isdir(path):
            path = os.path.join(path, JOBLIB_FILE)
        if not os.path.exists(path):
            raise SeldonError(f"sklearn model file not found: {path}", status_code=500)
        self._model = joblib.load(path)
        self.ready = True
        logger.info("loaded sklearn model from %s", path)

    def predict(self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
        if not self.ready:
            self.load()
        if self.method == "predict_proba":
            return self._model.predict_proba(X)
        return self._model.predict(X)
