"""TENSORFLOW_SERVER proxy (parity: `integrations/tfserving/TfServingProxy.py:
20-125`): forwards predict calls to an external TF-Serving endpoint over
REST ({"instances": ...} -> /v1/models/<name>:predict) or gRPC
(`/tensorflow.serving.PredictionService/Predict`, the reference's stub path
`TfServingProxy.py:35-89`). The gRPC frames are hand-encoded TensorProto /
PredictRequest wire bytes — no tensorflow or tensorflow-serving-api import,
just grpcio — so heterogeneous graphs can take the external server's fast
path without dragging the TF runtime into the image. In the TPU build this
proxy exists for heterogeneous graphs; native models should use JAX_SERVER.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError

# TensorProto dtype enum values (tensorflow/core/framework/types.proto)
_DT_FLOAT = 1
_DT_DOUBLE = 2
_DT_INT32 = 3
_DT_INT64 = 9


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def encode_predict_request(arr: np.ndarray, model_name: str, signature_name: str,
                           input_name: str) -> bytes:
    """tensorflow.serving.PredictRequest wire bytes: model_spec{name,
    signature_name} + inputs[input_name] = TensorProto(dtype, shape,
    float_val/double_val packed)."""
    arr = np.asarray(arr)
    flat = arr.reshape(-1)
    if arr.dtype == np.float64:
        dtype, val_field = _DT_DOUBLE, 6
        packed = struct.pack("<%dd" % flat.size, *flat.tolist())
    elif np.issubdtype(arr.dtype, np.integer):
        # int inputs stay ints on the wire (token-id models): int32 ->
        # int_val (7), anything wider -> int64_val (10); protobuf varints
        # encode negatives as 10-byte two's complement
        if arr.dtype.itemsize <= 4 and arr.dtype != np.uint32:
            dtype, val_field = _DT_INT32, 7
        else:
            dtype, val_field = _DT_INT64, 10
        packed = b"".join(
            _varint(int(v) & 0xFFFFFFFFFFFFFFFF) for v in flat.tolist())
    else:
        arr = arr.astype(np.float32)
        flat = arr.reshape(-1)
        dtype, val_field = _DT_FLOAT, 5
        packed = struct.pack("<%df" % flat.size, *flat.tolist())
    # TensorShapeProto: repeated Dim dim = 2; Dim.size = 1 (int64)
    shape = b"".join(_len_delim(2, _tag(1, 0) + _varint(d)) for d in arr.shape)
    tensor = (
        _tag(1, 0) + _varint(dtype)
        + _len_delim(2, shape)
        + _len_delim(val_field, packed)
    )
    model_spec = (
        _len_delim(1, model_name.encode())
        + _len_delim(3, signature_name.encode())
    )
    entry = _len_delim(1, input_name.encode()) + _len_delim(2, tensor)
    return _len_delim(1, model_spec) + _len_delim(2, entry)


def _read_varint(buf: bytes, off: int):
    shift = 0
    val = 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


def _iter_fields(buf: bytes):
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, off = _read_varint(buf, off)
        elif wire == 2:
            ln, off = _read_varint(buf, off)
            val = buf[off:off + ln]
            off += ln
        elif wire == 5:
            val = buf[off:off + 4]
            off += 4
        elif wire == 1:
            val = buf[off:off + 8]
            off += 8
        else:
            raise SeldonError(f"unsupported protobuf wire type {wire}")
        yield field, wire, val


def _signed64(v: int) -> int:
    """Protobuf varints carry negatives as 64-bit two's complement."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _varint_list(val, wire) -> list:
    """Decode an int_val/int64_val field occurrence: packed (wire 2) holds
    back-to-back varints; unpacked (wire 0) is a single value."""
    if wire == 0:
        return [_signed64(val)]
    out = []
    off = 0
    while off < len(val):
        v, off = _read_varint(val, off)
        out.append(_signed64(v))
    return out


def decode_tensor_proto(buf: bytes) -> np.ndarray:
    dtype = _DT_FLOAT
    dims = []
    floats: list = []
    doubles: list = []
    ints: list = []
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == 0:
            dtype = val
        elif field == 2 and wire == 2:  # tensor_shape
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 2 and w2 == 2:  # Dim
                    for f3, w3, v3 in _iter_fields(v2):
                        if f3 == 1 and w3 == 0:
                            dims.append(v3)
        elif field == 5:  # float_val (packed or repeated)
            if wire == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif field == 6:  # double_val
            if wire == 2:
                doubles.extend(struct.unpack(f"<{len(val) // 8}d", val))
            else:
                doubles.append(struct.unpack("<d", val)[0])
        elif field == 7:  # int_val (DT_INT32 and narrower)
            ints.extend(_varint_list(val, wire))
        elif field == 10:  # int64_val
            ints.extend(_varint_list(val, wire))
    if dtype == _DT_DOUBLE:
        arr = np.asarray(doubles, dtype=np.float64)
    elif dtype == _DT_FLOAT:
        arr = np.asarray(floats, dtype=np.float32)
    elif dtype == _DT_INT32:
        arr = np.asarray(ints, dtype=np.int32)
    elif dtype == _DT_INT64:
        arr = np.asarray(ints, dtype=np.int64)
    else:
        raise SeldonError(
            f"TF-Serving returned TensorProto dtype {dtype}, which this proxy "
            "does not decode (supported: DT_FLOAT/DT_DOUBLE/DT_INT32/DT_INT64)",
            status_code=502, reason="UPSTREAM_ERROR")
    if dims and int(np.prod(dims)) == arr.size:
        arr = arr.reshape(dims)
    return arr


def decode_predict_response(buf: bytes, output_name: str) -> np.ndarray:
    """tensorflow.serving.PredictResponse: outputs map (field 1); returns the
    named output, or the single output when only one is present."""
    outputs: Dict[str, np.ndarray] = {}
    for field, wire, val in _iter_fields(buf):
        if field == 1 and wire == 2:
            key = ""
            tensor = b""
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 2:
                    key = v2.decode()
                elif f2 == 2 and w2 == 2:
                    tensor = v2
            outputs[key] = decode_tensor_proto(tensor)
    if output_name in outputs:
        return outputs[output_name]
    if len(outputs) == 1:
        return next(iter(outputs.values()))
    raise SeldonError(
        f"TF-Serving response missing output {output_name!r} "
        f"(has {sorted(outputs)})", status_code=502, reason="UPSTREAM_ERROR")


class TFServingProxy(SeldonComponent):
    def __init__(
        self,
        model_uri: str = "",
        rest_endpoint: str = "http://localhost:8501",
        grpc_endpoint: str = "",
        model_name: str = "model",
        signature_name: str = "serving_default",
        model_input: str = "inputs",
        model_output: str = "outputs",
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.rest_endpoint = rest_endpoint.rstrip("/")
        # reference semantics (TfServingProxy.py:35-42): a gRPC endpoint,
        # when given, is the forwarding path
        self.grpc_endpoint = grpc_endpoint
        self.model_name = model_name
        self.signature_name = signature_name
        self.model_input = model_input
        self.model_output = model_output
        self._channel = None

    def _grpc_predict(self, arr: np.ndarray) -> np.ndarray:
        import grpc

        if self._channel is None:
            self._channel = grpc.insecure_channel(self.grpc_endpoint)
        rpc = self._channel.unary_unary(
            "/tensorflow.serving.PredictionService/Predict",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        req = encode_predict_request(
            arr, self.model_name, self.signature_name, self.model_input)
        try:
            resp = rpc(req, timeout=30)
        except grpc.RpcError as e:
            raise SeldonError(
                f"TF-Serving gRPC failed: {e.code()} {e.details()}",
                status_code=502, reason="UPSTREAM_ERROR") from e
        out = decode_predict_response(resp, self.model_output)
        if out.ndim == 1:
            out = out[None, :]
        return out

    def predict(self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
        arr = np.asarray(X)
        if self.grpc_endpoint:
            return self._grpc_predict(arr)
        import requests

        url = f"{self.rest_endpoint}/v1/models/{self.model_name}:predict"
        body = {"signature_name": self.signature_name, "instances": arr.tolist()}
        resp = requests.post(url, json=body, timeout=30)
        if resp.status_code != 200:
            raise SeldonError(
                f"TF-Serving returned {resp.status_code}: {resp.text[:500]}",
                status_code=502,
                reason="UPSTREAM_ERROR",
            )
        payload = resp.json()
        if "predictions" not in payload:
            raise SeldonError(f"TF-Serving response missing predictions: {json.dumps(payload)[:500]}")
        return np.asarray(payload["predictions"])
