"""TENSORFLOW_SERVER proxy (parity: `integrations/tfserving/TfServingProxy.py:
20-125`): forwards predict calls to an external TF-Serving endpoint over
REST ({"instances": ...} -> /v1/models/<name>:predict) or gRPC
(`/tensorflow.serving.PredictionService/Predict`, the reference's stub path
`TfServingProxy.py:35-89`). The gRPC frames are hand-encoded TensorProto /
PredictRequest wire bytes (codec/tensorproto.py) — no tensorflow or
tensorflow-serving-api import, just grpcio — so heterogeneous graphs can
take the external server's fast path without dragging the TF runtime into
the image. In the TPU build this proxy exists for heterogeneous graphs;
native models should use JAX_SERVER.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu.codec.tensorproto import (  # noqa: F401 — re-exported API
    decode_predict_response,
    decode_tensor_proto,
    encode_predict_request,
)
from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError


class TFServingProxy(SeldonComponent):
    def __init__(
        self,
        model_uri: str = "",
        rest_endpoint: str = "http://localhost:8501",
        grpc_endpoint: str = "",
        model_name: str = "model",
        signature_name: str = "serving_default",
        model_input: str = "inputs",
        model_output: str = "outputs",
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.rest_endpoint = rest_endpoint.rstrip("/")
        # reference semantics (TfServingProxy.py:35-42): a gRPC endpoint,
        # when given, is the forwarding path
        self.grpc_endpoint = grpc_endpoint
        self.model_name = model_name
        self.signature_name = signature_name
        self.model_input = model_input
        self.model_output = model_output
        self._channel = None

    def _grpc_predict(self, arr: np.ndarray) -> np.ndarray:
        import grpc

        if self._channel is None:
            self._channel = grpc.insecure_channel(self.grpc_endpoint)
        rpc = self._channel.unary_unary(
            "/tensorflow.serving.PredictionService/Predict",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        req = encode_predict_request(
            arr, self.model_name, self.signature_name, self.model_input)
        try:
            resp = rpc(req, timeout=30)
        except grpc.RpcError as e:
            raise SeldonError(
                f"TF-Serving gRPC failed: {e.code()} {e.details()}",
                status_code=502, reason="UPSTREAM_ERROR") from e
        out = decode_predict_response(resp, self.model_output)
        if out.ndim == 1:
            out = out[None, :]
        return out

    def predict(self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
        # graftlint: allow-host-sync-in-hot-path(request ingress for a remote TF-Serving backend: X is the transport's host payload, no device values exist in this proxy)
        arr = np.asarray(X)
        if self.grpc_endpoint:
            return self._grpc_predict(arr)
        import requests

        url = f"{self.rest_endpoint}/v1/models/{self.model_name}:predict"
        body = {"signature_name": self.signature_name, "instances": arr.tolist()}
        resp = requests.post(url, json=body, timeout=30)
        if resp.status_code != 200:
            raise SeldonError(
                f"TF-Serving returned {resp.status_code}: {resp.text[:500]}",
                status_code=502,
                reason="UPSTREAM_ERROR",
            )
        payload = resp.json()
        if "predictions" not in payload:
            raise SeldonError(f"TF-Serving response missing predictions: {json.dumps(payload)[:500]}")
        # graftlint: allow-host-sync-in-hot-path(response egress from the remote TF-Serving HTTP call: a JSON payload becoming ndarray, nothing device-resident)
        return np.asarray(payload["predictions"])
