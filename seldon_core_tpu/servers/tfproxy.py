"""TENSORFLOW_SERVER proxy (parity: `integrations/tfserving/TfServingProxy.py:
20-125`): forwards predict calls to an external TF-Serving REST endpoint
({"instances": ...} -> /v1/models/<name>:predict). In the TPU build this path
exists for heterogeneous graphs; native models should use JAX_SERVER instead.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError


class TFServingProxy(SeldonComponent):
    def __init__(
        self,
        model_uri: str = "",
        rest_endpoint: str = "http://localhost:8501",
        model_name: str = "model",
        signature_name: str = "serving_default",
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.rest_endpoint = rest_endpoint.rstrip("/")
        self.model_name = model_name
        self.signature_name = signature_name

    def predict(self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
        import requests

        url = f"{self.rest_endpoint}/v1/models/{self.model_name}:predict"
        body = {"signature_name": self.signature_name, "instances": np.asarray(X).tolist()}
        resp = requests.post(url, json=body, timeout=30)
        if resp.status_code != 200:
            raise SeldonError(
                f"TF-Serving returned {resp.status_code}: {resp.text[:500]}",
                status_code=502,
                reason="UPSTREAM_ERROR",
            )
        payload = resp.json()
        if "predictions" not in payload:
            raise SeldonError(f"TF-Serving response missing predictions: {json.dumps(payload)[:500]}")
        return np.asarray(payload["predictions"])
