"""MLflow prepackaged server (parity: `servers/mlflowserver/mlflowserver/
MLFlowServer.py:15-48`): loads a pyfunc model dir, predicts on a DataFrame.
mlflow is not installed in this image; load() raises a clear error.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu import storage
from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError


class MLFlowServer(SeldonComponent):
    def __init__(self, model_uri: str = "", **kwargs):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.ready = False
        self._model = None

    def load(self) -> None:
        if self.ready:
            return
        try:
            import mlflow.pyfunc
        except ImportError as e:
            raise SeldonError(
                "MLFLOW_SERVER requires the mlflow package, which is not installed",
                status_code=500,
            ) from e
        path = storage.download(self.model_uri)
        self._model = mlflow.pyfunc.load_model(path)
        self.ready = True

    def predict(self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
        if not self.ready:
            self.load()
        import pandas as pd

        df = pd.DataFrame(np.asarray(X), columns=list(names) if names else None)
        return np.asarray(self._model.predict(df))
