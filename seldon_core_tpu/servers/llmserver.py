"""LLM_SERVER: autoregressive text-generation prepackaged server.

The BASELINE.json stretch config ("Llama-2-7B Flax jaxserver on v5e-8 pod").
No reference counterpart — the reference's prepackaged servers are
request/response classifiers (`servers/sklearnserver/...`); LLM serving is the
TPU build's native extension, designed around XLA static shapes:

- prompts are bucketed to (batch_bucket, len_bucket) so there is ONE compiled
  prefill program per bucket pair and ONE decode program per batch bucket;
- prefill writes the prompt into the position-tracked KV cache in one pass
  (padded slots carry PAD_POS and are never attended — models/transformer.py);
- decode is a single ``lax.scan`` over steps: per-sequence cache offsets,
  greedy or temperature/top-k sampling, EOS masking inside the scan — no
  per-token Python dispatch;
- tensor parallelism: pass a mesh and the params shard per the model's
  logical axes (parallel.sharding), with activations following under GSPMD.

Long-context serving shards the KV cache itself: with a mesh carrying a
'seq' axis, prefill pins each layer's (k, v, pos) cache to a
NamedSharding that splits the max_len dim across devices, so a context
longer than one device's cache slice serves correctly — decode's attention
over the sharded cache becomes a GSPMD sequence-parallel computation (XLA
inserts the softmax all-reduces over ICI). 'data' shards the batch dim and
'model' the kv_heads dim when they divide. ``attention_impl='ring'``
(ops.ring_attention) remains the cache-less forward/training path.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import OrderedDict
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError

logger = logging.getLogger(__name__)

DEFAULT_LEN_BUCKETS = (32, 128, 512, 2048)
DEFAULT_BATCH_BUCKETS = (1, 4, 8)


class ByteTokenizer:
    """UTF-8 byte fallback tokenizer (ids 0..255): always available, exercises
    the full serving path without a vocab artifact. eos_id defaults to 0."""

    vocab_size = 256

    def __init__(self, eos_id: int = 0):
        self.eos_id = eos_id

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        ids = [int(i) for i in ids if 0 <= int(i) < 256 and int(i) != self.eos_id]
        return bytes(ids).decode("utf-8", errors="replace")


class HFTokenizer:
    """transformers tokenizer adapter (gated import; offline-friendly only if
    the vocab files are local)."""

    def __init__(self, name_or_path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name_or_path)
        # id 0 is usually a real token; with no EOS defined, use -1 so the
        # decode loop's EOS check never fires (generates to max_new_tokens)
        self.eos_id = self._tok.eos_token_id if self._tok.eos_token_id is not None else -1
        self.vocab_size = self._tok.vocab_size

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode([int(i) for i in ids])


def _cast_params(params, param_dtype: str, module_dtype) -> Any:
    """Cast float32 param leaves to the serving dtype ("auto" = the module's
    compute dtype). The module casts weights to its compute dtype inside
    every matmul anyway; pre-casting stores them that way in HBM, halving
    weight-streaming bytes for bf16 models (benchmarks/DECODE_NOTES.md)."""
    if not param_dtype:
        return params
    import jax
    import jax.numpy as jnp

    target = jnp.dtype(module_dtype) if param_dtype == "auto" else jnp.dtype(param_dtype)
    if target == jnp.float32:
        return params

    def cast(leaf):
        if hasattr(leaf, "dtype") and leaf.dtype == jnp.float32:
            return leaf.astype(target)
        return leaf

    return jax.tree.map(cast, params)


def _slot_sampler(top_k: int):
    """The per-slot sampling chain shared by every compiled batcher step
    (`_get_decode_step`, `_get_decode_step_paged`, `_get_spec_step`): one
    key split + top-k categorical per emitted token per slot, greedy under
    temperature <= 0. The speculative verify step is bit-exact vs plain
    decode ONLY while all three sample through this single definition —
    any fork of this code re-opens the parity hazard the CI suites
    (tests/test_batcher_pipeline.py, tests/test_speculative.py) exist to
    catch. generate()'s batch decode keeps its own variant: it draws one
    categorical for the whole batch from a single pre-split key, a
    different (batch-level) chain by design."""
    import jax
    import jax.numpy as jnp

    def sample(keys, lg, temperature):
        greedy = jnp.argmax(lg, axis=-1)
        kk = min(top_k, lg.shape[-1])
        topv, topi = jax.lax.top_k(lg, kk)

        def one(key, tv):
            key, sub = jax.random.split(key)
            return key, jax.random.categorical(
                sub, tv / jnp.maximum(temperature, 1e-6))

        keys, draw = jax.vmap(one)(keys, topv)
        sampled = jnp.take_along_axis(topi, draw[:, None], axis=-1)[:, 0]
        return keys, jnp.where(temperature <= 0.0, greedy, sampled)

    return sample


def fast_forward_key(seed: int, n_tokens: int):
    """The per-request rng key after ``n_tokens`` emitted tokens of a
    seeded generation — the deterministic-resume half of fleet fault
    tolerance (docs/resilience.md). The chain consumes EXACTLY one
    first-component split per emitted token (`_sample_first`'s host draw
    for the first token, then `_slot_sampler`'s per-step split), so
    replaying ``n_tokens`` splits from PRNGKey(seed) lands on the key the
    dead replica's slot held when it died. The caller then draws token
    ``n_tokens`` with `_slot_sampler`'s exact op order (split ->
    lax.top_k -> categorical -> gather); any fork of that order re-opens
    the bit-exactness hazard tests/test_chaos.py pins."""
    import jax

    key = jax.random.PRNGKey(int(seed))
    for _ in range(int(n_tokens)):
        key, _ = jax.random.split(key)
    return key


from seldon_core_tpu.utils import bucket as _bucket  # single bucketing policy


# terminal marker in the dense prefix-cache index trie: an object() can
# never collide with an int token id
_TERM = object()


class _PrefixTrieIndex:
    """Token trie over the dense prefix-cache entry keys, so
    ``_prefix_lookup`` walks the PROMPT once instead of scanning every
    entry (the old OrderedDict scan was O(entries x prefix length) under
    ``_prefix_lock`` — at fleet cache sizes the lock hold time scaled
    with cache population, not prompt length). ``candidates`` returns
    every stored key that is a prefix of the probe, shortest to longest,
    in O(len(probe)) node steps; the caller picks the longest one whose
    entry passes its predicates (dtype/geometry). ``work`` counts node
    visits — the regression signal tests/test_kv_cache.py pins to the
    prompt length, independent of entry count. NOT thread-safe on its
    own: every call happens under the server's ``_prefix_lock``, exactly
    like the OrderedDict it indexes."""

    __slots__ = ("_root", "work")

    def __init__(self):
        self._root: Dict[Any, Any] = {}
        self.work = 0

    def add(self, key: Tuple[int, ...]) -> None:
        node = self._root
        for t in key:
            node = node.setdefault(t, {})
        node[_TERM] = key

    def remove(self, key: Tuple[int, ...]) -> None:
        path = [(None, self._root)]
        node = self._root
        for t in key:
            nxt = node.get(t)
            if nxt is None:
                return
            path.append((t, nxt))
            node = nxt
        node.pop(_TERM, None)
        # prune now-empty suffix nodes so dead entries cost no walk time
        for i in range(len(path) - 1, 0, -1):
            tok, n = path[i]
            if n:
                break
            del path[i - 1][1][tok]

    def candidates(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        node = self._root
        out: List[Tuple[int, ...]] = []
        self.work += 1
        for t in tokens:
            if _TERM in node:
                out.append(node[_TERM])
            node = node.get(t)
            if node is None:
                return out
            self.work += 1
        if _TERM in node:
            out.append(node[_TERM])
        return out

    def clear(self) -> None:
        self._root = {}


# f32 init trees above this stream leaf-by-leaf through the quantizer
# instead of materializing whole (27 GB at 7B vs 16 GB single-chip HBM).
STREAM_INIT_THRESHOLD_BYTES = 2 << 30


class LLMServer(SeldonComponent):
    """Serves a registered transformer-family model for text generation.

    Parameters (graph-spec ``parameters`` or constructor kwargs):
      model_uri: jaxserver-style checkpoint dir (config.json + params) — or
      model + init_random=True for a randomly-initialised model (tests/bench)
      max_new_tokens, temperature, top_k, eos_id, tokenizer ("bytes" or an HF
      name), len_buckets, batch_buckets, mesh (object, programmatic only).
    """

    def __init__(
        self,
        model_uri: str = "",
        model: Optional[str] = None,
        model_kwargs: Optional[Dict[str, Any]] = None,
        init_random: bool = False,
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        top_k: int = 40,
        eos_id: Optional[int] = None,
        tokenizer: str = "bytes",
        len_buckets: Optional[Sequence[int]] = None,
        batch_buckets: Optional[Sequence[int]] = None,
        mesh: Optional[Any] = None,
        topology: Optional[Any] = None,
        tensor_parallel: int = 0,
        sequence_parallel: int = 0,
        quantize: str = "",
        param_dtype: str = "",
        kv_cache_dtype: str = "",
        kv_cache_layout: str = "",
        kv_page_size: int = 0,
        kv_pool_pages: int = 0,
        prefill_chunk: int = 0,
        continuous_batching: int = 0,
        continuous_batching_max_len: int = 0,
        decode_pipeline_depth: int = 2,
        decode_fuse_steps: int = 0,
        spec_mode: str = "",
        spec_k: int = 0,
        spec_ngram: int = 0,
        disaggregation: str = "",
        prefill_devices: int = 0,
        decode_devices: int = 0,
        prefill_workers: int = 0,
        handoff_transport: str = "",
        disagg_mesh: Optional[Any] = None,
        draft_model: Optional[str] = None,
        draft_model_kwargs: Optional[Dict[str, Any]] = None,
        draft_model_uri: str = "",
        prefix_cache_size: int = 0,
        prefix_cache_bytes: int = 0,
        lora_rank: int = 0,
        lora_max_adapters: int = 8,
        lora_adapters: Optional[Dict[str, str]] = None,
        slo_class_weights: Optional[Dict[str, float]] = None,
        tenant_weights: Optional[Dict[str, float]] = None,
        tenant_quota: int = 0,
        tenant_quotas: Optional[Dict[str, int]] = None,
        seed: int = 0,
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.model_name = model
        self.model_kwargs = dict(model_kwargs or {})
        self.init_random = bool(init_random)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.tokenizer_name = tokenizer
        self.len_buckets = tuple(len_buckets or DEFAULT_LEN_BUCKETS)
        self.batch_buckets = tuple(batch_buckets or DEFAULT_BATCH_BUCKETS)
        self.mesh = mesh
        # The injected device-world view (parallel/topology.py). None =
        # adopt the process topology at load(); tests and virtual-mesh
        # harnesses pass their own so the server never re-derives
        # jax.devices() itself.
        self.topology = topology
        # Spec-reachable sharding (typed unit parameters, like JAXServer's
        # tensor_parallel): builds a ('data', 'seq', 'model') mesh at load.
        self.tensor_parallel = int(tensor_parallel)
        self.sequence_parallel = int(sequence_parallel)
        # "int8": weight-only PTQ (ops/quantize.py) — the KV cache and
        # activations stay in the model dtype; only weights go int8 in HBM
        self.quantize = str(quantize or "")
        # Flax init leaves params f32 even for bf16-compute modules. An
        # interleaved A/B on the real chip showed pre-casting to bf16 is
        # SLOWER here (XLA hoists the f32->bf16 convert out of the decode
        # scan, so storage dtype costs nothing per step, and bf16-stored
        # weights landed in worse layouts) — benchmarks/DECODE_NOTES.md.
        # Default is therefore no cast; "auto" casts to the module compute
        # dtype, or pass an explicit dtype, for configs where HBM residency
        # matters more than step time.
        self.param_dtype = param_dtype
        # KV-cache storage: "bf16" (default — model dtype) or "int8"
        # (quantize-on-write, per-head per-position scales; halves the KV
        # read traffic that dominates the b8 decode step —
        # benchmarks/DECODE_NOTES.md). Normalized + validated at load().
        self.kv_cache_dtype = kv_cache_dtype
        # KV-cache layout for the continuous batcher's slot pool: "paged"
        # (default — global pool of fixed-size KV pages addressed through
        # per-slot block tables, so HBM is billed for pages actually written
        # and admission prefill can run in chunks interleaved with decode)
        # or "dense" (the historical [S, max_len, ...] allocation, kept for
        # A/B and parity testing). Normalized + validated at load().
        # generate()'s per-request caches stay dense either way.
        self.kv_cache_layout = kv_cache_layout
        # Tokens per KV page (paged layout; 0 = default 64). The batcher
        # rounds its cache length up to a page multiple.
        self.kv_page_size = int(kv_page_size)
        # Total pages in the global pool (0 = fully provisioned: every slot
        # can reach max_len simultaneously — no oversubscription, never
        # sheds on pages). Smaller pools oversubscribe: more slots per HBM
        # byte, with page-exhaustion shed (503 + Retry-After) as the relief
        # valve — docs/performance.md "Paged KV".
        self.kv_pool_pages = int(kv_pool_pages)
        # Admission prefill chunk size (paged layout; 0 = default 256).
        # A long prompt prefills chunk-by-chunk between decode steps so
        # admission never stalls serving for a whole compile bucket.
        self.prefill_chunk = int(prefill_chunk)
        # >0: serving transports route single-prompt /v1/generate (REST) and
        # jsonData {"prompt": ...} predicts (gRPC) through a shared
        # ContinuousBatcher with this many slots (runtime/batcher.py), so
        # concurrent clients join one in-flight decode batch.
        self.continuous_batching = int(continuous_batching)
        # cache length for the batcher's slot KV (0 = sized from the
        # len_buckets; see ContinuousBatcher.__init__)
        self.continuous_batching_max_len = int(continuous_batching_max_len) or None
        # Decode pipelining (runtime/batcher.py): how many decode steps the
        # batcher keeps dispatched ahead of the host (>=2 hides the
        # dispatch+sync round trip that serialized the served decode at 11%
        # of direct throughput — docs/performance.md "Decode pipelining"),
        # and how many steps to fuse into one device-side lax.scan between
        # host syncs when the admit queue is empty (0/1 = off).
        self.decode_pipeline_depth = int(decode_pipeline_depth)
        self.decode_fuse_steps = int(decode_fuse_steps)
        # Speculative decoding (runtime/batcher.py + _get_spec_step): "off"
        # (default), "ngram" — a zero-weight device-side prompt-lookup
        # proposer over each slot's prompt+generated history — or "draft"
        # — a small draft model (draft_model / draft_model_uri) runs K+1
        # greedy forwards per turn. Either way each batcher turn verifies
        # the K proposed tokens in ONE K+1-token target forward and accepts
        # the longest prefix agreeing with the per-slot sampling chain, so
        # greedy and seeded-sampled outputs stay bit-exact vs generate()
        # while accepted tokens per KV-cache read can exceed 1
        # (docs/performance.md "Speculative decoding"). Normalized +
        # validated at load().
        self.spec_mode = spec_mode
        # draft tokens per verify step (0 = default 4); the verify forward
        # is spec_k + 1 tokens wide
        self.spec_k = int(spec_k)
        # longest n-gram the self-draft proposer matches (0 = default 3)
        self.spec_ngram = int(spec_ngram)
        # Disaggregated prefill/decode (runtime/disagg.py,
        # docs/performance.md "Disaggregated serving"): "remote_prefill"
        # splits the device world into a prefill slice and a decode slice
        # (parallel/mesh.py disaggregated_mesh) — admission prefill runs on
        # prefill-slice workers and the written KV moves device-to-device
        # into the decode slice's pool, so the compute burst never touches
        # the latency-critical decode batch. Bit-exact vs single-slice
        # serving (tests/test_disagg.py). Normalized + validated at load().
        self.disaggregation = disaggregation
        # slice sizing (counts; the prefill slice takes devices from the
        # END of the enumeration, decode from the front; 0 decode = all
        # the rest) — or pass a prebuilt DisaggregatedMesh programmatically
        self.prefill_devices = int(prefill_devices)
        self.decode_devices = int(decode_devices)
        # prefill workers (one thread+device each; 0 = one per
        # prefill-slice device)
        self.prefill_workers = int(prefill_workers)
        # "" / "device" = direct jax.device_put KV handoff (shared
        # topology); "network" = frame the KV bucket and stream it over a
        # socket to the decode host (runtime/disagg.py HandoffReceiver) —
        # bit-exact either way, validated at load()
        self.handoff_transport = handoff_transport
        self.disagg_mesh = disagg_mesh
        # optional draft model: registry name + kwargs (random init on the
        # server's seed) or a jaxserver-style checkpoint dir. Must share
        # the target's vocab — draft proposals index the target's tokens.
        self.draft_model = str(draft_model or "")
        self.draft_model_kwargs = dict(draft_model_kwargs or {})
        self.draft_model_uri = str(draft_model_uri or "")
        # Prefix caching (opt-in): single-prompt requests reuse the KV cache
        # of the longest previously-prefilled token prefix (shared system
        # prompts prefill once); entries are LRU-evicted past this size.
        # Safe to share: jax arrays are immutable, decode never mutates them.
        # Each entry pins full per-layer KV caches of max_len, so the count
        # bound alone can hold multi-GB of HBM — prefix_cache_bytes (default
        # 512 MB whenever the cache is enabled) bounds the total pinned bytes.
        self.prefix_cache_size = int(prefix_cache_size)
        self.prefix_cache_bytes = int(prefix_cache_bytes) or (
            512 * 1024 * 1024 if self.prefix_cache_size else 0)
        self._prefix_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        # longest-prefix lookups walk this trie index in O(prompt) instead
        # of scanning the OrderedDict (which stays for LRU order + byte
        # accounting); membership is mirrored add/remove under _prefix_lock
        self._prefix_index = _PrefixTrieIndex()
        self._prefix_bytes = 0
        self._prefix_lock = threading.Lock()
        self._prefix_hits = 0
        # Batched LoRA multi-tenancy (runtime/adapters.py,
        # docs/multitenancy.md): lora_rank > 0 builds an AdapterRegistry
        # at load() — a dense [lora_max_adapters, ...] HBM pool of
        # low-rank q/o/FFN deltas gathered per slot inside the shared
        # decode/prefill/verify programs (adapter id 0 = identity).
        # ``lora_adapters`` maps name -> storage URI, preloaded at load().
        self.lora_rank = int(lora_rank)
        self.lora_max_adapters = int(lora_max_adapters)
        self.lora_adapters = dict(lora_adapters or {})
        self.adapter_registry: Optional[Any] = None
        # SLO-aware weighted-fair scheduling (runtime/scheduler.py): the
        # continuous batcher's admission queue orders requests by SLO
        # class ("interactive" latency-sensitive vs "batch" throughput)
        # and tenant under stride-scheduled weighted fairness, with
        # per-tenant queue quotas shedding 503 + Retry-After on breach.
        self.slo_class_weights = dict(slo_class_weights or {})
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_quota = int(tenant_quota)
        self.tenant_quotas = dict(tenant_quotas or {})
        self.seed = int(seed)
        self.ready = False
        self._eos_override = eos_id
        self._prefill_cache: Dict[Tuple[int, int], Any] = {}
        self._decode_cache: Dict[Tuple[int, int], Any] = {}
        self._request_count = 0
        # decode observability (metrics.registry sync_llm drains these at
        # /metrics scrape time): per-step wall times and the KV bytes the
        # last decode streamed per step
        from collections import deque

        self._decode_step_times: Any = deque(maxlen=4096)
        self._last_decode_kv_bytes = 0
        # pipelined-decode observability (batcher): per-call dispatch wall
        # (enqueue only, no sync), per-drain host sync wall, and the number
        # of steps in flight observed at each drain (host lag)
        self._decode_dispatch_times: Any = deque(maxlen=4096)
        self._decode_sync_times: Any = deque(maxlen=4096)
        self._decode_host_lag: Any = deque(maxlen=4096)
        # speculative decode observability: tokens accepted by each drained
        # verify step (drained into the accepted-tokens-per-step histogram
        # at /metrics scrape time, like the step-time deques above)
        self._spec_accepted: Any = deque(maxlen=4096)
        # streaming-latency observability (batcher on_token path): time to
        # first token per request and the gap before each surfaced token —
        # the headline pair disaggregation/chunked-prefill move
        # (seldon_llm_ttft_seconds / seldon_llm_inter_token_seconds)
        self._ttft_times: Any = deque(maxlen=4096)
        self._inter_token_times: Any = deque(maxlen=8192)
        # per-SLO-class TTFT observations (multi-tenant serving): the
        # batcher appends (class, ttft) pairs at first-token commit; the
        # scrape drains them into seldon_llm_tenant_ttft_seconds{slo_class}
        self._ttft_by_class: Any = deque(maxlen=4096)
        # disaggregated serving: per-handoff wall (prefill-slice compute +
        # device-to-device transfer + decode-side import)
        self._handoff_times: Any = deque(maxlen=4096)
        # per-device committed param copies for prefill-slice workers
        # (runtime/disagg.py); built on first use under its own lock
        self._device_params: Dict[Any, Any] = {}
        self._device_params_lock = threading.Lock()

    # ------------------------------------------------------------------
    def load(self) -> None:
        if self.ready:
            return
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models import get_model
        from seldon_core_tpu.models.transformer import normalize_kv_cache_dtype
        from seldon_core_tpu.parallel.topology import get_topology

        # Resolve the device-world view ONCE; everything below (mesh
        # construction, disagg splits, the batcher's placement defaults)
        # consumes it instead of re-deriving jax.devices().
        # racelint: allow-unguarded-shared-state(load()-time config normalization: runs once, before any serving thread or batcher loop exists — nothing can interleave with it)
        self.topology = self.topology or get_topology()
        topo = self.topology

        # Validate dtype knobs HERE, with a clear ValueError, instead of
        # letting an unknown string explode later inside a jitted cast or
        # cache init (where the traceback names nothing actionable).
        from seldon_core_tpu.models.transformer import normalize_kv_cache_layout

        # racelint: allow-unguarded-shared-state(load()-time config normalization: runs once, before any serving thread or batcher loop exists — nothing can interleave with it)
        self.kv_cache_dtype = normalize_kv_cache_dtype(self.kv_cache_dtype)
        # racelint: allow-unguarded-shared-state(load()-time config normalization: runs once, before any serving thread or batcher loop exists — nothing can interleave with it)
        self.kv_cache_layout = normalize_kv_cache_layout(self.kv_cache_layout)
        if self.kv_page_size < 0:
            raise ValueError(
                f"kv_page_size={self.kv_page_size} must be >= 0 "
                f"(0 = default page size)")
        if self.kv_pool_pages < 0:
            raise ValueError(
                f"kv_pool_pages={self.kv_pool_pages} must be >= 0 "
                f"(0 = fully provisioned pool)")
        if self.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk={self.prefill_chunk} must be >= 0 "
                f"(0 = default chunk size)")
        if self.param_dtype and self.param_dtype != "auto":
            try:
                jnp.dtype(self.param_dtype)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"unknown param_dtype {self.param_dtype!r}: expected '', "
                    f"'auto', or a jax dtype name (e.g. 'bfloat16')"
                ) from e
        if self.decode_pipeline_depth < 1:
            raise ValueError(
                f"decode_pipeline_depth={self.decode_pipeline_depth} must be "
                f">= 1 (1 = serial dispatch-then-sync, >=2 pipelines)"
            )
        if self.decode_fuse_steps < 0:
            raise ValueError(
                f"decode_fuse_steps={self.decode_fuse_steps} must be >= 0 "
                f"(0/1 = no fusing)"
            )
        from seldon_core_tpu.runtime.spec import normalize_spec_mode

        # racelint: allow-unguarded-shared-state(load()-time config normalization: runs once, before any serving thread or batcher loop exists — nothing can interleave with it)
        self.spec_mode = normalize_spec_mode(self.spec_mode)
        if self.spec_k < 0:
            raise ValueError(
                f"spec_k={self.spec_k} must be >= 0 (0 = default draft "
                f"depth when speculation is on)")
        if self.spec_ngram < 0:
            raise ValueError(
                f"spec_ngram={self.spec_ngram} must be >= 0 (0 = default "
                f"3-gram prompt lookup)")
        if self.spec_mode == "draft" and not (
                self.draft_model or self.draft_model_uri):
            raise ValueError(
                "spec_mode='draft' needs a draft model: pass draft_model="
                "<registry name> (+ draft_model_kwargs) or draft_model_uri")
        from seldon_core_tpu.runtime.disagg import normalize_disaggregation

        # racelint: allow-unguarded-shared-state(load()-time config normalization: runs once, before any serving thread or batcher loop exists — nothing can interleave with it)
        self.disaggregation = normalize_disaggregation(self.disaggregation)
        if self.prefill_devices < 0 or self.decode_devices < 0 or \
                self.prefill_workers < 0:
            raise ValueError(
                f"prefill_devices={self.prefill_devices} / decode_devices="
                f"{self.decode_devices} / prefill_workers="
                f"{self.prefill_workers} must be >= 0")
        if self.lora_rank < 0:
            raise ValueError(
                f"lora_rank={self.lora_rank} must be >= 0 (0 = adapters "
                f"off)")
        if self.lora_rank > 0:
            if self.disaggregation not in ("", "off"):
                raise ValueError(
                    "lora_rank > 0 does not yet compose with "
                    "disaggregation='remote_prefill': the adapter pool "
                    "lives on the decode slice and prefill-slice workers "
                    "would need committed replicas — a follow-up")
            if int(self.model_kwargs.get("n_experts", 0) or 0) > 0:
                raise ValueError(
                    "lora_rank > 0 does not support MoE FFNs: adapters "
                    "target the dense q/o/FFN projections")
        from seldon_core_tpu.runtime.scheduler import normalize_slo_class

        for cls in self.slo_class_weights:
            normalize_slo_class(cls)  # unknown class names fail at load()
        if self.disaggregation != "off":
            if self.tensor_parallel > 1 or self.sequence_parallel > 1 \
                    or self.mesh is not None:
                raise ValueError(
                    "disaggregation='remote_prefill' does not yet compose "
                    "with tensor/sequence parallelism or an explicit mesh: "
                    "the batcher's slot pool is single-device per slice — "
                    "shard WITHIN a slice is a follow-up")
            if self.disagg_mesh is None and topo.device_count < 2:
                raise ValueError(
                    "disaggregation='remote_prefill' needs >= 2 devices "
                    "(one per slice); this process sees "
                    f"{topo.device_count}")
        if self.handoff_transport not in ("", "device", "network"):
            raise ValueError(
                f"unknown handoff_transport {self.handoff_transport!r}: "
                "expected '', 'device' or 'network'")
        if self.handoff_transport == "network" \
                and self.disaggregation == "off":
            raise ValueError(
                "handoff_transport='network' only applies to "
                "disaggregation='remote_prefill' (there is no KV handoff "
                "without a prefill/decode split)")

        cfg_kwargs = dict(self.model_kwargs)
        name = self.model_name
        params = None
        if self.model_uri:
            from seldon_core_tpu import storage

            path = storage.download(self.model_uri)
            with open(os.path.join(path, "config.json")) as f:
                file_cfg = json.load(f)
            name = name or file_cfg["model"]
            cfg_kwargs = {**file_cfg.get("kwargs", {}), **cfg_kwargs}
            params = self._load_params(path, name, cfg_kwargs)
        if name is None:
            raise SeldonError("LLMServer needs model_uri or model=<registry name>", status_code=500)

        self._module = get_model(name, **cfg_kwargs)
        self._cfg = self._module.cfg

        # Big-config random init (e.g. Llama-2-7B dims for capacity/perf
        # work): whole-tree f32 init is 4 bytes/param — 27 GB at 7B, over
        # single-chip HBM — so when the int8 serving path is requested and
        # the f32 tree would exceed 2 GiB, initialize leaf-by-leaf on
        # device, quantizing each leaf as it is made. Peak residency is the
        # final int8 tree plus one f32 leaf.
        streamed = (
            params is None
            and self.init_random
            and self.quantize == "int8"
            and self._init_nbytes_f32() > STREAM_INIT_THRESHOLD_BYTES
        )
        if params is None and not streamed:
            if not self.init_random:
                raise SeldonError(
                    "No checkpoint: pass model_uri or init_random=True", status_code=500
                )
            params = jax.jit(self._module.init)(
                jax.random.PRNGKey(self.seed), jnp.zeros((1, 8), jnp.int32)
            )

        if not streamed:
            params = _cast_params(params, self.param_dtype, self._cfg.dtype)

        if self.mesh is None and (self.tensor_parallel > 1 or self.sequence_parallel > 1):
            tp = max(self.tensor_parallel, 1)
            sp = max(self.sequence_parallel, 1)
            n = topo.device_count
            if n % (tp * sp):
                raise SeldonError(
                    f"tensor_parallel={tp} * sequence_parallel={sp} does not "
                    f"divide {n} available devices",
                    status_code=500,
                )
            self.mesh = topo.mesh({"data": -1, "seq": sp, "model": tp})

        # quantize BEFORE sharding: shard_params understands QuantizedTensor
        # leaves (q under the weight's logical spec, scale under the channel
        # axis), so int8 + tensor parallelism compose.
        self._dequant = lambda p: p
        if self.quantize:
            if self.quantize != "int8":
                raise SeldonError(f"unsupported quantize={self.quantize!r} (int8 only)", status_code=500)
            from seldon_core_tpu.ops.quantize import dequantize_params, quantize_params

            params = self._streamed_quantized_init() if streamed else quantize_params(params)
            self._dequant = dequantize_params

        if self.mesh is not None:
            from seldon_core_tpu.parallel.sharding import logical_axis_tree, shard_params

            logical = logical_axis_tree(self._module, jax.ShapeDtypeStruct((1, 8), jnp.int32))
            params = shard_params(params, self.mesh, logical)
        self._params = params

        # Draft model for spec_mode="draft": loaded alongside the target,
        # replicated (it is small by construction — sharding it would cost
        # more in collectives than its forwards). Random init reuses the
        # server seed, so a draft configured identically to the target is
        # a bit-identical copy (the perfect-drafter fixture in
        # tests/test_speculative.py).
        self._draft_module = None
        self._draft_params = None
        self._draft_dequant = lambda p: p
        if self.draft_model or self.draft_model_uri:
            dname = self.draft_model or None
            dkw = dict(self.draft_model_kwargs)
            dparams = None
            if self.draft_model_uri:
                from seldon_core_tpu import storage

                dpath = storage.download(self.draft_model_uri)
                with open(os.path.join(dpath, "config.json")) as f:
                    dfile = json.load(f)
                dname = dname or dfile["model"]
                dkw = {**dfile.get("kwargs", {}), **dkw}
                dparams = self._load_params(dpath, dname, dkw)
            self._draft_module = get_model(dname, **dkw)
            self._draft_cfg = self._draft_module.cfg
            if self._draft_cfg.vocab_size != self._cfg.vocab_size:
                raise ValueError(
                    f"draft model vocab {self._draft_cfg.vocab_size} != "
                    f"target vocab {self._cfg.vocab_size}: draft proposals "
                    f"index the target's token space")
            if dparams is None:
                dparams = jax.jit(self._draft_module.init)(
                    jax.random.PRNGKey(self.seed), jnp.zeros((1, 8), jnp.int32))
            self._draft_params = _cast_params(
                dparams, self.param_dtype, self._draft_cfg.dtype)

        # Batched LoRA pool: built after params so pool dtype follows the
        # module compute dtype; preloads any configured adapter URIs
        # through the storage layer. A registry exists exactly when
        # lora_rank > 0 — the batcher keys its adapted-program choice on
        # ``adapter_registry is not None``.
        if self.lora_rank > 0:
            from seldon_core_tpu.runtime.adapters import AdapterRegistry

            # racelint: allow-unguarded-shared-state(load()-time build: runs once, before any serving thread or batcher loop exists)
            self.adapter_registry = AdapterRegistry(
                self._cfg, self.lora_rank, self.lora_max_adapters)
            for aname, uri in self.lora_adapters.items():
                self.adapter_registry.load_uri(aname, uri)

        if self.tokenizer_name == "bytes":
            self._tokenizer = ByteTokenizer()
        else:
            self._tokenizer = HFTokenizer(self.tokenizer_name)
        self.eos_id = self._eos_override if self._eos_override is not None else self._tokenizer.eos_id
        self.ready = True
        logger.info("LLMServer loaded %s (vocab=%d)", name, self._cfg.vocab_size)

    def _params_on(self, device):
        """Committed copy of the serving params on ``device`` (cached —
        one copy per prefill-slice device, built on a worker's first job).
        Disaggregation pays this duplication deliberately: on a real pod
        each slice owns its HBM anyway, and replicating the weights is
        what lets the prefill burst run without touching the decode
        slice. The cache is lock-guarded: two workers' first jobs race
        the build, and losing a copy would device_put the tree twice."""
        import jax

        with self._device_params_lock:
            params = self._device_params.get(device)
            if params is None:
                params = jax.device_put(self._params, device)
                self._device_params[device] = params
            return params

    def _init_shapes(self):
        import jax
        import jax.numpy as jnp

        return jax.eval_shape(
            self._module.init, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )

    def _init_nbytes_f32(self) -> int:
        import jax

        return sum(leaf.size * 4 for leaf in jax.tree.leaves(self._init_shapes()))

    def _streamed_quantized_init(self):
        """Leaf-by-leaf on-device random init + int8 quantize.

        Semantics match the whole-tree path in kind (≥2-D float leaves
        become QuantizedTensor, 1-D leaves stay float) but not in exact
        values: leaves draw from per-leaf keys (seed folded with the leaf
        path) with variance-scaled normals (std = 1/sqrt(fan_in)) for ≥2-D
        leaves, ones for 1-D scale/weight (norm) leaves, zeros otherwise.
        jit caches by (shape, std), so the 32 identical layers of a 7B
        config cost ~a dozen compiles, not ~200."""
        import zlib
        from functools import partial as _partial

        import jax
        import jax.numpy as jnp
        from jax.tree_util import keystr, tree_flatten_with_path

        from seldon_core_tpu.ops.quantize import _register_pytree, quantize_array

        _register_pytree()  # jit returns QuantizedTensor leaves
        target = jnp.dtype(self._cfg.dtype) if self.param_dtype == "auto" else (
            jnp.dtype(self.param_dtype) if self.param_dtype else jnp.float32
        )

        @_partial(jax.jit, static_argnums=(1, 2))
        def make_quantized(key, shape, std):
            w = jax.random.normal(key, shape, jnp.float32) * std
            return quantize_array(w.astype(target))

        flat, treedef = tree_flatten_with_path(self._init_shapes())
        root = jax.random.PRNGKey(self.seed)
        leaves = []
        for path, spec in flat:
            name = keystr(path)
            if jnp.issubdtype(spec.dtype, jnp.floating) and spec.ndim >= 2:
                key = jax.random.fold_in(root, zlib.crc32(name.encode()) & 0x7FFFFFFF)
                fan_in = int(np.prod(spec.shape[:-1]))
                leaves.append(make_quantized(key, spec.shape, 1.0 / float(fan_in) ** 0.5))
            elif jnp.issubdtype(spec.dtype, jnp.floating):
                fill = 1.0 if ("norm" in name.lower() or "scale" in name.lower()
                               or name.lower().endswith("weight']")) else 0.0
                # target, not spec.dtype: the whole-tree path casts 1-D f32
                # leaves through _cast_params too, and the two init paths
                # must serve with the same norm-weight dtype
                leaves.append(jnp.full(
                    spec.shape, fill,
                    target if spec.dtype == jnp.float32 else spec.dtype))
            else:
                leaves.append(jnp.zeros(spec.shape, spec.dtype))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _load_params(self, path: str, name: str, cfg_kwargs: Dict[str, Any]):
        orbax_dir = os.path.join(path, "params")
        if os.path.isdir(orbax_dir):
            import orbax.checkpoint as ocp

            return ocp.StandardCheckpointer().restore(os.path.abspath(orbax_dir))
        msgpack = os.path.join(path, "params.msgpack")
        if os.path.exists(msgpack):
            import flax.serialization
            import jax
            import jax.numpy as jnp

            from seldon_core_tpu.models import get_model

            module = get_model(name, **cfg_kwargs)
            target = jax.eval_shape(
                lambda: module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
            )
            target = jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), target)
            with open(msgpack, "rb") as f:
                blob = f.read()
            try:
                return flax.serialization.from_bytes(target, blob)
            except ValueError as orig:
                # checkpoint may hold only the 'params' collection (e.g. a
                # converted HF checkpoint); if the subtree restore also
                # fails, surface the ORIGINAL diagnostic (shape mismatch /
                # corruption), not the fallback's
                if "params" not in target:
                    raise
                try:
                    return flax.serialization.from_bytes({"params": target["params"]}, blob)
                except ValueError:
                    raise orig
        raise SeldonError(f"No params under {path}", status_code=500)

    # ------------------------------------------------------------------
    # Compiled stages
    # ------------------------------------------------------------------
    def _cache_shardings(self, b: int, max_len: int):
        """NamedSharding tree for the per-layer (k, v, pos) caches: max_len
        over 'seq' (the long-context axis), batch over 'data', kv_heads over
        'model' — each only when the mesh has that axis and it divides the
        dim. Returns None when the mesh can't shard anything."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        shape = dict(self.mesh.shape)

        def axis(name: str, dim: int):
            size = shape.get(name, 1)
            return name if size > 1 and dim % size == 0 else None

        dp = axis("data", b)
        sp = axis("seq", max_len)
        tp = axis("model", self._cfg.n_kv_heads)
        if not (dp or sp or tp):
            return None
        kv = NamedSharding(self.mesh, P(dp, sp, tp, None))
        pos = NamedSharding(self.mesh, P(dp, sp))
        if self.kv_cache_dtype == "int8":
            # int8 layout adds f32 [b, max_len, kvh] scale planes, sharded
            # alongside their values
            scale = NamedSharding(self.mesh, P(dp, sp, tp))
            return [(kv, scale, kv, scale, pos) for _ in range(self._cfg.n_layers)]
        return [(kv, kv, pos) for _ in range(self._cfg.n_layers)]

    def _get_extend(self, b: int, slen: int, max_len: int, donate: bool = False):
        """Suffix prefill: write ``slen`` tokens into an EXISTING cache at
        offset ``start`` (prefix-cache continuation). Padded slots carry
        PAD_POS positions, so they are never attended.

        ``donate=True`` donates the input cache buffers to the output (the
        scatter updates in place instead of copying the whole cache) — only
        safe when the caller's caches are NOT shared, so the prefix-cache
        continuation path (whose input caches stay live as a stored prefix
        entry) keeps the copying default."""
        key = ("extend", b, slen, max_len, donate)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        import jax

        module = self._module
        deq = self._dequant

        @partial(jax.jit, donate_argnums=(1,) if donate else ())
        def extend(params, caches, tokens, positions, start):
            logits, caches = module.apply(
                deq(params), tokens, positions=positions, caches=caches,
                cache_index=start,
            )
            return logits, caches

        self._prefill_cache[key] = extend
        return extend

    @staticmethod
    def _entry_nbytes(caches, last_logits) -> int:
        n = int(getattr(last_logits, "nbytes", 0))
        for layer in caches:
            for arr in layer:
                n += int(getattr(arr, "nbytes", 0))
        return n

    def clear_prefix_cache(self) -> None:
        """Drop every cached prefix AND its byte accounting. Clearing the
        OrderedDict directly instead leaves ``_prefix_bytes`` stuck at the
        old total, and once that phantom total nears the budget every later
        store immediately self-evicts — a permanent, silent 0% hit rate
        (found at 7B where one entry is ~300 MB of the 512 MB default).
        The continuous batcher's radix prefix cache (runtime/radix.py)
        clears alongside: both layers must read as cold together."""
        with self._prefix_lock:
            self._prefix_cache.clear()
            self._prefix_index.clear()
            self._prefix_bytes = 0
        svc = getattr(self, "_batcher_service", None)
        radix = getattr(svc.batcher, "_radix", None) if svc is not None else None
        if radix is not None:
            radix.clear()

    def _prefix_lookup(self, tokens: List[int],
                       max_len: Optional[int] = None):
        """Longest cached prefix of ``tokens`` with a compatible
        kv_cache_dtype; returns (prefix_len, entry_max_len, caches,
        last_logits) or None. With ``max_len`` set, only entries of exactly
        that cache length serve — generate()'s dense path reuses the whole
        cache object, so its geometry must match. Exact full-prompt hits
        return the stored logits so prefill is skipped entirely. The dtype
        check matters: a bf16 3-tuple cache fed to an int8-configured
        decode (or vice versa) would be structurally wrong, so a dtype
        flip must read as a miss, never a crash.

        Lookup walks the trie index (one pass over the prompt, O(prompt)
        node steps) instead of scanning entries: the lock hold time no
        longer scales with cache population
        (tests/test_kv_cache.py pins the regression). The continuous
        batcher does NOT call this — its prefix reuse is the page-pool
        radix trie (runtime/radix.py), which shares pages instead of
        reusing dense cache objects."""
        with self._prefix_lock:
            best = None
            for key in self._prefix_index.candidates(tokens):
                entry_max_len, entry_kvd, caches, last_logits, _nb = \
                    self._prefix_cache[key]
                if entry_kvd != self.kv_cache_dtype:
                    continue
                if max_len is not None and entry_max_len != max_len:
                    continue
                # candidates arrive shortest -> longest: the last passer
                # is the longest compatible prefix
                best = (len(key), entry_max_len, caches, last_logits)
            if best is not None:
                self._prefix_cache.move_to_end(tuple(tokens[: best[0]]))
                # hit accounting lives under the same lock as the cache it
                # describes (concurrent generate() calls race the bump)
                self._prefix_hits += 1
            return best

    def _prefix_store(self, tokens: List[int], max_len: int, caches, last_logits):
        key = tuple(tokens)
        nbytes = self._entry_nbytes(caches, last_logits)
        if self.prefix_cache_bytes and nbytes > self.prefix_cache_bytes:
            # A single over-budget entry would evict everything else. Warn
            # (once) instead of silently never populating: a large-model
            # config can exceed the default budget on every entry, which
            # would otherwise look like a mysterious 0% hit rate.
            if not getattr(self, "_prefix_overbudget_warned", False):
                self._prefix_overbudget_warned = True
                logger.warning(
                    "prefix cache entry (%d bytes) exceeds prefix_cache_bytes "
                    "(%d); nothing will be cached — raise prefix_cache_bytes "
                    "for this model size", nbytes, self.prefix_cache_bytes)
            return
        with self._prefix_lock:
            old = self._prefix_cache.pop(key, None)
            if old is not None:
                self._prefix_bytes -= old[-1]
            else:
                self._prefix_index.add(key)
            self._prefix_cache[key] = (
                max_len, self.kv_cache_dtype, caches, last_logits, nbytes)
            self._prefix_bytes += nbytes
            while self._prefix_cache and (
                len(self._prefix_cache) > self.prefix_cache_size
                or (self.prefix_cache_bytes
                    and self._prefix_bytes > self.prefix_cache_bytes)
            ):
                evicted_key, entry = self._prefix_cache.popitem(last=False)
                self._prefix_index.remove(evicted_key)
                self._prefix_bytes -= entry[-1]

    def _get_prefill(self, b: int, plen: int, max_len: int,
                     lora: bool = False):
        """``lora=True`` compiles the adapted variant: two extra trailing
        args (adapter_pool pytree, adapter_ids [b]) apply each sequence's
        low-rank q/o/FFN delta inside the same program
        (models/transformer.py ``lora_delta``)."""
        key = (b, plen, max_len, lora)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        import jax

        from seldon_core_tpu.models.transformer import init_kv_caches

        module, cfg = self._module, self._cfg
        deq = self._dequant

        kvd = self.kv_cache_dtype

        if lora:
            def prefill(params, tokens, positions, adapter_pool, adapter_ids):
                caches = init_kv_caches(cfg, tokens.shape[0], max_len, kvd)
                logits, caches = module.apply(
                    deq(params), tokens, positions=positions, caches=caches,
                    cache_index=0, adapters=adapter_pool,
                    adapter_ids=adapter_ids,
                )
                return logits, caches
        else:
            def prefill(params, tokens, positions):
                caches = init_kv_caches(cfg, tokens.shape[0], max_len, kvd)
                logits, caches = module.apply(
                    deq(params), tokens, positions=positions, caches=caches, cache_index=0
                )
                return logits, caches

        cache_shardings = self._cache_shardings(b, max_len)
        if cache_shardings is not None:
            # pin the cache layout at the jit boundary: decode then runs
            # sequence-parallel attention over the sharded slices
            fn = jax.jit(prefill, out_shardings=(None, cache_shardings))
        else:
            fn = jax.jit(prefill)
        self._prefill_cache[key] = fn
        return fn

    def _get_decode(self, b: int, max_len: int, donate: bool = True):
        """Compiled decode scan. ``donate=True`` (default) donates the input
        cache pytree to the output: XLA aliases the buffers, so the per-step
        ``dynamic_update_slice`` writes reuse the prefill's cache in place
        instead of copying the whole multi-GB cache into the scan carry.
        generate() passes donate=False only when the caches are shared with
        the prefix cache (a donated buffer is dead to later readers). The
        token/position arrays canNOT be donated here — the scan returns only
        (tokens, caches), so they have no matching output buffer; the
        pipelined per-step variant (``_get_decode_step``) is the one that
        threads and donates that state."""
        key = (b, max_len, donate)
        fn = self._decode_cache.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        module = self._module
        eos_id = self.eos_id
        top_k = self.top_k
        deq = self._dequant

        def decode(params, caches, last_tok, true_len, n_steps, rng, temperature):
            """last_tok [b], true_len [b]; returns (tokens [b, n_steps],
            final caches — returned so donation can alias input to output)."""

            def sample(logits, key):
                greedy = jnp.argmax(logits, axis=-1)
                k = min(top_k, logits.shape[-1])
                topv, topi = jax.lax.top_k(logits, k)
                draw = jax.random.categorical(key, topv / jnp.maximum(temperature, 1e-6))
                sampled = jnp.take_along_axis(topi, draw[:, None], axis=-1)[:, 0]
                return jnp.where(temperature <= 0.0, greedy, sampled)

            def step(carry, _):
                caches, tok, offset, done, key = carry
                positions = (true_len + offset)[:, None]
                cache_index = true_len + offset
                # dequant inside the scan body: the int8 copy is the one that
                # persists in HBM (hoisting the f32 copy out of the loop
                # would double weight residency for the whole decode)
                logits, caches = module.apply(
                    deq(params), tok[:, None], positions=positions, caches=caches,
                    cache_index=cache_index,
                )
                key, sub = jax.random.split(key)
                nxt = sample(logits[:, -1].astype(jnp.float32), sub)
                nxt = jnp.where(done, eos_id, nxt)
                done = done | (nxt == eos_id)
                return (caches, nxt, offset + 1, done, key), nxt

            done0 = jnp.zeros_like(last_tok, dtype=bool)
            (caches, _, _, _, _), toks = jax.lax.scan(
                step, (caches, last_tok, jnp.zeros_like(true_len), done0, rng), None,
                length=n_steps,
            )
            # the final caches are in the output ONLY so donate_argnums can
            # alias the cache argument onto them (input_output_alias in the
            # compiled HLO); generate() discards them
            return toks.T, caches  # [b, n_steps], caches

        donate_kw = dict(donate_argnums=(1,)) if donate else {}
        cache_shardings = self._cache_shardings(b, max_len)
        if cache_shardings is not None:
            # keep the scan carry on the prefill's sharded layout instead of
            # letting XLA gather the cache onto every device
            decode = jax.jit(
                decode,
                static_argnames=("n_steps",),
                in_shardings=(None, cache_shardings, None, None, None, None),
                **donate_kw,
            )
        else:
            decode = partial(jax.jit, static_argnames=("n_steps",), **donate_kw)(decode)
        self._decode_cache[key] = decode
        return decode

    def _get_decode_step(self, slots: int, max_len: int, k: int = 1,
                         lora: bool = False):
        """Compiled pipelined decode step for the ContinuousBatcher: runs
        ``k`` decode micro-steps device-side (``lax.scan``) over ``slots``
        cache slots, with the sampling state IN the loop — per-slot rng
        keys, last token and next position all live on device and are
        threaded from output to input across calls, so the host never
        round-trips token/position state through NumPy between steps.

        Returns ``(caches, last_tok, next_pos, keys, tokens[slots, k])``.
        The cache pytree, position array and key array are donated (the
        per-step scatter updates in place; the caller reassigns from the
        outputs). ``last_tok`` is deliberately NOT donated: the stacked
        ``tokens`` output can alias the final-token carry buffer (reshape
        bitcasts), and the host reads ``tokens`` while the next step — which
        would invalidate a donated ``last_tok`` — is already in flight.

        Per-slot sampling reproduces generate()'s chain exactly (split then
        top-k categorical per step, one key per sequence), so a slot seeded
        like a generate() request emits identical tokens — the parity bar in
        tests/test_batcher_pipeline.py. The donation/transfer/dtype shape of
        the COMPILED step is pinned by the llm.decode_step_s4 contract in
        tools/hlolint (docs/static-analysis.md): changing the carry
        structure here must keep every donated leaf aliasable or CI goes
        red on the dropped donation."""
        key = ("pipestep", slots, max_len, k, lora)
        fn = self._decode_cache.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        module = self._module
        top_k = self.top_k
        deq = self._dequant

        def core(params, caches, last_tok, next_pos, keys, temperature,
                 adapter_pool, adapter_ids):
            sample = _slot_sampler(top_k)

            def step(carry, _):
                caches, tok, pos, keys = carry
                logits, caches = module.apply(
                    deq(params), tok[:, None], positions=pos[:, None],
                    caches=caches, cache_index=pos,
                    adapters=adapter_pool, adapter_ids=adapter_ids,
                )
                keys, nxt = sample(keys, logits[:, -1].astype(jnp.float32),
                                   temperature)
                return (caches, nxt, pos + 1, keys), nxt

            (caches, tok, pos, keys), toks = jax.lax.scan(
                step, (caches, last_tok, next_pos, keys), None, length=k)
            return caches, tok, pos, keys, toks.T  # tokens [slots, k]

        if lora:
            # adapted variant (llm.lora_decode_step hlolint contract): the
            # pool/id args are NOT donated — the pool is the registry's
            # long-lived shared state and the ids array is host-managed
            # like the block tables
            @partial(jax.jit, donate_argnums=(1, 3, 4))
            def decode_step(params, caches, last_tok, next_pos, keys,
                            temperature, adapter_pool, adapter_ids):
                return core(params, caches, last_tok, next_pos, keys,
                            temperature, adapter_pool, adapter_ids)
        else:
            @partial(jax.jit, donate_argnums=(1, 3, 4))
            def decode_step(params, caches, last_tok, next_pos, keys,
                            temperature):
                return core(params, caches, last_tok, next_pos, keys,
                            temperature, None, None)

        self._decode_cache[key] = decode_step
        return decode_step

    def _get_prefill_chunk(self, chunk: int, n_pages: int,
                           lora: bool = False):
        """Compiled chunked-prefill step for the PAGED continuous batcher:
        write ``chunk`` prompt tokens (one sequence, PAD_POS padding) into
        the global page pool through the slot's block-table row, reading the
        earlier chunks' KV back from the pool — so a long admission prefill
        runs piecewise between decode steps instead of stalling serving for
        its whole compile bucket (Sarathi-Serve-style chunked prefill;
        Agrawal et al., OSDI 2024). The pool pytree is donated: the scatter
        updates in place, and the batcher threads the returned pool into
        the next dispatch. Returns (logits [1, chunk, vocab], pools)."""
        key = ("pchunk", chunk, n_pages, lora)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        import jax

        module = self._module
        deq = self._dequant

        if lora:
            # adapted chunked prefill: the admitted sequence's adapter id
            # rides as a [1] array so its q/o/FFN deltas shape the hidden
            # states its KV is computed FROM (the k/v projections stay
            # base — runtime/adapters.py, the KV-purity invariant)
            @partial(jax.jit, donate_argnums=(1,))
            def prefill_chunk(params, pools, block_row, tokens, positions,
                              adapter_pool, adapter_ids):
                logits, pools = module.apply(
                    deq(params), tokens, positions=positions, caches=pools,
                    block_tables=block_row, adapters=adapter_pool,
                    adapter_ids=adapter_ids,
                )
                return logits, pools
        else:
            @partial(jax.jit, donate_argnums=(1,))
            def prefill_chunk(params, pools, block_row, tokens, positions):
                logits, pools = module.apply(
                    deq(params), tokens, positions=positions, caches=pools,
                    block_tables=block_row,
                )
                return logits, pools

        self._prefill_cache[key] = prefill_chunk
        return prefill_chunk

    def _get_handoff_import(self, n_pages: int,
                            staged_pages: Optional[int] = None):
        """Compiled decode-side KV-handoff import for DISAGGREGATED serving
        (runtime/disagg.py): copy a prefill worker's staged pages (staging
        pool rows RESERVED_PAGES..) into the decode pool pages the
        admission allocated, whole pages at a time. ``staged_pages`` is
        the STATIC page count of the transferred buffer — workers ship
        only a power-of-two bucket covering the prompt's written pages,
        not the whole staging pool, so interconnect bytes track prompt
        length (DECODE_NOTES.md "interconnect math") at a bounded
        O(log n_pages) compile count. ``n_valid`` (traced) masks the copy
        to the prompt's exact pages — rows past it (and NULL block-row
        entries) target TRASH_PAGE, so one compile serves every prompt
        length inside a bucket. The slot pool is donated (the scatter
        updates in place behind in-flight steps in device program order);
        the staged buffer is NOT — it is a transient dropped after the
        call. Cached on the server (like the prefill programs) so every
        batcher built on it shares one compile per bucket. Compiled-form
        contract: ``disagg.import_pages`` in tools/hlolint (zero host
        transfers, donation intact, bytes within the committed budget)."""
        m = n_pages if staged_pages is None else min(staged_pages, n_pages)
        key = ("handoff_import", n_pages, m)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import (NULL_PAGE,
                                                        RESERVED_PAGES,
                                                        TRASH_PAGE)

        @partial(jax.jit, donate_argnums=(0,))
        def import_pages(pools, staged, block_row, n_valid):
            src = jnp.arange(m) + RESERVED_PAGES
            tgt = jnp.where(
                (jnp.arange(m) < n_valid) & (block_row[:m] != NULL_PAGE),
                block_row[:m], TRASH_PAGE)
            return [
                tuple(pool.at[tgt].set(st[src])
                      for pool, st in zip(pool_layer, staged_layer))
                for pool_layer, staged_layer in zip(pools, staged)
            ]

        self._prefill_cache[key] = import_pages
        return import_pages

    def _get_staging_pool_init(self, pool_pages: int, page_size: int):
        """Compiled zero-init of a prefill worker's staging page pool
        (runtime/disagg.py): cached on the server so M workers (and every
        rebuilt batcher) share one compile — each worker still executes it
        once and commits the result to its own device."""
        key = ("staging_init", pool_pages, page_size)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        import jax

        from seldon_core_tpu.models.transformer import init_paged_kv_caches

        fn = jax.jit(lambda: init_paged_kv_caches(
            self._cfg, pool_pages, page_size, self.kv_cache_dtype))
        self._prefill_cache[key] = fn
        return fn

    def _get_decode_step_paged(self, slots: int, n_pages: int, k: int = 1,
                               lora: bool = False):
        """Compiled pipelined decode step over the PAGED pool: identical
        sampling state machine to ``_get_decode_step`` (per-slot rng keys,
        device-resident token/position state, k-step ``lax.scan``), with the
        KV read/write routed through per-slot block tables instead of a
        dense [S, max_len] slot cache. The block tables are an extra input,
        NOT donated and NOT modified by the step — the host updates them
        through the batcher's jitted table ops between dispatches, and
        device program order serializes those against in-flight steps.

        Returns ``(pools, last_tok, next_pos, keys, tokens[slots, k])`` with
        the same donation shape as the dense step (pools, next_pos, keys
        donated; last_tok not, for the same stacked-output aliasing reason).
        Token parity with the dense step is bit-exact on the gather
        fallback (tests/test_paged_kv.py); the compiled-form contract is
        pinned as llm.paged_decode_step_s4 in tools/hlolint."""
        key = ("pagedstep", slots, n_pages, k, lora)
        fn = self._decode_cache.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        module = self._module
        top_k = self.top_k
        deq = self._dequant

        def core(params, pools, last_tok, next_pos, keys, temperature,
                 block_tables, adapter_pool, adapter_ids):
            sample = _slot_sampler(top_k)

            def step(carry, _):
                pools, tok, pos, keys = carry
                logits, pools = module.apply(
                    deq(params), tok[:, None], positions=pos[:, None],
                    caches=pools, block_tables=block_tables,
                    adapters=adapter_pool, adapter_ids=adapter_ids,
                )
                keys, nxt = sample(keys, logits[:, -1].astype(jnp.float32),
                                   temperature)
                return (pools, nxt, pos + 1, keys), nxt

            (pools, tok, pos, keys), toks = jax.lax.scan(
                step, (pools, last_tok, next_pos, keys), None, length=k)
            return pools, tok, pos, keys, toks.T  # tokens [slots, k]

        if lora:
            # adapted paged step (llm.lora_decode_step hlolint contract):
            # same donation shape as the base step; the adapter pool/ids
            # ride along un-donated like the block tables
            @partial(jax.jit, donate_argnums=(1, 3, 4))
            def decode_step(params, pools, last_tok, next_pos, keys,
                            temperature, block_tables, adapter_pool,
                            adapter_ids):
                return core(params, pools, last_tok, next_pos, keys,
                            temperature, block_tables, adapter_pool,
                            adapter_ids)
        else:
            @partial(jax.jit, donate_argnums=(1, 3, 4))
            def decode_step(params, pools, last_tok, next_pos, keys,
                            temperature, block_tables):
                return core(params, pools, last_tok, next_pos, keys,
                            temperature, block_tables, None, None)

        self._decode_cache[key] = decode_step
        return decode_step

    def _get_draft_prefill(self, b: int, plen: int, max_len: int):
        """DRAFT-model prompt prefill into a fresh dense cache (the dense
        batcher's draft admission): same shape contract as ``_get_prefill``
        but over the draft module; the logits are discarded — only the
        written KV matters, drafting always restarts from the last accepted
        target token."""
        key = ("draft_prefill", b, plen, max_len)
        fn = self._prefill_cache.get(key)
        if fn is not None:
            return fn
        import jax

        from seldon_core_tpu.models.transformer import init_kv_caches

        module, cfg = self._draft_module, self._draft_cfg
        deq = self._draft_dequant

        def prefill(params, tokens, positions):
            caches = init_kv_caches(cfg, tokens.shape[0], max_len)
            logits, caches = module.apply(
                deq(params), tokens, positions=positions, caches=caches,
                cache_index=0)
            return logits, caches

        fn = jax.jit(prefill)
        self._prefill_cache[key] = fn
        return fn

    def _get_spec_step(self, slots: int, spec_k: int, hist_len: int, *,
                       mode: str = "ngram", layout: str = "paged",
                       n_pages: int = 0, lora: bool = False):
        """Compiled speculative decode step for the ContinuousBatcher: ONE
        dispatch drafts up to K tokens per slot, verifies them in a single
        K+1-token target forward, and accepts the longest prefix that
        agrees with the slot's exact sampling chain.

        Drafting. ``mode="ngram"`` runs a zero-weight prompt-lookup
        proposer (Saxena's prompt-lookup decoding; the self-draft family of
        Leviathan et al. 2023) over the slot's device-resident
        prompt+generated token history ``hist [S, hist_len]``: the longest
        (up to spec_ngram) trailing n-gram is matched against every earlier
        position — most recent longest match wins — and the K tokens that
        followed it are proposed. ``mode="draft"`` runs K+1 sequential
        greedy forwards of the small draft model over its own cache
        (drafting consumes NO slot rng — the chain belongs to the target).
        The draft cache is always DENSE [S, max_len] regardless of the
        target layout: the draft is small by construction, so paging it
        would buy nothing and cost a second allocator. Either way the
        per-slot ``draft_cap`` input clamps the offer (the batcher's
        acceptance-rate controller + cache-edge headroom).

        Verification. The target forward feeds [last_tok, d_1..d_K] at
        positions next_pos..next_pos+K (columns past the cap carry PAD_POS:
        masked from attention, writes dropped/trash-redirected). Token j+1
        is then SAMPLED from the target logits at column j on generate()'s
        exact per-slot rng chain — split once per ACCEPTED token, never per
        forward — and the draft is accepted only while the sample equals
        it. This is the chain-exact form of the rejection-sampling
        correction: the emitted tokens are precisely the ones sequential
        decode would have emitted (greedy bit-exact, seeded sampling on the
        identical key sequence), speculation only changes how many arrive
        per forward (1..K+1, output ``n_acc``).

        Cache repair. Rows written for drafts that lost verification
        (positions next_pos+a..next_pos+K, and the draft model's own rows
        in draft mode) have their position entries reset to PAD_POS inside
        this same program — the reset_pages idiom — so the cache never
        holds tokens that lost verification: they are unattendable
        immediately and their rows are overwritten when the true tokens
        reach those positions.

        Returns ``(caches, last_tok, next_pos, keys, hist,
        tokens[S, K+1], n_acc[S])`` (+ draft caches in draft mode) with the
        decode-step donation discipline: caches, next_pos, keys, hist (and
        draft caches) donated; last_tok NOT (its buffer may alias the
        stacked token output the host still reads). The compiled form is
        pinned by the llm.verify_step_k4 / llm.draft_verify_step_k4
        contracts in tools/hlolint (zero host transfers, intact aliasing,
        cost bands)."""
        key = ("specstep", slots, spec_k, hist_len, mode, layout, n_pages,
               lora)
        fn = self._decode_cache.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import (
            PAD_POS, paged_write_targets)

        module = self._module
        top_k_cfg = self.top_k
        deq = self._dequant
        K = int(spec_k)
        S = int(slots)
        H = int(hist_len)
        NGRAM = max(int(self.spec_ngram) or 3, 1)
        draft_mode = mode == "draft"
        paged = layout == "paged"
        if draft_mode:
            dmodule = self._draft_module
            ddeq = self._draft_dequant

        def core(params, caches, last_tok, next_pos, keys, temperature,
                 hist, draft_cap, bt, dparams, dcaches,
                 apool=None, aids=None):
            # verification samples through the SAME chain every compiled
            # decode step uses — the bit-exactness contract lives in
            # _slot_sampler, not in a local copy
            _sample = _slot_sampler(top_k_cfg)

            def sample(keys_, lg):
                return _sample(keys_, lg, temperature)

            cap = jnp.clip(draft_cap, 0, K)

            if draft_mode:
                # K+1 sequential greedy draft forwards: feeds t_0,d_1..d_K
                # so the draft cache covers every position the target may
                # accept (incl. the all-accepted bonus case)
                def dstep(carry, _):
                    dc, tok, pos = carry
                    dlg, dc = dmodule.apply(
                        ddeq(dparams), tok[:, None],
                        positions=pos[:, None], caches=dc,
                        cache_index=pos)
                    nxt = jnp.argmax(
                        dlg[:, -1].astype(jnp.float32), axis=-1
                    ).astype(tok.dtype)
                    return (dc, nxt, pos + 1), nxt

                (dcaches, _, _), dtoks = jax.lax.scan(
                    dstep, (dcaches, last_tok, next_pos), None, length=K + 1)
                drafts = dtoks.T[:, :K]
                dlen = cap
            else:
                # prompt-lookup proposer: matched-length score per earlier
                # position (prefix-AND over the trailing NGRAM tokens),
                # longest match wins, most recent breaks ties
                idx = jnp.arange(H)
                ok = jnp.ones((S, H), bool)
                length = jnp.zeros((S, H), jnp.int32)
                for j in range(NGRAM):
                    hj = hist[:, jnp.clip(idx - j, 0, H - 1)]
                    cj = jnp.take_along_axis(
                        hist, jnp.clip(next_pos - j, 0, H - 1)[:, None],
                        axis=1)
                    ok = ok & (hj == cj) & ((idx - j) >= 0)[None, :] \
                        & ((next_pos - j) >= 0)[:, None]
                    length = length + ok.astype(jnp.int32)
                cand = idx[None, :] < next_pos[:, None]
                score = jnp.where(cand & (length > 0),
                                  length * H + idx[None, :], -1)
                best = jnp.argmax(score, axis=1)
                has = jnp.take_along_axis(score, best[:, None], axis=1)[:, 0] >= 0
                offs = jnp.arange(1, K + 1)
                src = best[:, None] + offs[None, :]
                drafts = jnp.take_along_axis(
                    hist, jnp.clip(src, 0, H - 1), axis=1)
                dlen = jnp.where(
                    has,
                    jnp.sum((src <= next_pos[:, None]).astype(jnp.int32),
                            axis=1),
                    0)
                dlen = jnp.minimum(dlen, cap)

            cols = jnp.arange(K + 1)
            tokens_in = jnp.concatenate([last_tok[:, None], drafts], axis=1)
            positions = jnp.where(cols[None, :] <= dlen[:, None],
                                  next_pos[:, None] + cols[None, :], PAD_POS)
            # the TARGET verify forward carries the per-slot adapters
            # (llm.lora_verify_step contract); the draft forwards above
            # stay base-model — proposals are only proposals, and the
            # chain-exact accept loop below enforces the ADAPTED target's
            # distribution either way
            if bt is None:
                logits, caches = module.apply(
                    deq(params), tokens_in, positions=positions,
                    caches=caches, cache_index=next_pos,
                    adapters=apool, adapter_ids=aids)
            else:
                logits, caches = module.apply(
                    deq(params), tokens_in, positions=positions,
                    caches=caches, block_tables=bt,
                    adapters=apool, adapter_ids=aids)
            lg32 = logits.astype(jnp.float32)

            # chain-exact accept loop: sample column j -> token j+1; rng
            # advances ONLY while accepting, so the key state after this
            # step equals sequential decode's after the same tokens
            a = jnp.zeros((S,), jnp.int32)
            valid = jnp.ones((S,), bool)
            out_cols = []
            cur_keys = keys
            for j in range(K + 1):
                keys2, sj = sample(cur_keys, lg32[:, j])
                cur_keys = jnp.where(valid[:, None], keys2, cur_keys)
                a = a + valid.astype(jnp.int32)
                out_cols.append(jnp.where(valid, sj, 0))
                if j < K:
                    valid = valid & (sj == tokens_in[:, j + 1]) \
                        & (j + 1 <= dlen)
            toks = jnp.stack(out_cols, axis=1)  # [S, K+1]
            new_last = jnp.take_along_axis(toks, (a - 1)[:, None], axis=1)[:, 0]

            # history append: fed t_0 plus the a accepted samples (columns
            # past a land at index H -> dropped)
            wcols = jnp.arange(K + 2)
            wtok = jnp.concatenate([last_tok[:, None], toks], axis=1)
            wpos = jnp.where(wcols[None, :] <= a[:, None],
                             next_pos[:, None] + wcols[None, :], H)
            rows = jnp.arange(S)[:, None]
            hist = hist.at[rows, wpos].set(wtok, mode="drop")

            # reject repair: columns a..K lost verification — reset their
            # position rows to PAD_POS (unattendable now, overwritten when
            # the true tokens reach those positions). Surviving columns map
            # to PAD_POS write targets (dense: dropped; paged: trash).
            rcols = jnp.arange(1, K + 1)
            rej = rcols[None, :] >= a[:, None]
            rpos = jnp.where(rej, next_pos[:, None] + rcols[None, :], PAD_POS)

            def repair(cs, tables):
                if tables is None:
                    return [layer[:-1] + (
                        layer[-1].at[rows, rpos].set(PAD_POS, mode="drop"),)
                        for layer in cs]
                ps = cs[0][0].shape[1]
                entry, off = paged_write_targets(tables, rpos, ps)
                return [layer[:-1] + (layer[-1].at[entry, off].set(PAD_POS),)
                        for layer in cs]

            caches = repair(caches, bt)
            if draft_mode:
                dcaches = repair(dcaches, None)  # draft cache is dense
                return (caches, new_last, next_pos + a, cur_keys, hist,
                        toks, a, dcaches)
            return (caches, new_last, next_pos + a, cur_keys, hist, toks, a)

        # lora=True appends (adapter_pool, adapter_ids) to each signature
        # (un-donated, like the block tables); the donation shape of the
        # serving state is identical to the base variant
        if paged and draft_mode and lora:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 7, 10))
            def spec_step(params, pools, last_tok, next_pos, keys,
                          temperature, block_tables, hist, draft_cap,
                          draft_params, draft_caches, adapter_pool,
                          adapter_ids):
                return core(params, pools, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, block_tables,
                            draft_params, draft_caches, adapter_pool,
                            adapter_ids)
        elif paged and draft_mode:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 7, 10))
            def spec_step(params, pools, last_tok, next_pos, keys,
                          temperature, block_tables, hist, draft_cap,
                          draft_params, draft_caches):
                return core(params, pools, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, block_tables,
                            draft_params, draft_caches)
        elif paged and lora:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 7))
            def spec_step(params, pools, last_tok, next_pos, keys,
                          temperature, block_tables, hist, draft_cap,
                          adapter_pool, adapter_ids):
                return core(params, pools, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, block_tables,
                            None, None, adapter_pool, adapter_ids)
        elif paged:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 7))
            def spec_step(params, pools, last_tok, next_pos, keys,
                          temperature, block_tables, hist, draft_cap):
                return core(params, pools, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, block_tables,
                            None, None)
        elif draft_mode and lora:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 6, 9))
            def spec_step(params, caches, last_tok, next_pos, keys,
                          temperature, hist, draft_cap, draft_params,
                          draft_caches, adapter_pool, adapter_ids):
                return core(params, caches, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, None,
                            draft_params, draft_caches, adapter_pool,
                            adapter_ids)
        elif draft_mode:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 6, 9))
            def spec_step(params, caches, last_tok, next_pos, keys,
                          temperature, hist, draft_cap, draft_params,
                          draft_caches):
                return core(params, caches, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, None,
                            draft_params, draft_caches)
        elif lora:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 6))
            def spec_step(params, caches, last_tok, next_pos, keys,
                          temperature, hist, draft_cap, adapter_pool,
                          adapter_ids):
                return core(params, caches, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, None, None, None,
                            adapter_pool, adapter_ids)
        else:
            @partial(jax.jit, donate_argnums=(1, 3, 4, 6))
            def spec_step(params, caches, last_tok, next_pos, keys,
                          temperature, hist, draft_cap):
                return core(params, caches, last_tok, next_pos, keys,
                            temperature, hist, draft_cap, None, None, None)

        self._decode_cache[key] = spec_step
        return spec_step

    # ------------------------------------------------------------------
    def generate(
        self,
        prompts: Sequence[Any],
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        seed: Optional[int] = None,
    ) -> Dict[str, Any]:
        """prompts: list of strings or of int token lists/arrays."""
        if not self.ready:
            self.load()
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.transformer import PAD_POS

        max_new = int(max_new_tokens or self.max_new_tokens)
        temp = self.temperature if temperature is None else float(temperature)

        token_lists: List[List[int]] = []
        text_mode = []
        for p in prompts:
            if isinstance(p, str):
                token_lists.append(self._tokenizer.encode(p))
                text_mode.append(True)
            else:
                # graftlint: allow-host-sync-in-hot-path(prompt ingress: p is caller-supplied host tokens, never a device array)
                token_lists.append([int(t) for t in np.asarray(p).ravel()])
                text_mode.append(False)
        if not token_lists:
            raise SeldonError("generate() needs at least one prompt")
        if any(len(t) == 0 for t in token_lists):
            raise SeldonError("empty prompt")

        n = len(token_lists)
        max_batch = self.batch_buckets[-1]
        if n > max_batch:
            # split oversized batches and merge (one compiled program per bucket)
            out_tokens, out_texts = [], []
            for i in range(0, n, max_batch):
                part = self.generate(
                    prompts[i : i + max_batch], max_new_tokens=max_new,
                    temperature=temp, seed=seed,
                )
                out_tokens.extend(part["tokens"])
                out_texts.extend(part["texts"])
            return {"tokens": out_tokens, "texts": out_texts}
        nb = _bucket(n, self.batch_buckets)
        longest = max(len(t) for t in token_lists)
        plen = min(_bucket(longest, self.len_buckets), self._cfg.max_seq_len)
        if longest > plen:
            logger.warning("prompt of %d tokens truncated to max_seq_len %d", longest, plen)
        token_lists = [t[-plen:] for t in token_lists]  # keep the prompt tail
        if self.prefix_cache_size > 0 and n == 1:
            # one shared cache size for all single-prompt requests — a
            # per-request max_len would make every different prompt-length
            # bucket a guaranteed prefix-cache miss. Never smaller than the
            # actual prompt bucket (over-long prompts exceed the top bucket).
            max_len = (
                max(plen, min(self.len_buckets[-1], self._cfg.max_seq_len))
                + max(max_new, self.max_new_tokens)
            )
        else:
            max_len = min(plen + max_new, self._cfg.max_seq_len + max_new)
        if self.mesh is not None:
            # round the cache length up to a multiple of the seq axis so the
            # KV cache can actually shard over it
            sp = dict(self.mesh.shape).get("seq", 1)
            if sp > 1:
                max_len = -(-max_len // sp) * sp

        tokens = np.zeros((nb, plen), np.int32)
        positions = np.full((nb, plen), PAD_POS, np.int32)
        true_len = np.ones((nb,), np.int32)  # dummy rows decode from slot 1
        last_tok = np.zeros((nb,), np.int32)
        for i, toks in enumerate(token_lists):
            L = len(toks)
            tokens[i, :L] = toks
            positions[i, :L] = np.arange(L)
            true_len[i] = L
            last_tok[i] = toks[-1]

        # Prefix cache: single-prompt requests skip recomputing the KV of a
        # previously-seen token prefix (e.g. a shared system prompt); only
        # the suffix prefills, at its own bucketed length.
        use_prefix = self.prefix_cache_size > 0 and n == 1 and nb == 1
        # Donate the cache buffers into the decode scan (in-place
        # dynamic_update_slice, no full-cache copy per call) — except when
        # the same cache object lives on as a prefix-cache entry, which a
        # donation would invalidate.
        decode = self._get_decode(nb, max_len, donate=not use_prefix)
        hit = self._prefix_lookup(token_lists[0], max_len) if use_prefix else None
        if hit is not None and hit[0] == len(token_lists[0]):
            _, _, caches, first_logits = hit
        elif hit is not None:
            p0, _, caches, _ = hit
            suffix = token_lists[0][p0:]
            L = len(suffix)
            slen = min(_bucket(L, self.len_buckets), max_len - p0)
            stoks = np.zeros((1, slen), np.int32)
            spos = np.full((1, slen), PAD_POS, np.int32)
            stoks[0, :L] = suffix
            spos[0, :L] = np.arange(p0, p0 + L)
            extend = self._get_extend(1, slen, max_len)
            logits, caches = extend(
                self._params, caches, jnp.asarray(stoks), jnp.asarray(spos),
                jnp.asarray(p0, jnp.int32),
            )
            # graftlint: allow-host-sync-in-hot-path(generate() is the synchronous API: the first sampled token is drawn on the host once per request, before decode dispatch)
            first_logits = np.asarray(logits[:, L - 1]).astype(np.float32)
            self._prefix_store(token_lists[0], max_len, caches, first_logits)
        else:
            prefill = self._get_prefill(nb, plen, max_len)
            logits, caches = prefill(self._params, jnp.asarray(tokens), jnp.asarray(positions))
            # next-token logits live at each sequence's last real slot
            # graftlint: allow-host-sync-in-hot-path(generate() is the synchronous API: first-token sampling happens on the host once per request)
            first_logits = np.asarray(
                logits[jnp.arange(nb), jnp.asarray(true_len) - 1]
            ).astype(np.float32)
            if use_prefix:
                self._prefix_store(token_lists[0], max_len, caches, first_logits)
        # explicit seed => reproducible; otherwise vary per request. The
        # fetch-and-increment is atomic under the lock: two concurrent
        # unseeded generate() calls must not share an rng chain (and the
        # count must not lose updates)
        with self._prefix_lock:
            request_index = self._request_count
            self._request_count += 1
        rng = jax.random.PRNGKey(
            int(seed) if seed is not None else self.seed + request_index
        )

        if temp <= 0.0:
            first_tok = first_logits.argmax(-1).astype(np.int32)
        else:
            k = min(self.top_k, first_logits.shape[-1])
            rng, sub = jax.random.split(rng)
            topv = np.sort(first_logits, axis=-1)[:, -k:]
            topi = np.argsort(first_logits, axis=-1)[:, -k:]
            # graftlint: allow-host-sync-in-hot-path(once-per-request first-token sample on generate()'s rng chain — the per-token path stays device-resident)
            draw = np.asarray(jax.random.categorical(sub, jnp.asarray(topv) / max(temp, 1e-6)))
            first_tok = topi[np.arange(nb), draw].astype(np.int32)

        out_tokens = [first_tok[:, None]]
        if max_new > 1:
            import time as _time

            self._last_decode_kv_bytes = self._entry_nbytes(caches, None)
            t0 = _time.perf_counter()
            toks, _ = decode(
                self._params, caches, jnp.asarray(first_tok), jnp.asarray(true_len),
                max_new - 1, rng, jnp.asarray(temp, jnp.float32),
            )
            # graftlint: allow-host-sync-in-hot-path(generate()'s one deliberate result sync: the whole fused decode ran device-side; callers that must not block use the pipelined batcher instead)
            toks = np.asarray(toks)  # blocks: the wall below covers device time
            self._decode_step_times.append(
                (_time.perf_counter() - t0) / (max_new - 1)
            )
            out_tokens.append(toks)
        all_toks = np.concatenate(out_tokens, axis=1)[:n]  # drop batch padding

        results_tokens: List[List[int]] = []
        results_text: List[Optional[str]] = []
        for i in range(n):
            seq = all_toks[i].tolist()
            if self.eos_id in seq:
                seq = seq[: seq.index(self.eos_id)]
            results_tokens.append(seq)
            results_text.append(self._tokenizer.decode(seq) if text_mode[i] else None)
        return {"tokens": results_tokens, "texts": results_text}

    # ------------------------------------------------------------------
    # SeldonComponent surface
    # ------------------------------------------------------------------
    def predict(self, X, names: Sequence[str], meta: Optional[Dict] = None):
        if isinstance(X, (bytes, bytearray)):
            X = X.decode("utf-8")
        if isinstance(X, str):
            out = self.generate([X])
            return out["texts"][0]
        if isinstance(X, dict):
            prompts = X.get("prompts") or X.get("prompt")
            if prompts is None:
                raise SeldonError("jsonData needs 'prompts'")
            if isinstance(prompts, str):
                prompts = [prompts]
            out = self.generate(
                prompts,
                max_new_tokens=X.get("max_new_tokens"),
                temperature=X.get("temperature"),
                seed=X.get("seed"),
            )
            return {"texts": out["texts"], "tokens": out["tokens"]}
        # graftlint: allow-host-sync-in-hot-path(request ingress: X is the transport's host payload, never a device array)
        arr = np.atleast_2d(np.asarray(X, dtype=np.int64))
        prompts = [row[row >= 0] for row in arr]  # -1 right-padding
        out = self.generate(prompts)
        width = max(len(t) for t in out["tokens"])
        padded = np.full((len(prompts), width), -1, np.int64)
        for i, t in enumerate(out["tokens"]):
            padded[i, : len(t)] = t
        return padded

    def tags(self) -> Dict[str, Any]:
        # request/prefix-cache accounting mutates under _prefix_lock on the
        # serving path; the stats scrape reads it under the same lock
        with self._prefix_lock:
            out = {"llm_requests": self._request_count}
            if self.prefix_cache_size:
                out["prefix_cache_hits"] = self._prefix_hits
                out["prefix_cache_entries"] = len(self._prefix_cache)
        return out

    def prefix_match_len(self, prompt: Any) -> int:
        """Cached-prefix length (tokens) this server already holds for
        ``prompt`` — the cheap probe ReplicaSet's prefix-aware routing
        calls before dispatch (runtime/engine.py). Reads the batcher's
        page-pool radix trie when continuous batching is on, else the
        dense entry index; both are O(prompt) walks under their own
        locks, no device work, no pinning."""
        if not self.ready:
            return 0
        if isinstance(prompt, str):
            ids = self._tokenizer.encode(prompt)
        else:
            # graftlint: allow-host-sync-in-hot-path(routing probe ingress: prompt is caller-supplied host tokens, never a device array)
            ids = [int(t) for t in np.asarray(prompt).ravel()]
        svc = getattr(self, "_batcher_service", None)
        radix = getattr(svc.batcher, "_radix", None) if svc is not None \
            else None
        if radix is not None:
            return radix.match_len(ids)
        with self._prefix_lock:
            cands = self._prefix_index.candidates(ids)
            return len(cands[-1]) if cands else 0

    def flight_recorder(self):
        """The active batcher's flight recorder (runtime/flight.py), or
        None when tracing is off / no batcher service exists — the
        /debug/timeline + gRPC DebugTimeline data source
        (observability/timeline.py)."""
        svc = getattr(self, "_batcher_service", None)
        if svc is None:
            return None
        return getattr(svc.batcher, "_flight", None)

    def llm_stats(self) -> Dict[str, Any]:
        """Decode-bandwidth observability snapshot, consumed by
        MetricsRegistry.sync_llm at /metrics scrape time: resident KV bytes
        (continuous-batching slot caches + pinned prefix entries), slot
        occupancy, the KV bytes the last decode streamed per step, and the
        decode step-time observations accumulated since the last scrape
        (drained here — each is observed into the histogram exactly once)."""
        def drain(dq) -> List[float]:
            out: List[float] = []
            while True:
                try:
                    out.append(dq.popleft())
                except IndexError:
                    return out

        occupancy = 0.0
        slot_bytes = 0
        in_flight = 0
        inflight_hwm = 0
        depth = self.decode_pipeline_depth
        fuse = self.decode_fuse_steps
        page_stats = {"kv_pages_total": 0, "kv_pages_in_use": 0,
                      "kv_page_size": 0, "kv_page_fragmentation": 0.0,
                      "kv_page_sheds": 0}
        spec_stats = {"spec_mode": self.spec_mode, "spec_k": self.spec_k,
                      "spec_accept_rate": 0.0,
                      "spec_tokens_per_forward": 0.0,
                      "spec_slot_steps_total": 0,
                      "spec_accept_rate_per_slot": [],
                      "spec_draft_overhead_fraction": 0.0}
        handoff_stats = {"disaggregation": self.disaggregation or "off",
                         "handoffs_total": 0,
                         "handoff_transfer_bytes_total": 0,
                         "handoff_queue_depth": 0,
                         "handoff_network_bytes_total": 0}
        # radix prefix cache (runtime/radix.py): cached/shared block
        # gauges + the hit/cow/eviction/bytes-saved lifetime counters
        # (metrics/registry.py seldon_llm_prefix_*)
        prefix_stats = {"prefix_cached_blocks": 0, "prefix_shared_pages": 0,
                        "prefix_hit_blocks": 0, "prefix_hit_tokens": 0,
                        "prefix_cow_copies": 0, "prefix_evicted_blocks": 0,
                        "prefix_bytes_saved": 0}
        # multi-tenant serving (docs/multitenancy.md): adapter-pool
        # occupancy/churn/bytes plus the scheduler's per-(tenant, class)
        # tallies — seldon_llm_adapter_* / seldon_tenant_*_total
        adapter_stats = {"adapter_loaded": 0, "adapter_evictions_total": 0,
                        "adapter_pool_bytes": 0}
        reg = getattr(self, "adapter_registry", None)
        if reg is not None:
            snap = reg.stats()
            adapter_stats = {k: snap[k] for k in adapter_stats}
        tenant_counters: List[dict] = []
        queue_by_class: Dict[str, int] = {}
        svc = getattr(self, "_batcher_service", None)
        if svc is not None:
            batcher = svc.batcher
            occupancy = sum(1 for s in batcher._slots if s.active) / max(batcher.S, 1)
            slot_bytes = self._entry_nbytes(batcher._caches, None)
            in_flight = len(batcher._inflight)
            inflight_hwm = batcher._inflight_hwm
            depth = batcher.pipeline_depth
            fuse = batcher.fuse_steps
            radix_stats = None
            if getattr(batcher, "_radix", None) is not None:
                # ONE trie walk per scrape: page_stats reuses the snapshot
                radix_stats = batcher._radix.stats()
                prefix_stats.update(radix_stats)
            if getattr(batcher, "paged", False):
                page_stats = batcher.page_stats(radix_stats=radix_stats)
            if getattr(batcher, "spec_mode", "off") != "off":
                spec_stats.update(batcher.spec_stats())
            if getattr(batcher, "_remote", None) is not None:
                handoff_stats.update(batcher.handoff_stats())
            sched = getattr(batcher, "_pending", None)
            if hasattr(sched, "counters"):
                tenant_counters = sched.counters()
                queue_by_class = sched.depths()
        with self._prefix_lock:
            prefix_bytes = self._prefix_bytes
        return {
            "kv_cache_dtype": self.kv_cache_dtype,
            "kv_cache_layout": self.kv_cache_layout,
            # paged-pool accounting (zeros under the dense layout):
            # in-use/total page gauge pair plus internal fragmentation —
            # the slack between tokens written and pages held
            **page_stats,
            "kv_cache_bytes": slot_bytes + prefix_bytes,
            "kv_occupancy": occupancy,
            "kv_bytes_per_step": self._last_decode_kv_bytes,
            "decode_step_times_s": drain(self._decode_step_times),
            # pipelined decode: dispatch (enqueue-only) vs sync (host block)
            # split, current/high-water steps-in-flight, and the host lag
            # observed at each drain (steps the host trails the device)
            "decode_dispatch_times_s": drain(self._decode_dispatch_times),
            "decode_sync_times_s": drain(self._decode_sync_times),
            "decode_host_lag_steps": drain(self._decode_host_lag),
            "decode_steps_in_flight": in_flight,
            "decode_inflight_hwm": inflight_hwm,
            "decode_pipeline_depth": depth,
            "decode_fuse_steps": fuse,
            # speculative decoding: aggregate + per-slot acceptance, the
            # accepted-tokens-per-verify-step observations accumulated
            # since the last scrape, and the draft compute-overhead
            # fraction (metrics/registry.py seldon_llm_spec_*)
            **spec_stats,
            "spec_accepted_per_step": drain(self._spec_accepted),
            # streaming latency (batcher on_token path): TTFT per request
            # and the gap observed before each surfaced token — the
            # headline pair disaggregation moves (seldon_llm_ttft_seconds /
            # seldon_llm_inter_token_seconds). Multi-token drains (fused /
            # speculative steps) surface their block in one burst, so a
            # block's trailing tokens record ~0 gaps by construction.
            "ttft_s": drain(self._ttft_times),
            "inter_token_s": drain(self._inter_token_times),
            # disaggregated serving: per-handoff wall (prefill + D2D
            # transfer + import) and the transfer-queue counters
            **handoff_stats,
            "handoff_times_s": drain(self._handoff_times),
            # radix prefix cache: block-level reuse counters + the
            # shared-page gauge (docs/performance.md "Radix prefix cache")
            **prefix_stats,
            # multi-tenant serving: adapter pool + per-tenant fairness
            # tallies + per-class TTFT drains (docs/multitenancy.md)
            **adapter_stats,
            "tenant_counters": tenant_counters,
            "queue_by_class": queue_by_class,
            "ttft_by_class": drain(self._ttft_by_class),
        }
