"""JAX_SERVER: the native TPU prepackaged server.

This is the component that replaces the reference's delegation to external
native inference servers (`integrations/tfserving/TfServingProxy.py:20-125`,
`integrations/nvidia-inference-server/TRTProxy.py:31-81`): instead of proxying
to a C++ process over HTTP, the XLA-compiled model runs in-process on TPU.

Checkpoint layout at ``modelUri``:
    config.json   {"model": "<registry name>", "kwargs": {...},
                   "input_shape": [...], "input_dtype": "float32",
                   "batch_buckets": [1, 8, 64], "apply_kwargs": {...}}
    params/       orbax checkpoint of the param pytree (preferred), or
    params.msgpack  flax serialized params.

Serving path: request ndarray -> device staging with batch bucketing
(codec.staging) -> jitted apply (one compiled program per bucket) -> slice
back to the true batch. Optionally shards params + activations over a device
mesh via parallel.sharding for models larger than one chip.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu import storage
from seldon_core_tpu.codec.staging import DEFAULT_BUCKETS, pad_batch
from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError

logger = logging.getLogger(__name__)


class JAXServer(SeldonComponent):
    def __init__(
        self,
        model_uri: str = "",
        model: Optional[str] = None,
        mesh: Optional[Any] = None,
        topology: Optional[Any] = None,
        param_sharding_rules: Optional[Any] = None,
        batch_buckets: Optional[Sequence[int]] = None,
        strict_sharding: bool = False,
        tensor_parallel: int = 0,
        quantize: str = "",
        param_dtype: str = "",
        **kwargs: Any,
    ):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.model_name = model
        self.mesh = mesh
        # Injected device-world view (parallel/topology.py); None = adopt
        # the process topology at load() instead of re-deriving it here.
        self.topology = topology
        self.param_sharding_rules = param_sharding_rules
        self.strict_sharding = strict_sharding
        # Spec-reachable sharding: `tensor_parallel` arrives as a typed unit
        # parameter from the graph spec (the CR analogue of the reference's
        # per-predictor `replicas`, proto/seldon_deployment.proto:57) and
        # builds the standard ('data', 'model') serving mesh at load time.
        self.tensor_parallel = int(tensor_parallel)
        # "int8": weight-only PTQ — weights live in HBM as int8, dequant
        # fuses into the matmuls (ops/quantize.py)
        self.quantize = str(quantize or "")
        # Param-dtype cast at load ("auto" = module compute dtype). Off by
        # default: the on-chip A/B showed pre-cast bf16 params decode SLOWER
        # (XLA hoists the convert; see benchmarks/DECODE_NOTES.md). The knob
        # stays for HBM-residency-bound configs.
        self.param_dtype = param_dtype
        self.batch_buckets = tuple(batch_buckets) if batch_buckets else DEFAULT_BUCKETS
        self.ready = False
        self._apply = None
        self._params = None
        self._config: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    def load(self) -> None:
        if self.ready:
            return
        import jax
        import flax

        path = storage.download(self.model_uri)
        cfg_path = os.path.join(path, "config.json")
        if not os.path.exists(cfg_path):
            raise SeldonError(f"JAXServer checkpoint missing config.json at {path}", status_code=500)
        with open(cfg_path) as f:
            self._config = json.load(f)

        from seldon_core_tpu.models import get_model

        name = self.model_name or self._config["model"]
        module = get_model(name, **self._config.get("kwargs", {}))
        self._module = module

        if self.mesh is None and self.tensor_parallel > 1:
            from seldon_core_tpu.parallel.topology import get_topology

            self.topology = self.topology or get_topology()
            n = self.topology.device_count
            if n % self.tensor_parallel:
                raise SeldonError(
                    f"tensor_parallel={self.tensor_parallel} does not divide "
                    f"{n} available devices",
                    status_code=500,
                )
            self.mesh = self.topology.serving_mesh(
                model_parallel=self.tensor_parallel)

        params = self._load_params(path)
        param_dtype = self._config.get("param_dtype", self.param_dtype)
        module_dtype = getattr(module, "dtype", None)
        if param_dtype and (param_dtype != "auto" or module_dtype is not None):
            # only "auto" needs the module's compute dtype; an explicit
            # param_dtype casts regardless of whether the module exposes one
            from seldon_core_tpu.servers.llmserver import _cast_params

            params = _cast_params(
                params, param_dtype, module_dtype or "float32"
            )
        apply_kwargs = self._config.get("apply_kwargs", {})

        def apply_fn(params, x):
            out = module.apply(params, x, **apply_kwargs)
            if isinstance(out, tuple):
                out = out[0]
            return out

        quantize = self.quantize or self._config.get("quantize", "")
        if quantize:
            if quantize != "int8":
                raise SeldonError(f"unsupported quantize={quantize!r} (int8 only)", status_code=500)
            # Composes with a mesh: shard_params places q under the weight's
            # logical spec and scale under its channel (last) axis, so int8
            # and tensor parallelism are no longer mutually exclusive.
            from seldon_core_tpu.ops.quantize import dequantize_params, quantize_params

            params = quantize_params(params)
            base_apply = apply_fn

            def apply_fn(params, x):  # noqa: F811 — quantized wrapper
                return base_apply(dequantize_params(params), x)

        if self.mesh is not None:
            from seldon_core_tpu.parallel.sharding import shard_apply

            # The jitted program shards the batch dim over the 'data' axis, so
            # every compiled bucket must be a multiple of its size — round the
            # buckets up (padding masks the remainder, sliced off on return).
            dp = dict(self.mesh.shape).get("data", 1)
            if dp > 1:
                self.batch_buckets = tuple(sorted({-(-b // dp) * dp for b in self.batch_buckets}))

            example_input = None
            shape = self._config.get("input_shape")
            if shape is not None:
                example_input = jax.ShapeDtypeStruct(
                    (1, *shape), jax.numpy.dtype(self._config.get("input_dtype", "float32"))
                )
            self._apply, params = shard_apply(
                apply_fn, module, params, self.mesh,
                rules=self.param_sharding_rules, example_input=example_input,
                strict=self.strict_sharding,
            )
        else:
            self._apply = jax.jit(apply_fn)
        self._params = params
        self.ready = True
        logger.info("JAXServer loaded model %s from %s", name, path)

    def _load_params(self, path: str):
        import jax

        orbax_dir = os.path.join(path, "params")
        msgpack_file = os.path.join(path, "params.msgpack")
        if os.path.isdir(orbax_dir):
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            params = ckptr.restore(os.path.abspath(orbax_dir))
            return params
        if os.path.exists(msgpack_file):
            import flax.serialization

            from seldon_core_tpu.models import get_model

            # Build an abstract target so deserialization restores exact dtypes.
            module = self._module
            shape = self._config.get("input_shape")
            dtype = self._config.get("input_dtype", "float32")
            if shape is None:
                raise SeldonError("config.json needs input_shape to restore msgpack params", status_code=500)
            example = jax.ShapeDtypeStruct((1, *shape), jax.numpy.dtype(dtype))
            target = jax.eval_shape(lambda: module.init(jax.random.PRNGKey(0), jax.numpy.zeros(example.shape, example.dtype)))
            target = jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), target)
            with open(msgpack_file, "rb") as f:
                blob = f.read()
            try:
                return flax.serialization.from_bytes(target, blob)
            except ValueError as orig:
                # params-only checkpoint (e.g. converted from HF): retry
                # against the params subtree; surface the original
                # diagnostic if that also fails
                if "params" not in target:
                    raise
                try:
                    return flax.serialization.from_bytes({"params": target["params"]}, blob)
                except ValueError:
                    raise orig
        raise SeldonError(f"No params found under {path} (expected params/ or params.msgpack)", status_code=500)

    # ------------------------------------------------------------------
    def predict(self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
        if not self.ready:
            self.load()
        # graftlint: allow-host-sync-in-hot-path(request ingress: X arrives as host payload from the transport, never a device array)
        arr = np.asarray(X)
        dtype = np.dtype(self._config.get("input_dtype", "float32"))
        if arr.dtype != dtype:
            arr = arr.astype(dtype)
        padded, true_n = pad_batch(arr, self.batch_buckets)
        out = self._apply(self._params, padded)
        # graftlint: allow-host-sync-in-hot-path(the sync predict API's one deliberate result sync: the response must carry host bytes; batching above this keeps the chip busy)
        return np.asarray(out)[:true_n]

    def jax_fn(self):
        if not self.ready:
            self.load()
        apply = self._apply

        def fn(params, x):
            return apply(params, x)

        return fn, self._params

    def class_names(self):
        return self._config.get("class_names")

    @property
    def input_dtype(self):
        """Declared request dtype from the checkpoint config."""
        return np.dtype(self._config.get("input_dtype", "float32"))


def export_checkpoint(
    out_dir: str,
    model: str,
    params: Any,
    kwargs: Optional[Dict[str, Any]] = None,
    input_shape: Optional[Sequence[int]] = None,
    input_dtype: str = "float32",
    apply_kwargs: Optional[Dict[str, Any]] = None,
    class_names: Optional[Sequence[str]] = None,
    use_orbax: bool = True,
) -> str:
    """Write a JAXServer-servable checkpoint directory."""
    os.makedirs(out_dir, exist_ok=True)
    cfg = {
        "model": model,
        "kwargs": kwargs or {},
        "input_dtype": input_dtype,
    }
    if input_shape is not None:
        cfg["input_shape"] = list(input_shape)
    if apply_kwargs:
        cfg["apply_kwargs"] = apply_kwargs
    if class_names:
        cfg["class_names"] = list(class_names)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(cfg, f)
    if use_orbax:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(os.path.join(out_dir, "params")), params)
        ckptr.wait_until_finished()
    else:
        import flax.serialization

        with open(os.path.join(out_dir, "params.msgpack"), "wb") as f:
            f.write(flax.serialization.to_bytes(params))
    return out_dir
