"""XGBoost prepackaged server (parity: `servers/xgboostserver/xgboostserver/
XGBoostServer.py:10-26`). xgboost is not installed in this image; the class
degrades with a clear error at load() so graph specs referencing it still parse.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu import storage
from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError

BOOSTER_FILE = "model.bst"


class XGBoostServer(SeldonComponent):
    def __init__(self, model_uri: str = "", **kwargs):
        super().__init__(**kwargs)
        self.model_uri = model_uri
        self.ready = False
        self._booster = None

    def load(self) -> None:
        if self.ready:
            return
        try:
            import xgboost as xgb
        except ImportError as e:
            raise SeldonError(
                "XGBOOST_SERVER requires the xgboost package, which is not installed",
                status_code=500,
            ) from e
        path = storage.download(self.model_uri)
        if os.path.isdir(path):
            path = os.path.join(path, BOOSTER_FILE)
        self._booster = xgb.Booster(model_file=path)
        self._xgb = xgb
        self.ready = True

    def predict(self, X: np.ndarray, names: Sequence[str], meta: Optional[Dict] = None):
        if not self.ready:
            self.load()
        dmat = self._xgb.DMatrix(X)
        return self._booster.predict(dmat)
