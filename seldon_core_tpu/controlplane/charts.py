"""Helm-chart rendering (in-repo subset renderer) + drift guarantees.

The reference packages its control plane and sample topologies as helm
charts (`helm-charts/seldon-core-operator/templates/statefulset.yaml:1-70`,
`helm-charts/seldon-mab/templates/mab.json`). This build ships real charts
under ``deploy/charts/`` — valid for stock ``helm install`` — written in a
deliberately restricted template subset so this module can render them
without the helm binary (absent from CI and this image):

    {{ .Values.a.b }}                 dotted lookups (Values/Release/Chart)
    {{ .Values.x | default "y" }}     default filter
    {{ .Values.x | toJson }}          JSON-encode a value
    {{ .Values.x | b64enc }}          base64 of the (string) value
    {{- if .Values.flag }} / {{- else }} / {{- end }}   truthiness blocks
                                      (non-nested, like the charts we ship)

Tests assert drift both ways: the operator chart rendered with default
values must equal the raw manifests (``deploy/{crd,operator}.yaml``), and
each topology chart must equal its ``deploy/examples/*.json`` CR — so
"helm user" and "kubectl apply user" can never see different objects.
"""

from __future__ import annotations

import base64
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

CHARTS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "deploy", "charts",
)

_EXPR = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}")
_IF = re.compile(r"^\s*if\s+(.*)$")


def _load_yaml(text: str) -> Any:
    import yaml

    return list(yaml.safe_load_all(text))


def _lookup(path: str, ctx: Dict[str, Any]) -> Any:
    cur: Any = ctx
    for part in path.lstrip(".").split("."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


def _eval_expr(expr: str, ctx: Dict[str, Any]) -> Any:
    """`.Values.a.b | default "x" | toJson` — left-to-right pipeline."""
    parts = [p.strip() for p in expr.split("|")]
    head = parts[0]
    if head.startswith('"') and head.endswith('"'):
        value: Any = head[1:-1]
    elif head.startswith("."):
        value = _lookup(head, ctx)
    else:
        raise ValueError(f"unsupported template expression {expr!r}")
    for f in parts[1:]:
        if f.startswith("default"):
            arg = f[len("default"):].strip()
            if value in (None, ""):
                value = arg[1:-1] if arg.startswith('"') else _lookup(arg, ctx)
        elif f == "toJson":
            value = json.dumps(value)
        elif f == "b64enc":
            value = base64.b64encode(str(value).encode()).decode()
        elif f == "quote":
            value = json.dumps(str(value))
        elif f == "int":
            value = int(value)
        else:
            raise ValueError(f"unsupported template filter {f!r}")
    return value


def render_template(text: str, ctx: Dict[str, Any]) -> str:
    """Render one template file under the documented subset."""
    out: List[str] = []
    # stack of (emitting, seen_true) for if/else/end
    stack: List[List[bool]] = []

    def emitting() -> bool:
        return all(frame[0] for frame in stack)

    pos = 0
    for m in _EXPR.finditer(text):
        literal = text[pos:m.start()]
        # `{{-` trims preceding whitespace+newline, `-}}` trims following
        if m.group(0).startswith("{{-"):
            literal = literal.rstrip(" \t")
            if literal.endswith("\n"):
                literal = literal[:-1]
        if emitting():
            out.append(literal)
        expr = m.group(1)
        pos = m.end()
        if m.group(0).endswith("-}}") and pos < len(text) and text[pos] == "\n":
            pos += 1
        ifm = _IF.match(expr)
        if ifm:
            cond = bool(_eval_expr(ifm.group(1), ctx)) if emitting() else False
            stack.append([cond, cond])
            continue
        if expr == "else":
            if not stack:
                raise ValueError("else without if")
            frame = stack[-1]
            frame[0] = (not frame[1]) and all(f[0] for f in stack[:-1])
            frame[1] = frame[1] or frame[0]
            continue
        if expr == "end":
            if not stack:
                raise ValueError("end without if")
            stack.pop()
            continue
        if emitting():
            value = _eval_expr(expr, ctx)
            out.append("" if value is None else str(value))
    if stack:
        raise ValueError("unclosed if block")
    out.append(text[pos:])
    return "".join(out)


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    merged = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(merged.get(k), dict):
            merged[k] = _deep_merge(merged[k], v)
        else:
            merged[k] = v
    return merged


def render_chart(
    chart_dir: str,
    values: Optional[Dict[str, Any]] = None,
    namespace: str = "seldon-system",
    release: str = "seldon",
) -> List[Tuple[str, str]]:
    """Render every template of a chart. Returns [(template_name, text)]."""
    import yaml

    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        chart_meta = yaml.safe_load(f)
    with open(os.path.join(chart_dir, "values.yaml")) as f:
        default_values = yaml.safe_load(f) or {}
    ctx = {
        "Values": _deep_merge(default_values, values or {}),
        "Release": {"Name": release, "Namespace": namespace},
        "Chart": chart_meta,
    }
    tmpl_dir = os.path.join(chart_dir, "templates")
    rendered: List[Tuple[str, str]] = []
    for name in sorted(os.listdir(tmpl_dir)):
        if name.startswith("_"):
            continue
        with open(os.path.join(tmpl_dir, name)) as f:
            rendered.append((name, render_template(f.read(), ctx)))
    return rendered


def render_chart_docs(chart_dir: str, values: Optional[Dict[str, Any]] = None,
                      **kw: Any) -> List[Any]:
    """Rendered chart as parsed YAML/JSON documents (drift-test currency)."""
    docs: List[Any] = []
    for name, text in render_chart(chart_dir, values, **kw):
        if name.endswith(".json"):
            docs.append(json.loads(text))
        else:
            docs.extend(d for d in _load_yaml(text) if d is not None)
    return docs
