"""Kubernetes Quantity / IntOrString parsing.

The reference vendors a 2.1k-line protobuf JsonFormat to accept k8s
`resource.Quantity` ("500m", "1Gi") and `IntOrString` values inside
componentSpecs (`engine/src/main/java/io/seldon/engine/pb/
{QuantityUtils,IntOrStringUtils}.java`). The dataclass-based spec here needs
only the value semantics: parse the suffix grammar to a float so the
validator can check CR resource requests and the renderer can compare/scale
them.
"""

from __future__ import annotations

import re
from typing import Union

# k8s suffix grammar: decimal SI (n, u, m, k, M, G, ...), binary
# (Ki, Mi, ...). Plain scientific notation (e.g. "1e3") is also legal.
_SUFFIXES = {
    "n": 1e-9, "u": 1e-6, "m": 1e-3,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}

_QUANTITY_RE = re.compile(
    r"^(?P<num>[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?)"
    r"(?P<suffix>n|u|m|k|Ki|[MGTPE]i?)?$"
)


def parse_quantity(value: Union[str, int, float]) -> float:
    """'500m' -> 0.5, '1Gi' -> 1073741824.0, 2 -> 2.0. Raises ValueError on
    anything outside the Quantity grammar (matching the k8s API's rejection)."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {value!r}")
    num = float(m.group("num"))
    suffix = m.group("suffix")
    if not suffix:
        return num
    factor = _SUFFIXES.get(suffix)
    if factor is None:  # regex/table drift must stay a ValueError
        raise ValueError(f"invalid quantity suffix {suffix!r}")
    return num * factor


def parse_int_or_string(value: Union[str, int]) -> Union[int, str]:
    """k8s IntOrString: ints pass through, numeric strings become ints,
    percent strings ('25%') and names stay strings (their k8s meaning is
    field-specific)."""
    if isinstance(value, bool):
        raise ValueError(f"invalid IntOrString {value!r}")
    if isinstance(value, int):
        return value
    s = str(value).strip()
    if re.fullmatch(r"[+-]?\d+", s):
        return int(s)
    return s


def validate_resources(resources: dict, path: str, problems: list) -> None:
    """Check every quantity in a k8s resources block ({limits,requests});
    appends problem strings in the validator's format."""
    for section in ("limits", "requests"):
        for key, value in (resources.get(section) or {}).items():
            try:
                q = parse_quantity(value)
            except ValueError:
                problems.append(f"{path}.{section}.{key}: invalid quantity {value!r}")
                continue
            if q < 0:
                problems.append(f"{path}.{section}.{key}: negative quantity {value!r}")
