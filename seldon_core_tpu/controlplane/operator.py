"""Control-plane reconcile loop (the operator).

The reference's operator is an external Go controller (cloned at build time,
`seldon-controller/Makefile:5-9`) deployed by
`helm-charts/seldon-core-operator/templates/statefulset.yaml:1-70`: it watches
``SeldonDeployment`` CRs, renders per-predictor Deployments with the engine
injected, and converges the cluster, with a defaulting/validating webhook in
front. This module is that loop as a small Python process:

    watch CR sources -> validate + default -> render -> diff -> apply/delete
                                         \\-> status written back per CR

The cluster is a pluggable backend. ``FileCluster`` (the default) stores
applied manifests as JSON files keyed by kind/namespace/name — a faithful,
testable stand-in for ``kubectl apply`` that also works as a local dry-run
target; a real-cluster backend only needs apply/delete/list to swap in
(``KubectlCluster`` shells out to kubectl when a cluster is reachable).

Admission (webhook role): a CR that fails validation is NOT partially
applied — its status goes to Failed with the problem list, matching the
reference's rejection of bad graphs (`testing/scripts/test_bad_graphs.py`).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import signal
import subprocess
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from seldon_core_tpu.contracts.graph import SeldonDeploymentSpec
from seldon_core_tpu.controlplane.render import render_manifests
from seldon_core_tpu.controlplane.validate import default_deployment, validate_deployment

logger = logging.getLogger(__name__)

OWNER_LABEL = "seldon-deployment-id"


def _manifest_key(m: Dict[str, Any]) -> Tuple[str, str, str]:
    meta = m.get("metadata", {})
    return (m.get("kind", ""), meta.get("namespace", "default"), meta.get("name", ""))


class FileCluster:
    """Applied-manifest store: one JSON file per object under
    ``<root>/<kind>/<namespace>/<name>.json``. apply() is idempotent and
    reports created/updated/unchanged so the reconciler can log convergence
    the way a controller's event stream would."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, kind: str, namespace: str, name: str) -> str:
        return os.path.join(self.root, kind.lower(), namespace, f"{name}.json")

    def apply(self, manifest: Dict[str, Any]) -> str:
        kind, namespace, name = _manifest_key(manifest)
        if not kind or not name:
            raise ValueError(f"manifest missing kind or metadata.name: {manifest}")
        path = self._path(kind, namespace, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        serialized = json.dumps(manifest, indent=2, sort_keys=True)
        if os.path.exists(path):
            with open(path) as f:
                if f.read() == serialized:
                    return "unchanged"
            status = "updated"
        else:
            status = "created"
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(serialized)
        os.replace(tmp, path)
        return status

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        path = self._path(kind, namespace, name)
        if os.path.exists(path):
            os.remove(path)
            return True
        return False

    def get(self, kind: str, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        path = self._path(kind, namespace, name)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def list(self, label: Optional[str] = None, value: Optional[str] = None) -> List[Dict[str, Any]]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                if not fn.endswith(".json"):
                    continue
                with open(os.path.join(dirpath, fn)) as f:
                    m = json.load(f)
                labels = m.get("metadata", {}).get("labels", {})
                if label is not None and labels.get(label) != value:
                    continue
                out.append(m)
        return out


class KubectlCluster:
    """Real-cluster backend: shells out to kubectl. Only used when a
    kubeconfig/cluster is actually reachable; everything above it is
    backend-agnostic."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    def _resource_version(self, manifest: Dict[str, Any]) -> Optional[str]:
        """resourceVersion of the live object, None when it does not exist.
        --ignore-not-found separates 'absent' (rc 0, empty output) from a
        real get failure (rc != 0 — apiserver timeout, RBAC), which raises:
        a transient error must not misreport an update as a creation."""
        meta = manifest.get("metadata", {})
        args = [self.kubectl, "get", manifest.get("kind", "").lower(),
                meta.get("name", ""), "--ignore-not-found",
                "-o", "jsonpath={.metadata.resourceVersion}"]
        if meta.get("namespace"):
            # no -n when the manifest omits it: apply uses the context
            # default namespace, and get must look in the same place
            args += ["-n", meta["namespace"]]
        res = subprocess.run(args, capture_output=True)
        if res.returncode != 0:
            raise RuntimeError(f"kubectl get failed: {res.stderr.decode()}")
        rv = res.stdout.decode().strip()
        return rv or None

    def apply(self, manifest: Dict[str, Any]) -> str:
        # created/updated/unchanged from machine-stable signals only
        # (exit codes, -o json, resourceVersion) — kubectl's human apply
        # message ("configured"/"unchanged") is not a stable interface.
        rv_before = self._resource_version(manifest)
        res = subprocess.run(
            [self.kubectl, "apply", "-f", "-", "-o", "json"],
            input=json.dumps(manifest).encode(),
            capture_output=True,
        )
        if res.returncode != 0:
            raise RuntimeError(f"kubectl apply failed: {res.stderr.decode()}")
        try:
            rv_after = json.loads(res.stdout.decode()).get(
                "metadata", {}).get("resourceVersion")
        except (ValueError, AttributeError) as e:
            raise RuntimeError(f"kubectl apply returned non-JSON output: {e}")
        if rv_before is None:
            return "created"
        return "unchanged" if rv_after == rv_before else "updated"

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        # -o name prints one line per deleted object (machine format);
        # --ignore-not-found + empty output = nothing existed
        res = subprocess.run(
            [self.kubectl, "delete", kind.lower(), name, "-n", namespace,
             "--ignore-not-found", "-o", "name"],
            capture_output=True,
        )
        return res.returncode == 0 and bool(res.stdout.strip())

    def list(self, label: Optional[str] = None, value: Optional[str] = None) -> List[Dict[str, Any]]:
        items: List[Dict[str, Any]] = []
        # VirtualServices queried separately: the Istio CRD may be absent, and
        # a missing resource type would fail the whole combined query (which
        # would orphan VirtualServices on prune/delete).
        for kinds in ("deployments,services,horizontalpodautoscalers",
                      "virtualservices.networking.istio.io"):
            args = [self.kubectl, "get", kinds, "-A", "-o", "json"]
            if label is not None:
                args += ["-l", f"{label}={value}"]
            res = subprocess.run(args, capture_output=True)
            if res.returncode != 0:
                continue
            items.extend(json.loads(res.stdout.decode()).get("items", []))
        return items


@dataclass
class ReconcileResult:
    name: str
    ok: bool
    applied: Dict[str, str] = field(default_factory=dict)  # "Kind/ns/name" -> status
    deleted: List[str] = field(default_factory=list)
    problems: List[str] = field(default_factory=list)
    transient: bool = False  # failed for a reason a retry might fix

    def to_status(self) -> Dict[str, Any]:
        return {
            "state": "Available" if self.ok else "Failed",
            "description": "; ".join(self.problems) if self.problems else "",
            "applied": self.applied,
            "deleted": self.deleted,
        }


class Reconciler:
    """Converge one SeldonDeployment: desired = render(CR), actual = objects
    in the cluster carrying this CR's owner label; apply the difference."""

    def __init__(
        self,
        cluster,
        namespace: str = "default",
        engine_image: Optional[str] = None,
        tpu_chips: int = 1,
        tpu_topology: Optional[str] = None,
    ):
        self.cluster = cluster
        self.namespace = namespace
        self.engine_image = engine_image
        self.tpu_chips = tpu_chips
        self.tpu_topology = tpu_topology

    def reconcile(self, sdep: SeldonDeploymentSpec | Dict[str, Any]) -> ReconcileResult:
        if isinstance(sdep, dict):
            sdep = SeldonDeploymentSpec.from_dict(sdep)
        sdep = default_deployment(sdep)
        problems = validate_deployment(sdep)
        if problems:
            # webhook semantics: reject outright, change nothing
            return ReconcileResult(name=sdep.name, ok=False, problems=problems)

        kwargs: Dict[str, Any] = {
            "namespace": self.namespace,
            "tpu_chips": self.tpu_chips,
            "tpu_topology": self.tpu_topology,
            "validate": False,  # already validated above
        }
        if self.engine_image:
            kwargs["engine_image"] = self.engine_image
        desired = render_manifests(sdep, **kwargs)
        for m in desired:
            m.setdefault("metadata", {}).setdefault("labels", {})[OWNER_LABEL] = sdep.name

        result = ReconcileResult(name=sdep.name, ok=True)
        desired_keys = set()
        for m in desired:
            key = _manifest_key(m)
            desired_keys.add(key)
            status = self.cluster.apply(m)
            result.applied["/".join(key)] = status

        # prune: objects we own that the new spec no longer renders
        # (e.g. a predictor removed, an HPA dropped, a VirtualService gone)
        for m in self.cluster.list(label=OWNER_LABEL, value=sdep.name):
            key = _manifest_key(m)
            if key not in desired_keys:
                if self.cluster.delete(*key):
                    result.deleted.append("/".join(key))
        return result

    def delete(self, name: str) -> List[str]:
        """CR removed: delete everything carrying its owner label."""
        gone = []
        for m in self.cluster.list(label=OWNER_LABEL, value=name):
            key = _manifest_key(m)
            if self.cluster.delete(*key):
                gone.append("/".join(key))
        return gone


class Operator:
    """The watch loop over a directory of CR files (*.json / *.yaml / *.yml).

    Each pass: parse every CR source, reconcile the changed ones (content
    hash), delete owned objects of CRs whose files vanished, and write each
    CR's status to ``<cr-dir>/.status/<name>.json`` — the stand-in for the
    CRD status subresource (`templates/crd.yaml` ``subresources.status``)."""

    def __init__(
        self,
        cr_dir: str,
        reconciler: Reconciler,
        interval: float = 2.0,
        status_dir: Optional[str] = None,
        clock=None,
        sleep=None,
    ):
        self.cr_dir = cr_dir
        self.reconciler = reconciler
        self.interval = interval
        # separate from cr_dir when the CR source is read-only (e.g. a
        # mounted ConfigMap)
        self.status_dir = status_dir or os.path.join(cr_dir, ".status")
        # Injectable time pair (the autoscaler's idiom,
        # controlplane/autoscaler.py): ``clock`` stamps, ``sleep`` waits
        # between passes. Tests hand in testing.faults.FaultClock and its
        # advance so whole reconcile loops run in zero wall time — no
        # time.sleep dependence in any operator test.
        self.clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else time.sleep
        self._seen: Dict[str, str] = {}  # cr name -> content hash
        self._sources: Dict[str, str] = {}  # cr name -> file path
        self._wrote_status: set = set()  # names written THIS pass
        self._stop = False

    # ------------------------------------------------------------------
    def _load_crs(self) -> Tuple[Dict[str, Tuple[Dict[str, Any], str, str]], set]:
        """Returns (name -> (cr dict, content hash, path), parsed_paths).
        Unparseable files surface as Failed status under the file's basename —
        they are NOT treated as deletions (a file caught mid-rewrite must not
        tear down its live objects); ``parsed_paths`` lets the deletion sweep
        distinguish a torn write (path absent from it) from a file that
        parsed fine but now names a different CR."""
        crs: Dict[str, Tuple[Dict[str, Any], str, str]] = {}
        parsed_paths: set = set()
        if not os.path.isdir(self.cr_dir):
            return crs, parsed_paths
        for fn in sorted(os.listdir(self.cr_dir)):
            if not fn.endswith((".json", ".yaml", ".yml")):
                continue
            path = os.path.join(self.cr_dir, fn)
            try:
                with open(path) as f:
                    raw = f.read()
                if fn.endswith(".json"):
                    cr = json.loads(raw)
                else:
                    import yaml

                    cr = yaml.safe_load(raw)
                if not isinstance(cr, dict):
                    raise ValueError("CR must be a mapping")
            except Exception as e:
                name = os.path.splitext(fn)[0]
                self._write_status(name, {"state": "Failed", "description": f"unparseable CR: {e}"})
                logger.error("CR %s unparseable: %s", path, e)
                continue
            name = cr.get("metadata", {}).get("name") or cr.get("spec", {}).get("name") or cr.get("name") or os.path.splitext(fn)[0]
            digest = hashlib.sha256(json.dumps(cr, sort_keys=True).encode()).hexdigest()
            crs[name] = (cr, digest, path)
            parsed_paths.add(path)
        return crs, parsed_paths

    def _write_status(self, name: str, status: Dict[str, Any]) -> None:
        os.makedirs(self.status_dir, exist_ok=True)
        path = os.path.join(self.status_dir, f"{name}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(status, f, indent=2)
        os.replace(tmp, path)
        self._wrote_status.add(name)

    def _sweep_stale_status(self, crs: Dict[str, Any]) -> List[str]:
        """Remove status files no live CR backs: deleting a CR used to
        orphan ``.status/<name>.json`` forever (the owned objects were
        pruned but the status record accumulated).  A just-written status
        (this pass — the 'Deleted' tombstone included, so one pass can
        still read it) and any tracked or parsed CR's status are kept;
        everything else is a leftover from a removed CR or a previous
        operator incarnation."""
        if not os.path.isdir(self.status_dir):
            return []
        swept = []
        for fn in sorted(os.listdir(self.status_dir)):
            if not fn.endswith(".json"):
                continue
            name = os.path.splitext(fn)[0]
            if (name in crs or name in self._sources
                    or name in self._wrote_status):
                continue
            try:
                os.remove(os.path.join(self.status_dir, fn))
            except OSError:
                continue  # racing writer/reader: retry next pass
            swept.append(name)
            logger.info("swept stale status for removed CR %s", name)
        return swept

    def read_status(self, name: str) -> Optional[Dict[str, Any]]:
        path = os.path.join(self.status_dir, f"{name}.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    # ------------------------------------------------------------------
    def run_once(self) -> Dict[str, ReconcileResult]:
        """One reconcile pass; returns results for CRs that were acted on."""
        results: Dict[str, ReconcileResult] = {}
        self._wrote_status = set()
        crs, parsed_paths = self._load_crs()

        # Deletions first, keyed on the tracked source path (covers CRs whose
        # reconcile only ever failed transiently). A tracked CR is gone when
        # its file vanished OR the file parsed cleanly to a different name
        # (rename-in-place). A file that exists but failed to parse is a torn
        # write: keep the live objects.
        for name, path in list(self._sources.items()):
            if name in crs:
                continue
            if os.path.exists(path) and path not in parsed_paths:
                continue  # momentarily unparseable — not a deletion
            gone = self.reconciler.delete(name)
            logger.info("CR %s removed; deleted %d objects", name, len(gone))
            results[name] = ReconcileResult(name=name, ok=True, deleted=gone)
            self._write_status(name, {"state": "Deleted", "deleted": gone})
            self._seen.pop(name, None)
            del self._sources[name]

        for name, (cr, digest, path) in crs.items():
            if self._seen.get(name) == digest:
                continue
            try:
                res = self.reconciler.reconcile(cr)
            except Exception as e:  # keep the loop alive on a bad CR
                logger.exception("reconcile %s failed", name)
                res = ReconcileResult(name=name, ok=False, problems=[str(e)], transient=True)
            results[name] = res
            self._write_status(name, res.to_status())
            # Mark seen on success and on stable validation failures (no point
            # re-spamming those); an exception (apply error, API hiccup) leaves
            # the CR unseen so the next pass retries it.
            if not res.transient:
                self._seen[name] = digest
            self._sources[name] = path
            logger.info(
                "reconciled %s: %s (%d applied, %d deleted)",
                name, "ok" if res.ok else f"FAILED: {res.problems}",
                len(res.applied), len(res.deleted),
            )
        # last: sweep status files no live CR backs (a 'Deleted' tombstone
        # written above survives this pass and is swept on the next)
        self._sweep_stale_status(crs)
        return results

    def run_forever(self) -> None:
        signal.signal(signal.SIGTERM, lambda *_: setattr(self, "_stop", True))
        signal.signal(signal.SIGINT, lambda *_: setattr(self, "_stop", True))
        logger.info("operator watching %s every %.1fs", self.cr_dir, self.interval)
        while not self._stop:
            t0 = self.clock()
            try:
                self.run_once()
            except Exception:
                # a broken pass (unwritable status dir, backend outage) must
                # not crash-loop the controller; retry next tick
                logger.exception("reconcile pass failed")
            # constant cadence on the injected clock: the wait shrinks by
            # the pass's own duration, so a slow reconcile (big cluster,
            # kubectl round-trips) doesn't stretch the watch period to
            # interval + pass time
            elapsed = self.clock() - t0
            self._sleep(max(self.interval - elapsed, 0.0))
        logger.info("operator stopped")
