"""SeldonDeployment CR -> Kubernetes manifests.

The capability of the reference operator's reconcile step (SURVEY.md §3.4:
per-predictor Deployments with the engine container injected and
``ENGINE_PREDICTOR`` carrying the base64 predictor spec, Services, ingress
annotations, HPA), as a pure function — usable from a kopf/controller loop or
a CLI (`seldon-core-tpu render`), and trivially testable without a cluster.

TPU-first differences from the reference's layout:
- one engine container runs the whole graph in-process on TPU (the reference
  injects an orchestrator beside N model containers); `componentSpecs`
  containers are still added for genuinely external units (remote endpoints);
- the engine container requests ``google.com/tpu`` chips and gets the
  TPU-topology nodeSelector instead of GPU resources;
- traffic splitting renders an Istio VirtualService weighted across
  per-predictor Services (the reference's Ambassador/Istio annotations).
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional

from seldon_core_tpu.contracts.graph import PredictorSpec, SeldonDeploymentSpec
from seldon_core_tpu.contracts.payload import SeldonError
from seldon_core_tpu.controlplane.validate import require_valid

DEFAULT_ENGINE_IMAGE = "seldon-core-tpu/engine:latest"
ENGINE_HTTP_PORT = 8000
ENGINE_GRPC_PORT = 5001
METRICS_PATH = "/metrics"


def _dep_labels(sdep: SeldonDeploymentSpec, p: PredictorSpec) -> Dict[str, str]:
    return {
        "app": f"{sdep.name}-{p.name}",
        "seldon-deployment-id": sdep.name,
        "seldon-predictor": p.name,
        **p.labels,
    }


def _engine_container(
    sdep: SeldonDeploymentSpec,
    p: PredictorSpec,
    engine_image: str,
    tpu_chips: int,
) -> Dict[str, Any]:
    env = [
        {"name": "DEPLOYMENT_NAME", "value": sdep.name},
        {"name": "PREDICTOR_ID", "value": p.name},
        {
            "name": "ENGINE_PREDICTOR",
            "value": base64.b64encode(json.dumps(p.to_dict()).encode()).decode(),
        },
        {"name": "ENGINE_SERVER_PORT", "value": str(ENGINE_HTTP_PORT)},
        {"name": "ENGINE_SERVER_GRPC_PORT", "value": str(ENGINE_GRPC_PORT)},
    ]
    for item in p.svc_orch_spec.get("env", []) or []:
        env.append(item)
    resources: Dict[str, Any] = p.svc_orch_spec.get("resources") or {}
    if tpu_chips > 0:
        resources = {
            "limits": {**resources.get("limits", {}), "google.com/tpu": tpu_chips},
            "requests": {**resources.get("requests", {}), "google.com/tpu": tpu_chips},
        }
    container = {
        "name": "seldon-engine-tpu",
        "image": engine_image,
        "args": ["engine", "--port", str(ENGINE_HTTP_PORT)],
        "env": env,
        "ports": [
            {"name": "http", "containerPort": ENGINE_HTTP_PORT},
            {"name": "grpc", "containerPort": ENGINE_GRPC_PORT},
        ],
        "readinessProbe": {
            "httpGet": {"path": "/ready", "port": ENGINE_HTTP_PORT},
            "initialDelaySeconds": 10,
            "periodSeconds": 5,
        },
        "livenessProbe": {
            "httpGet": {"path": "/live", "port": ENGINE_HTTP_PORT},
            "initialDelaySeconds": 20,
            "periodSeconds": 10,
        },
        "lifecycle": {
            # drain before shutdown: the reference's /pause rollout contract
            "preStop": {
                "httpGet": {"path": "/pause", "port": ENGINE_HTTP_PORT},
            }
        },
    }
    if resources:
        container["resources"] = resources
    return container


def _deployment(
    sdep: SeldonDeploymentSpec,
    p: PredictorSpec,
    namespace: str,
    engine_image: str,
    tpu_chips: int,
    tpu_topology: Optional[str],
) -> Dict[str, Any]:
    labels = _dep_labels(sdep, p)
    containers = [_engine_container(sdep, p, engine_image, tpu_chips)]
    node_selector: Dict[str, str] = {}
    if tpu_topology:
        node_selector["cloud.google.com/gke-tpu-topology"] = tpu_topology
    for cs in p.component_specs:
        spec = cs.get("spec", cs)
        containers.extend(spec.get("containers", []) or [])
        node_selector.update(spec.get("nodeSelector", {}) or {})
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"{sdep.name}-{p.name}",
            "namespace": namespace,
            "labels": labels,
            "annotations": {**sdep.annotations, **p.annotations},
        },
        "spec": {
            "replicas": p.replicas,
            "selector": {"matchLabels": {"app": labels["app"]}},
            "template": {
                "metadata": {
                    "labels": labels,
                    "annotations": {
                        "prometheus.io/scrape": "true",
                        "prometheus.io/path": METRICS_PATH,
                        "prometheus.io/port": str(ENGINE_HTTP_PORT),
                    },
                },
                "spec": {
                    "containers": containers,
                    **({"nodeSelector": node_selector} if node_selector else {}),
                    "terminationGracePeriodSeconds": 30,
                },
            },
        },
    }


def _service(sdep: SeldonDeploymentSpec, p: PredictorSpec, namespace: str) -> Dict[str, Any]:
    labels = _dep_labels(sdep, p)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{sdep.name}-{p.name}",
            "namespace": namespace,
            "labels": labels,
        },
        "spec": {
            "selector": {"app": labels["app"]},
            "ports": [
                {"name": "http", "port": ENGINE_HTTP_PORT, "targetPort": ENGINE_HTTP_PORT},
                {"name": "grpc", "port": ENGINE_GRPC_PORT, "targetPort": ENGINE_GRPC_PORT},
            ],
        },
    }


def _hpa(sdep: SeldonDeploymentSpec, p: PredictorSpec, namespace: str) -> Dict[str, Any]:
    return {
        "apiVersion": "autoscaling/v2",
        "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": f"{sdep.name}-{p.name}", "namespace": namespace},
        "spec": {
            "scaleTargetRef": {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "name": f"{sdep.name}-{p.name}",
            },
            "minReplicas": p.hpa_spec.get("minReplicas", 1),
            "maxReplicas": p.hpa_spec["maxReplicas"],
            **({"metrics": p.hpa_spec["metrics"]} if p.hpa_spec.get("metrics") else {}),
        },
    }


def _virtual_service(sdep: SeldonDeploymentSpec, namespace: str) -> Dict[str, Any]:
    routes = [
        {
            "destination": {
                "host": f"{sdep.name}-{p.name}.{namespace}.svc.cluster.local",
                "port": {"number": ENGINE_HTTP_PORT},
            },
            "weight": p.traffic,
        }
        for p in sdep.predictors
        if not p.shadow
    ]
    vs: Dict[str, Any] = {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": {"name": sdep.name, "namespace": namespace},
        "spec": {
            "hosts": [sdep.name],
            "http": [
                {
                    "match": [{"uri": {"prefix": f"/seldon/{namespace}/{sdep.name}/"}}],
                    "rewrite": {"uri": "/"},
                    "route": routes,
                }
            ],
        },
    }
    shadows = [p for p in sdep.predictors if p.shadow]
    if shadows:
        vs["spec"]["http"][0]["mirror"] = {
            "host": f"{sdep.name}-{shadows[0].name}.{namespace}.svc.cluster.local",
            "port": {"number": ENGINE_HTTP_PORT},
        }
    return vs


def _explainer_objects(
    sdep: SeldonDeploymentSpec, p: PredictorSpec, namespace: str, engine_image: str
) -> List[Dict[str, Any]]:
    """Explainer Deployment + Service for a predictor carrying the CRD
    ``explainer`` field (`proto/seldon_deployment.proto:45-51,63`). Default
    container serves analytics.explainers.SaliencyExplainer over the
    predictor's modelUri; ``containerSpec`` overrides it wholesale."""
    exp = p.explainer
    exp_type = exp.get("type", "saliency") or "saliency"
    if not exp.get("containerSpec") and exp_type not in ("saliency",):
        raise SeldonError(
            f"unsupported explainer type {exp_type!r}: built-in support is "
            "'saliency'; other explainers need an explicit containerSpec",
            reason="BAD_GRAPH",
        )
    name = f"{sdep.name}-{p.name}-explainer"
    labels = {**_dep_labels(sdep, p), "seldon-explainer": p.name}
    # copy: the spec's nested dict must not accumulate mutations (envFrom)
    # across renders of the same held spec object
    container = dict(exp["containerSpec"]) if exp.get("containerSpec") else None
    if not container:
        model_uri = exp.get("modelUri") or p.graph.model_uri or ""
        container = {
            "name": "explainer",
            "image": engine_image,
            "args": ["microservice",
                     "seldon_core_tpu.analytics.explainers.SaliencyExplainer", "REST"],
            "env": [
                {"name": "PREDICTIVE_UNIT_SERVICE_PORT", "value": str(ENGINE_HTTP_PORT)},
                {"name": "PREDICTIVE_UNIT_PARAMETERS", "value": json.dumps([
                    {"name": "model_uri", "value": model_uri, "type": "STRING"},
                ])},
            ],
            "ports": [{"name": "http", "containerPort": ENGINE_HTTP_PORT}],
        }
    pod_spec: Dict[str, Any] = {"containers": [container]}
    if exp.get("serviceAccountName"):
        pod_spec["serviceAccountName"] = exp["serviceAccountName"]
    if exp.get("envSecretRefName"):
        container["envFrom"] = list(container.get("envFrom", [])) + [
            {"secretRef": {"name": exp["envSecretRefName"]}}
        ]
    deployment = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"seldon-explainer-app": name}},
            "template": {
                "metadata": {"labels": {**labels, "seldon-explainer-app": name}},
                "spec": pod_spec,
            },
        },
    }
    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "selector": {"seldon-explainer-app": name},
            "ports": [{"name": "http", "port": ENGINE_HTTP_PORT,
                       "targetPort": ENGINE_HTTP_PORT}],
        },
    }
    return [deployment, service]


def render_manifests(
    sdep: SeldonDeploymentSpec,
    namespace: str = "default",
    engine_image: str = DEFAULT_ENGINE_IMAGE,
    tpu_chips: int = 1,
    tpu_topology: Optional[str] = None,
    validate: bool = True,
) -> List[Dict[str, Any]]:
    """Render the full manifest set for one SeldonDeployment CR."""
    if validate:
        sdep = require_valid(sdep)
    out: List[Dict[str, Any]] = []
    for p in sdep.predictors:
        out.append(_deployment(sdep, p, namespace, engine_image, tpu_chips, tpu_topology))
        out.append(_service(sdep, p, namespace))
        if p.hpa_spec.get("maxReplicas"):
            out.append(_hpa(sdep, p, namespace))
        if p.explainer:
            out.extend(_explainer_objects(sdep, p, namespace, engine_image))
    if len([p for p in sdep.predictors if not p.shadow]) > 1 or any(
        p.shadow for p in sdep.predictors
    ):
        out.append(_virtual_service(sdep, namespace))
    return out
