"""Control plane: defaulting/validation and manifest rendering for
SeldonDeployment-compatible CRs (capability of the reference's external Go
operator + webhooks — SURVEY.md §2.8, §3.4), plus the signal-driven
autoscaler that closes the elastic loop (controlplane/autoscaler.py,
docs/control-plane.md)."""

from seldon_core_tpu.controlplane.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    ReplicaSignals,
    decide_rebalance,
    decide_scale,
)
from seldon_core_tpu.controlplane.validate import default_deployment, validate_deployment
from seldon_core_tpu.controlplane.render import render_manifests

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "ReplicaSignals",
    "decide_rebalance",
    "decide_scale",
    "default_deployment",
    "validate_deployment",
    "render_manifests",
]
