"""Control plane: defaulting/validation and manifest rendering for
SeldonDeployment-compatible CRs (capability of the reference's external Go
operator + webhooks — SURVEY.md §2.8, §3.4)."""

from seldon_core_tpu.controlplane.validate import default_deployment, validate_deployment
from seldon_core_tpu.controlplane.render import render_manifests

__all__ = ["default_deployment", "validate_deployment", "render_manifests"]
