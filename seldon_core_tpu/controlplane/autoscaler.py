"""Signal-driven autoscaler: the loop that closes elastic serving.

The reference platform's defining capability is not the single replica —
it is the control loop around it: HPA-scaled Deployments behind the
engine's service, routers shifting traffic, an operator converging the
graph (PAPER.md layer map).  Every INPUT for that loop already exists
here — the scaling-signal snapshot (observability/timeline.py
``scaling_snapshot``: queue depth, slot/page pressure, handoff backlog,
TTFT / queue-wait / worst-gap quantiles), ``ReplicaSet`` dispatch
(runtime/engine.py), and the deterministic fault harness
(testing/faults.py).  This module is the loop itself:

    poll scaling_snapshot per replica -> pure decision -> actuate

with two actuators:

- **ReplicaSet size.**  Scale-up builds a replica through the injected
  factory and adds it behind least-loaded/prefix-aware dispatch.
  Scale-down DRAINS: the replica stops receiving fleet traffic
  immediately (``ReplicaSet.drain_replica``), its in-flight and queued
  requests run to completion, and only a provably idle replica is
  detached (``ReplicaSet.collect_drained``) — a live request is never
  dropped by a scale decision (tests/test_autoscaler.py proves the
  spike -> up -> quiesce -> down cycle resolves every client future).
- **The prefill:decode slice ratio** of ``disaggregation=
  "remote_prefill"`` deployments (``ContinuousBatcher.rebalance_disagg``)
  — the TPU-native scaling axis no Kubernetes primitive expresses: when
  the prompt-length mix shifts long (handoff backlog piles up while
  decode pages stay slack), devices move from the decode slice to the
  prefill slice, and back when the mix shifts short.  The rebalance is
  bit-exact: workers run the server's SAME compiled prefill programs on
  the re-split mesh (tests/test_autoscaler.py parity, dense + paged).

Determinism discipline (docs/control-plane.md):

- every decision is a PURE function of (signals, config, history) —
  :func:`decide_scale` / :func:`decide_rebalance` take plain data and
  return a :class:`Decision`; the ``Autoscaler`` object only gathers
  inputs, applies outputs, and keeps the bounded history;
- the clock is injectable (``testing.faults.FaultClock``) so cooldowns
  and stability windows advance by explicit test control, never wall
  time — there is no ``time.sleep`` anywhere in the decision path;
- the mutable history/tally state is lock-guarded: ``tick()`` runs on
  the controller thread while ``autoscaler_stats()`` is read by the
  /metrics scrape thread (racelint models this class; the exact
  interleaving an unlocked reconstruction loses a tally under is
  explored and replayed in tests/test_schedules.py).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# Decision kinds (Decision.action)
HOLD = "hold"
SCALE_UP = "scale_up"
SCALE_DOWN = "scale_down"
REBALANCE = "rebalance"


@dataclass(frozen=True)
class ReplicaSignals:
    """One replica's scaling signals, parsed from the
    ``observability.timeline.scaling_snapshot`` dict.  The field list here
    IS the controller's consumption contract with the snapshot schema —
    tests/test_scaling_schema.py pins every name/type/quantile key this
    parser touches, so a timeline refactor cannot silently starve the
    loop."""

    queue_depth: int = 0
    active_slots: int = 0
    total_slots: int = 1
    steps_in_flight: int = 0
    page_pressure: float = 0.0
    page_sheds_total: int = 0
    handoff_queue_depth: int = 0
    draining: bool = False
    ejected: bool = False
    prefill_devices: int = 0
    decode_devices: int = 0
    ttft_p95_s: Optional[float] = None
    queue_wait_p95_s: Optional[float] = None
    worst_gap_p95_s: Optional[float] = None

    @classmethod
    def from_scaling(cls, snap: dict) -> "ReplicaSignals":
        """Parse one ``scaling_snapshot()`` dict.  Quantiles come from the
        flight recorder's ``requests`` block when tracing is on; absent
        (tracing off) they stay None and the latency terms of the decision
        simply do not fire — load signals alone still scale."""
        req = snap.get("requests") or {}

        def q(key: str) -> Optional[float]:
            block = req.get(key) or {}
            v = block.get("p95")
            return None if v is None else float(v)

        return cls(
            queue_depth=int(snap.get("queue_depth", 0)),
            active_slots=int(snap.get("active_slots", 0)),
            total_slots=max(int(snap.get("total_slots", 1)), 1),
            steps_in_flight=int(snap.get("steps_in_flight", 0)),
            page_pressure=float(snap.get("page_pressure", 0.0)),
            page_sheds_total=int(snap.get("page_sheds_total", 0)),
            handoff_queue_depth=int(snap.get("handoff_queue_depth", 0)),
            draining=bool(snap.get("draining", False)),
            ejected=bool(snap.get("ejected", False)),
            prefill_devices=int(snap.get("prefill_devices", 0)),
            decode_devices=int(snap.get("decode_devices", 0)),
            ttft_p95_s=q("ttft_s"),
            queue_wait_p95_s=q("queue_wait_s"),
            worst_gap_p95_s=q("worst_gap_s"),
        )


@dataclass(frozen=True)
class AutoscalerConfig:
    """Thresholds and hysteresis for the scale decision.  Up and down use
    SEPARATE thresholds plus consecutive-tick stability windows and a
    cooldown, so a signal hovering at one boundary cannot flap the fleet
    (docs/control-plane.md "The decision function")."""

    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up triggers (any one, sustained up_stable_ticks):
    up_queue_per_slot: float = 1.0      # queued work / total slots
    up_page_pressure: float = 0.85      # page-pool in-use fraction
    up_ttft_p95_s: Optional[float] = None   # TTFT SLO (None = load-only)
    up_queue_wait_p95_s: Optional[float] = None
    up_stable_ticks: int = 2
    # scale-down trigger (all of, sustained down_stable_ticks):
    down_queue_per_slot: float = 0.25
    down_page_pressure: float = 0.5
    down_stable_ticks: int = 4
    cooldown_s: float = 30.0            # between any two scale actions
    # disagg prefill:decode rebalance (None disables):
    rebalance: bool = False
    rebalance_backlog_high: float = 1.0   # handoff backlog per prefill dev
    rebalance_backlog_low: float = 0.0    # backlog at/below = prefill slack
    rebalance_stable_ticks: int = 2
    rebalance_cooldown_s: float = 30.0
    min_prefill_devices: int = 1
    min_decode_devices: int = 1


@dataclass(frozen=True)
class Decision:
    """One tick's verdict: what to do and why.  ``action`` is one of
    hold / scale_up / scale_down / rebalance; ``target`` is the replica
    count (scale) or prefill-device count (rebalance) AFTER the action."""

    action: str = HOLD
    target: int = 0
    reason: str = ""


@dataclass(frozen=True)
class ControllerState:
    """The decision history a tick consumes — immutable so the decision
    functions stay pure (a new state is returned, never mutated in
    place).  ``over_ticks`` / ``under_ticks`` / ``long_ticks`` /
    ``short_ticks`` are the consecutive-tick stability counters;
    ``last_scale_t`` / ``last_rebalance_t`` anchor the cooldowns on the
    injected clock."""

    over_ticks: int = 0
    under_ticks: int = 0
    long_ticks: int = 0
    short_ticks: int = 0
    last_scale_t: float = float("-inf")
    last_rebalance_t: float = float("-inf")


def _fleet_pressure(signals: Sequence[ReplicaSignals]) -> Tuple[float, float]:
    """(queued work per slot, max page pressure) over the NON-draining,
    NON-ejected replicas — a draining replica's emptying queue must not
    drag the fleet mean down and mask real overload on the survivors,
    and an ejected corpse's frozen snapshot must not count as serving
    capacity at all."""
    live = [s for s in signals
            if not s.draining and not s.ejected] or list(signals)
    queued = sum(s.queue_depth + s.active_slots for s in live)
    slots = sum(s.total_slots for s in live) or 1
    pages = max((s.page_pressure for s in live), default=0.0)
    return queued / slots, pages


def decide_scale(
    signals: Sequence[ReplicaSignals],
    cfg: AutoscalerConfig,
    state: ControllerState,
    now: float,
    n_replicas: int,
    n_draining: int = 0,
    n_ejected: int = 0,
) -> Tuple[Decision, ControllerState]:
    """The pure replica-count decision: (signals, config, history) ->
    (decision, next history).  No clock reads, no I/O — ``now`` comes
    from the caller's injected clock, which is what lets
    tests/test_schedules.py and the spike scenario explore it
    deterministically."""
    if not signals:
        return Decision(HOLD, n_replicas, "no signals"), state
    queue_per_slot, page_pressure = _fleet_pressure(signals)
    live = [s for s in signals if not s.draining] or list(signals)

    over = queue_per_slot >= cfg.up_queue_per_slot or \
        page_pressure >= cfg.up_page_pressure
    if not over and cfg.up_ttft_p95_s is not None:
        over = any(s.ttft_p95_s is not None
                   and s.ttft_p95_s >= cfg.up_ttft_p95_s for s in live)
    if not over and cfg.up_queue_wait_p95_s is not None:
        over = any(s.queue_wait_p95_s is not None
                   and s.queue_wait_p95_s >= cfg.up_queue_wait_p95_s
                   for s in live)
    under = (queue_per_slot <= cfg.down_queue_per_slot
             and page_pressure <= cfg.down_page_pressure)

    state = replace(
        state,
        over_ticks=state.over_ticks + 1 if over else 0,
        under_ticks=state.under_ticks + 1 if under else 0,
    )
    in_cooldown = now - state.last_scale_t < cfg.cooldown_s
    # replicas taking fleet traffic: drained AND ejected members are out
    serving = n_replicas - n_draining - n_ejected

    # replace-on-ejection (docs/control-plane.md): an unplanned death is a
    # capacity loss the load signals may take ticks to notice — replace
    # the corpse NOW rather than waiting for queues to back up. Stability
    # windows don't apply (the ejection itself is the sustained signal);
    # the cooldown still does, so a flapping replica cannot stampede the
    # fleet.
    if (n_ejected > 0 and not in_cooldown
            and serving < cfg.max_replicas):
        return (
            Decision(SCALE_UP, serving + 1,
                     f"{n_ejected} replica(s) ejected — replacing"),
            replace(state, over_ticks=0, under_ticks=0, last_scale_t=now),
        )
    if (over and state.over_ticks >= cfg.up_stable_ticks
            and not in_cooldown and serving < cfg.max_replicas):
        return (
            Decision(SCALE_UP, serving + 1,
                     f"queue/slot {queue_per_slot:.2f}, pages "
                     f"{page_pressure:.2f} over for {state.over_ticks} ticks"),
            replace(state, over_ticks=0, under_ticks=0, last_scale_t=now),
        )
    if (under and state.under_ticks >= cfg.down_stable_ticks
            and not in_cooldown and serving > cfg.min_replicas):
        return (
            Decision(SCALE_DOWN, serving - 1,
                     f"queue/slot {queue_per_slot:.2f} under for "
                     f"{state.under_ticks} ticks"),
            replace(state, over_ticks=0, under_ticks=0, last_scale_t=now),
        )
    return Decision(HOLD, serving, "within band"), state


def decide_rebalance(
    signals: Sequence[ReplicaSignals],
    cfg: AutoscalerConfig,
    state: ControllerState,
    now: float,
) -> Tuple[Decision, ControllerState]:
    """The pure prefill:decode split decision for disaggregated replicas.
    The steering signal is the handoff backlog per prefill device — the
    direct trace of the prompt-length mix: long prompts pile admissions
    up on the prefill slice while decode pages stay slack; short prompts
    leave prefill idle while the decode batch is the constraint."""
    dis = [s for s in signals
           if s.prefill_devices > 0 and s.decode_devices > 0]
    if not cfg.rebalance or not dis:
        return Decision(HOLD, 0, "rebalance off or no disagg replica"), state
    s = dis[0]  # one disagg topology per predictor by construction
    backlog_per_dev = s.handoff_queue_depth / max(s.prefill_devices, 1)
    long_mix = backlog_per_dev >= cfg.rebalance_backlog_high
    short_mix = (s.handoff_queue_depth <= cfg.rebalance_backlog_low
                 and s.queue_depth == 0)
    state = replace(
        state,
        long_ticks=state.long_ticks + 1 if long_mix else 0,
        short_ticks=state.short_ticks + 1 if short_mix else 0,
    )
    if now - state.last_rebalance_t < cfg.rebalance_cooldown_s:
        return Decision(HOLD, s.prefill_devices, "rebalance cooldown"), state
    if (long_mix and state.long_ticks >= cfg.rebalance_stable_ticks
            and s.decode_devices > cfg.min_decode_devices):
        return (
            Decision(REBALANCE, s.prefill_devices + 1,
                     f"handoff backlog {s.handoff_queue_depth} over "
                     f"{s.prefill_devices} prefill devs for "
                     f"{state.long_ticks} ticks"),
            replace(state, long_ticks=0, short_ticks=0,
                    last_rebalance_t=now),
        )
    if (short_mix and state.short_ticks >= cfg.rebalance_stable_ticks
            and s.prefill_devices > cfg.min_prefill_devices):
        return (
            Decision(REBALANCE, s.prefill_devices - 1,
                     f"prefill idle for {state.short_ticks} ticks"),
            replace(state, long_ticks=0, short_ticks=0,
                    last_rebalance_t=now),
        )
    return Decision(HOLD, s.prefill_devices, "split within band"), state


class Autoscaler:
    """The control loop around a :class:`~seldon_core_tpu.runtime.engine.
    ReplicaSet`: gather per-replica signals, run the pure decisions, apply
    them.  ``tick()`` is one pass — tests and the fault harness drive it
    directly; ``run_forever`` is the production loop on the injectable
    clock/sleep pair (the operator's idiom, controlplane/operator.py).

    Concurrency: ``tick()`` runs on the controller thread while
    ``autoscaler_stats()`` serves the /metrics scrape thread and a second
    tick may arrive from an admin trigger — all mutable state
    (ControllerState, tallies, last decision) lives under ``self._lock``.
    The actuators are NOT called under it: ``ReplicaSet`` and the batcher
    take their own locks, and nesting ours outside theirs would couple
    two lock orders for no benefit (the tick section below swaps state
    first, then actuates lock-free).
    """

    def __init__(
        self,
        replica_set: Any,
        config: Optional[AutoscalerConfig] = None,
        replica_factory: Optional[Callable[[], Any]] = None,
        clock: Callable[[], float] = time.monotonic,
        interval_s: float = 5.0,
        snapshot_fn: Optional[Callable[[Any], dict]] = None,
    ):
        self.replica_set = replica_set
        self.config = config or AutoscalerConfig()
        self.replica_factory = replica_factory
        self.clock = clock
        self.interval_s = float(interval_s)
        if snapshot_fn is None:
            from seldon_core_tpu.observability.timeline import (
                scaling_snapshot)

            snapshot_fn = scaling_snapshot
        self._snapshot_fn = snapshot_fn
        self._lock = threading.Lock()
        self._state = ControllerState()
        self._stop = threading.Event()
        # lifetime tallies for /metrics (sync_controlplane catch-up idiom)
        self._scale_ups_total = 0
        self._scale_downs_total = 0
        self._rebalances_total = 0
        self._collected_total = 0
        self._ticks_total = 0
        self._last_decision = Decision()

    # -- signal gathering ------------------------------------------------
    def signals(self) -> List[ReplicaSignals]:
        reps = self.replica_set.members()
        draining = self.replica_set.draining_members()
        ej = getattr(self.replica_set, "ejected_members", None)
        ejected = ej() if ej is not None else []
        out = []
        for r in reps:
            snap = dict(self._snapshot_fn(r))
            if r in draining:
                snap["draining"] = True
            if r in ejected:
                snap["ejected"] = True
            out.append(ReplicaSignals.from_scaling(snap))
        return out

    # -- one pass ---------------------------------------------------------
    def tick(self) -> Decision:
        """One control pass: decide on fresh signals, actuate, and sweep
        drained replicas.  Returns the scale decision (rebalance runs as a
        side decision when enabled)."""
        # sweep fleet health FIRST: a replica that died since the last
        # tick must read as ejected in THIS tick's signals, so the replace
        # branch fires one control pass after the death, not two
        check = getattr(self.replica_set, "check_health", None)
        if check is not None:
            check()
        sigs = self.signals()
        now = self.clock()
        n = len(self.replica_set.members())
        n_drain = len(self.replica_set.draining_members())
        ej = getattr(self.replica_set, "ejected_members", None)
        n_ej = len(ej()) if ej is not None else 0
        with self._lock:
            self._ticks_total += 1
            decision, self._state = decide_scale(
                sigs, self.config, self._state, now, n, n_drain, n_ej)
            reb = Decision(HOLD, 0, "")
            if self.config.rebalance:
                reb, self._state = decide_rebalance(
                    sigs, self.config, self._state, now)
            self._last_decision = decision
        # actuate OUTSIDE the controller lock (see class docstring);
        # tallies count actions APPLIED, not decisions — an unactuatable
        # decision (no factory, last replica, rebalance refused) must not
        # tick the /metrics event counters while the fleet never moves
        applied_up = applied_down = applied_reb = False
        if decision.action == SCALE_UP:
            applied_up = self._actuate_up(decision)
        elif decision.action == SCALE_DOWN:
            applied_down = self._actuate_down(decision)
        if reb.action == REBALANCE:
            applied_reb = self._actuate_rebalance(reb)
        collected = self.replica_set.collect_drained()
        with self._lock:
            if applied_up:
                self._scale_ups_total += 1
            if applied_down:
                self._scale_downs_total += 1
            if applied_reb:
                self._rebalances_total += 1
            if collected:
                self._collected_total += len(collected)
        if collected:
            logger.info("autoscaler detached %d drained replica(s)",
                        len(collected))
        return decision

    def _actuate_up(self, decision: Decision) -> bool:
        # a replica still draining is WARM (loaded params, hot caches):
        # cancelling its drain is strictly cheaper than a cold build
        resumed = self.replica_set.undrain_replica()
        if resumed is not None:
            logger.info("autoscaler resumed a draining replica toward %d: "
                        "%s", decision.target, decision.reason)
            return True
        if self.replica_factory is None:
            logger.warning("scale-up decided (%s) but no replica factory "
                           "configured", decision.reason)
            return False
        replica = self.replica_factory()
        self.replica_set.add_replica(replica)
        logger.info("autoscaler scale-up to %d: %s", decision.target,
                    decision.reason)
        return True

    def _actuate_down(self, decision: Decision) -> bool:
        drained = self.replica_set.drain_replica()
        if drained is not None:
            logger.info("autoscaler draining one replica toward %d: %s",
                        decision.target, decision.reason)
        return drained is not None

    def _actuate_rebalance(self, decision: Decision) -> bool:
        from seldon_core_tpu.runtime.batcher import get_batcher_service

        moved = False
        for r in self.replica_set.members():
            svc = get_batcher_service(r)
            b = getattr(svc, "batcher", None)
            if b is not None and getattr(b, "_remote", None) is not None:
                if b.rebalance_disagg(decision.target):
                    moved = True
                    logger.info("autoscaler rebalanced prefill slice to "
                                "%d devices: %s", decision.target,
                                decision.reason)
        return moved

    # -- loop / stats ------------------------------------------------------
    def run_forever(self, sleep: Optional[Callable[[float], Any]] = None
                    ) -> None:
        """The production loop.  ``sleep`` is injectable like the clock —
        tests pass ``clock.advance`` so the loop runs in zero wall time;
        the default real sleep waits on the stop event so ``stop()``
        interrupts it immediately."""
        if sleep is None:
            sleep = lambda s: self._stop.wait(s)  # noqa: E731
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                # one broken pass (a replica torn down mid-poll) must not
                # kill the controller; the next tick re-reads the world
                logger.exception("autoscaler tick failed")
            sleep(self.interval_s)

    def stop(self) -> None:
        self._stop.set()

    def autoscaler_stats(self) -> dict:
        """Lifetime tallies + the current shape, for
        ``MetricsRegistry.sync_controlplane`` (scrape-thread reader — the
        same lock the tick's writes hold)."""
        with self._lock:
            last = self._last_decision
            out = {
                "autoscaler_replicas": len(self.replica_set.members()),
                "autoscaler_draining": len(
                    self.replica_set.draining_members()),
                "autoscaler_ticks_total": self._ticks_total,
                "autoscaler_scale_ups_total": self._scale_ups_total,
                "autoscaler_scale_downs_total": self._scale_downs_total,
                "autoscaler_rebalances_total": self._rebalances_total,
                "autoscaler_collected_total": self._collected_total,
                "autoscaler_last_action": last.action,
            }
        return out
