"""Defaulting + validation webhooks' logic, as pure functions.

The reference performs these in the operator's defaulting/validating webhook
(SURVEY.md §2.8: "defaulting/validating webhook"; invalid specs are rejected
before rollout — testing/scripts/test_bad_graphs.py). Same contract here:
``default_deployment`` fills the fields the webhook would, and
``validate_deployment`` returns every problem found (empty list = valid);
``require_valid`` raises SeldonError for API use.
"""

from __future__ import annotations

import re
from typing import List

from seldon_core_tpu.contracts.graph import (
    PredictiveUnit,
    SeldonDeploymentSpec,
    UnitImplementation,
    UnitType,
)
from seldon_core_tpu.contracts.payload import SeldonError
from seldon_core_tpu.controlplane.quantity import validate_resources

_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")  # RFC 1123 label

# implementations the engine can run without an endpoint or model_uri
_SELF_CONTAINED = {
    UnitImplementation.SIMPLE_MODEL,
    UnitImplementation.SIMPLE_ROUTER,
    UnitImplementation.RANDOM_ABTEST,
    UnitImplementation.AVERAGE_COMBINER,
    UnitImplementation.EPSILON_GREEDY,
    UnitImplementation.THOMPSON_SAMPLING,
    UnitImplementation.MAHALANOBIS_OD,
    UnitImplementation.ISOLATION_FOREST_OD,
    UnitImplementation.VAE_OD,
    UnitImplementation.SEQ2SEQ_OD,
}
_SERVER_IMPLS = {
    UnitImplementation.SKLEARN_SERVER,
    UnitImplementation.XGBOOST_SERVER,
    UnitImplementation.TENSORFLOW_SERVER,
    UnitImplementation.MLFLOW_SERVER,
    UnitImplementation.JAX_SERVER,
}


def default_deployment(sdep: SeldonDeploymentSpec) -> SeldonDeploymentSpec:
    """Fill the fields the reference's defaulting webhook would: predictor
    names, replicas>=1, and traffic weights when none are set (100 for a lone
    predictor; an even split across non-shadow predictors otherwise, so the
    rendered VirtualService never routes 0% everywhere)."""
    for i, p in enumerate(sdep.predictors):
        if not p.name:
            p.name = f"predictor-{i}"
        if p.replicas < 1:
            p.replicas = 1
    live = [p for p in sdep.predictors if not p.shadow]
    if live and not any(p.traffic for p in live):
        share, rem = divmod(100, len(live))
        for i, p in enumerate(live):
            p.traffic = share + (1 if i < rem else 0)
    return sdep


def _validate_unit(unit: PredictiveUnit, path: str, problems: List[str], seen: set) -> None:
    if not unit.name:
        problems.append(f"{path}: unit has no name")
    elif unit.name in seen:
        problems.append(f"{path}: duplicate unit name {unit.name!r}")
    else:
        seen.add(unit.name)

    runnable = (
        (unit.implementation in _SELF_CONTAINED)
        or (unit.implementation in _SERVER_IMPLS and (unit.model_uri or unit.implementation == UnitImplementation.TENSORFLOW_SERVER))
        or (unit.endpoint is not None and unit.endpoint.service_host)
        or unit.implementation in (None, UnitImplementation.UNKNOWN_IMPLEMENTATION)
        # custom units resolve by name at engine build; their validity is a
        # deploy-time concern (componentSpecs must supply the container)
    )
    if unit.implementation in _SERVER_IMPLS and not unit.model_uri and unit.implementation != UnitImplementation.TENSORFLOW_SERVER:
        problems.append(f"{path}: {unit.implementation.value} requires modelUri")
    if not runnable:
        problems.append(f"{path}: unit {unit.name!r} is not resolvable")

    if unit.type == UnitType.ROUTER and len(unit.children) < 1:
        problems.append(f"{path}: ROUTER {unit.name!r} needs at least one child")
    if unit.type == UnitType.COMBINER and len(unit.children) < 1:
        problems.append(f"{path}: COMBINER {unit.name!r} needs at least one child")
    if unit.type in (UnitType.TRANSFORMER, UnitType.OUTPUT_TRANSFORMER) and len(unit.children) > 1:
        problems.append(
            f"{path}: {unit.type.value} {unit.name!r} must have at most one child (got {len(unit.children)})"
        )
    if unit.type == UnitType.MODEL and len(unit.children) > 1:
        problems.append(f"{path}: MODEL {unit.name!r} cannot fan out to {len(unit.children)} children")

    for c in unit.children:
        _validate_unit(c, f"{path}.{unit.name}", problems, seen)


def validate_deployment(sdep: SeldonDeploymentSpec) -> List[str]:
    problems: List[str] = []
    if not _NAME_RE.match(sdep.name or ""):
        problems.append(f"deployment name {sdep.name!r} is not a valid DNS label")
    if not sdep.predictors:
        problems.append("deployment has no predictors")

    names = set()
    total_traffic = 0
    any_traffic = False
    for p in sdep.predictors:
        path = f"predictor[{p.name}]"
        if not _NAME_RE.match(p.name or ""):
            problems.append(f"{path}: name is not a valid DNS label")
        if p.name in names:
            problems.append(f"{path}: duplicate predictor name")
        names.add(p.name)
        if p.replicas < 1:
            problems.append(f"{path}: replicas must be >= 1")
        if p.traffic:
            any_traffic = True
            if not 0 <= p.traffic <= 100:
                problems.append(f"{path}: traffic {p.traffic} outside [0, 100]")
        if not p.shadow:
            total_traffic += p.traffic
        if p.hpa_spec:
            mn = p.hpa_spec.get("minReplicas", 1)
            mx = p.hpa_spec.get("maxReplicas")
            if mx is None:
                problems.append(f"{path}: hpaSpec needs maxReplicas")
            elif mn > mx:
                problems.append(f"{path}: hpaSpec minReplicas {mn} > maxReplicas {mx}")
        # k8s Quantity grammar for every resources block the CR carries
        # (svcOrchSpec and componentSpecs containers — the surface the
        # reference's vendored QuantityUtils JSON parser accepted)
        if p.svc_orch_spec.get("resources"):
            validate_resources(p.svc_orch_spec["resources"], f"{path}.svcOrchSpec.resources", problems)
        for ci, cs in enumerate(p.component_specs):
            spec = cs.get("spec", cs)
            for cj, container in enumerate(spec.get("containers", []) or []):
                if container.get("resources"):
                    validate_resources(
                        container["resources"],
                        f"{path}.componentSpecs[{ci}].containers[{cj}].resources",
                        problems,
                    )
        _validate_unit(p.graph, path, problems, seen=set())

    if any_traffic and len([p for p in sdep.predictors if not p.shadow]) > 1 and total_traffic != 100:
        problems.append(f"traffic weights across predictors sum to {total_traffic}, expected 100")
    return problems


def require_valid(sdep: SeldonDeploymentSpec) -> SeldonDeploymentSpec:
    sdep = default_deployment(sdep)
    problems = validate_deployment(sdep)
    if problems:
        raise SeldonError("; ".join(problems), reason="BAD_GRAPH", status_code=400)
    return sdep
