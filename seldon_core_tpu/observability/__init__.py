"""Observability services: request logger (capability of the reference's
`seldon-request-logger/app/app.py`)."""
