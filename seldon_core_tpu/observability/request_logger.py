"""Request logger service.

Capability of the reference's `seldon-request-logger/app/app.py:15-60`: a
small HTTP service that receives the engine's CloudEvents-style
request/response pairs (`CE-Type: seldon.message.pair` headers —
`engine/.../PredictionService.java:162-191`) and flattens each batch element
into one JSON line on stdout for the fluentd/Elastic pipeline.

The engine side posts pairs when ``REQUEST_LOGGER_URL`` is set
(transport/rest.py), mirroring the reference's `log.messages.externally`.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

from aiohttp import web


def _rows(data: Optional[Dict[str, Any]]) -> List[Any]:
    """Per-element rows from a SeldonMessage dict: one row per batch entry of
    ndarray/tensor data, else the scalar payload."""
    if not data:
        return [None]
    d = data.get("data", {})
    if "ndarray" in d:
        arr = d["ndarray"]
        return list(arr) if isinstance(arr, list) else [arr]
    if "tensor" in d:
        shape = d["tensor"].get("shape", [])
        values = d["tensor"].get("values", [])
        if len(shape) == 2 and shape[0] * shape[1] == len(values):
            n = shape[0]
            w = shape[1]
            return [values[i * w : (i + 1) * w] for i in range(n)]
        return [values]
    for key in ("strData", "binData", "jsonData"):
        if key in data:
            return [data[key]]
    return [None]


def flatten_pair(body: Dict[str, Any], ce_headers: Dict[str, str]) -> List[Dict[str, Any]]:
    """One log record per request row, paired positionally with response rows
    (the reference's per-element flattening)."""
    request = body.get("request", {})
    response = body.get("response", {})
    puid = (
        request.get("meta", {}).get("puid")
        or response.get("meta", {}).get("puid")
        or ce_headers.get("ce-requestid", "")
    )
    req_rows = _rows(request)
    resp_rows = _rows(response)
    n = max(len(req_rows), len(resp_rows))
    out = []
    for i in range(n):
        out.append(
            {
                "request.id": puid,
                "request.elem": i,
                "request.data": req_rows[i] if i < len(req_rows) else None,
                "response.data": resp_rows[i] if i < len(resp_rows) else None,
                "ce-type": ce_headers.get("ce-type", ""),
                "ce-source": ce_headers.get("ce-source", ""),
                "sdep": ce_headers.get("ce-sdep", ""),
            }
        )
    return out


def make_logger_app(out=None) -> web.Application:
    out = out or sys.stdout
    app = web.Application(client_max_size=1 << 26)

    async def handle(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "bad json"}, status=400)
        ce = {k.lower(): v for k, v in request.headers.items() if k.lower().startswith("ce-")}
        for record in flatten_pair(body, ce):
            out.write(json.dumps(record) + "\n")
        out.flush()
        return web.json_response({"status": "ok"})

    async def health(request):
        return web.json_response({"status": "ok"})

    app.router.add_post("/", handle)
    app.router.add_post("/api/log", handle)
    app.router.add_get("/ready", health)
    app.router.add_get("/live", health)
    return app
