"""/debug/timeline: recent per-request flight-recorder timelines plus the
aggregated scaling-signal snapshot.

The REST route (transport/rest.py, on both the component and engine apps)
and its gRPC mirror (``Model/DebugTimeline``, transport/grpc_server.py)
both render through :func:`timeline_report`, so the two transports can
never drift. Schema: docs/observability.md "The /debug/timeline schema".

The scaling block is the per-request-derived half of what ROADMAP item 4
(elastic control plane) consumes: queue depth, slot occupancy, page
pressure and shed totals say how loaded the replica IS; the flight
recorder's TTFT / queue-wait / worst-gap quantiles say what that load is
DOING to requests — the pair a scale controller steers by.

Deliberately read-only and drain-free: unlike ``llm_stats`` (which drains
its observation deques into the /metrics histograms), everything here is a
snapshot — hitting /debug/timeline in a loop never starves the Prometheus
scrape.
"""

from __future__ import annotations

from typing import Any, Optional


def parse_n(raw: Any, default: int = 32) -> int:
    """The shared ``?n=`` / jsonData ``n`` parse for every timeline
    surface (REST component app, REST engine app, gRPC DebugTimeline):
    one clamp, one default — three hand-kept copies would drift."""
    try:
        return int(raw)
    except (TypeError, ValueError):
        return default


def _batcher(component: Any):
    svc = getattr(component, "_batcher_service", None)
    return None if svc is None else svc.batcher


def _recorder(component: Any, batcher: Any):
    fn = getattr(component, "flight_recorder", None)
    if fn is not None:
        return fn()
    return getattr(batcher, "_flight", None) if batcher is not None else None


def timeline_report(component: Any, n: int = 32) -> dict:
    """The /debug/timeline payload for one component. Components without a
    batcher (or with tracing disabled) report ``tracing: false`` with an
    empty timeline list — the endpoint never 500s on configuration."""
    from seldon_core_tpu.tracing import get_tracer

    batcher = _batcher(component)
    recorder = _recorder(component, batcher)
    out: dict = {
        "tracing": recorder is not None,
        "tracer_enabled": get_tracer().enabled,
        "timelines": [],
        "scaling": scaling_snapshot(component, batcher, recorder),
    }
    if recorder is not None:
        out["timelines"] = recorder.timelines(n)
    return out


def scaling_snapshot(component: Any, batcher: Any = None,
                     recorder: Optional[Any] = None) -> dict:
    """The aggregated scaling-signal snapshot (load state + request-latency
    quantiles). Safe on a bare component: absent layers report zeros."""
    if batcher is None:
        batcher = _batcher(component)
    if recorder is None:
        recorder = _recorder(component, batcher)
    snap: dict = {
        "active_slots": 0,
        "total_slots": 0,
        "queue_depth": 0,
        "steps_in_flight": 0,
        "page_pressure": 0.0,
        "page_sheds_total": 0,
        "handoff_queue_depth": 0,
        "draining": False,
        # fleet health (runtime/engine.py ReplicaSet): True when the fleet
        # quarantined this replica after an unplanned death — the
        # autoscaler reads it as a replace signal (docs/control-plane.md);
        # a solo component is never ejected
        "ejected": False,
        "prefill_devices": 0,
        "decode_devices": 0,
        # multi-tenant: queued admissions per SLO class (the weighted-fair
        # scheduler's split of queue_depth — runtime/scheduler.py)
        "queue_by_class": {},
    }
    if batcher is not None:
        snap["active_slots"] = sum(1 for s in batcher._slots if s.active)
        snap["total_slots"] = batcher.S
        sched = batcher._pending
        if hasattr(sched, "depths"):
            # ONE scheduler-lock read: queue_depth derives from the same
            # snapshot as its per-class split, so the two can never
            # disagree within one scaling snapshot
            by_class = sched.depths()
            snap["queue_by_class"] = by_class
            snap["queue_depth"] = sum(by_class.values())
        else:
            snap["queue_depth"] = len(sched)
        snap["steps_in_flight"] = len(batcher._inflight)
        snap["draining"] = bool(getattr(batcher, "draining", False))
        if getattr(batcher, "paged", False):
            pages = batcher.page_stats()
            total = max(pages["kv_pages_total"], 1)
            snap["page_pressure"] = pages["kv_pages_in_use"] / total
            snap["page_sheds_total"] = pages["kv_page_sheds"]
        if getattr(batcher, "_remote", None) is not None:
            snap["handoff_queue_depth"] = (
                batcher.handoff_stats()["handoff_queue_depth"])
            mesh = getattr(batcher, "disagg_mesh", None)
            if mesh is not None:
                # the prefill:decode split the autoscaler's rebalance
                # actuator steers (controlplane/autoscaler.py)
                snap["prefill_devices"] = len(mesh.prefill_devices)
                snap["decode_devices"] = len(mesh.decode_devices)
    if recorder is not None:
        snap["requests"] = recorder.snapshot()
    return snap


def retry_after_hint(component: Any, default_s: float = 1.0) -> float:
    """The transport-side dynamic ``Retry-After`` for shed responses
    (docs/resilience.md "Dynamic backoff"): components with a batcher
    delegate to its backlog-derived hint
    (``ContinuousBatcher.retry_after_hint`` — base x the full drain waves
    queued ahead, doubled near page exhaustion); everything else keeps
    the configured constant.  Wired into
    ``AdmissionController.retry_after_fn`` by the REST/gRPC apps, and
    called OUTSIDE any admission lock."""
    batcher = _batcher(component)
    hint = getattr(batcher, "retry_after_hint", None)
    if hint is None:
        return float(default_s)
    # ``default_s`` is the admission controller's CONFIGURED constant
    # (annotation/env): it stays the floor — the batcher hint (based on
    # its own shed_retry_after_s knob) may only raise backoff above it,
    # never silently undercut an operator's explicit setting
    return max(float(hint()), float(default_s))


def engine_retry_after_hint(engine: Any, default_s: float = 1.0) -> float:
    """The engine-edge variant: the WORST (largest) backlog-derived hint
    among the graph's in-process components, so a shed at the engine edge
    reflects the busiest batcher behind it."""
    comps = getattr(engine, "_components", {}) or {}
    return max((retry_after_hint(c, default_s) for c in comps.values()),
               default=float(default_s))


def wire_retry_after(admission: Any, component: Any = None,
                     engine: Any = None) -> Any:
    """THE one place dynamic shed backoff is wired (docs/resilience.md
    "Dynamic backoff"): installs ``retry_after_fn`` on an
    AdmissionController unless one is already set.  All four transport
    apps (REST/gRPC x component/engine) call this — hand-kept copies of
    the closure were exactly the drift :func:`parse_n` exists to
    prevent.  The fn runs outside the admission lock by the controller's
    contract."""
    if admission.retry_after_fn is not None:
        return admission
    if engine is not None:
        admission.retry_after_fn = (
            lambda: engine_retry_after_hint(engine, admission.retry_after_s))
    elif component is not None:
        admission.retry_after_fn = (
            lambda: retry_after_hint(component, admission.retry_after_s))
    return admission
