"""Prometheus rules + Grafana dashboard generated from the engine's actual
metric names (metrics/registry.py), so the artifacts can never drift from
the code. Parity: the reference's analytics chart
(`helm-charts/seldon-core-analytics/files/` — prometheus-config.yaml, alert
rules, and the predictions-analytics Grafana dashboard).

``seldon-core-tpu analytics --out deploy/analytics`` writes the rendered
files; the committed copies under deploy/analytics/ are that command's
output.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

# single source of truth: the names registered in metrics/registry.py
REQUESTS_TOTAL = "seldon_api_executor_server_requests_total"
REQUESTS_SECONDS = "seldon_api_executor_server_requests_seconds"
FEEDBACK_TOTAL = "seldon_api_model_feedback_total"
FEEDBACK_REWARD = "seldon_api_model_feedback_reward_total"
# request-timeline layer (tracing + flight recorder, PR 10)
TTFT_SECONDS = "seldon_llm_ttft_seconds"
INTER_TOKEN_SECONDS = "seldon_llm_inter_token_seconds"
TRACES_RETAINED = "seldon_llm_traces_retained_total"
TRACE_SPANS_DROPPED = "seldon_trace_spans_dropped_total"
TRACE_EXPORT_SECONDS = "seldon_trace_export_seconds"


def prometheus_scrape_config() -> Dict[str, Any]:
    """Scrape config keyed on the pod annotations the renderer emits
    (controlplane/render.py: prometheus.io/scrape|path|port)."""
    return {
        "global": {"scrape_interval": "15s"},
        "rule_files": ["rules/seldon-alerts.yaml"],
        "scrape_configs": [
            {
                "job_name": "seldon-engines",
                "kubernetes_sd_configs": [{"role": "pod"}],
                "relabel_configs": [
                    {
                        "source_labels": ["__meta_kubernetes_pod_annotation_prometheus_io_scrape"],
                        "action": "keep",
                        "regex": "true",
                    },
                    {
                        "source_labels": ["__meta_kubernetes_pod_annotation_prometheus_io_path"],
                        "action": "replace",
                        "target_label": "__metrics_path__",
                        "regex": "(.+)",
                    },
                    {
                        "source_labels": ["__address__",
                                          "__meta_kubernetes_pod_annotation_prometheus_io_port"],
                        "action": "replace",
                        "regex": r"([^:]+)(?::\d+)?;(\d+)",
                        "replacement": "$1:$2",
                        "target_label": "__address__",
                    },
                    {
                        "source_labels": ["__meta_kubernetes_pod_label_seldon_deployment_id"],
                        "action": "replace",
                        "target_label": "deployment",
                    },
                ],
            }
        ],
    }


def prometheus_alert_rules() -> Dict[str, Any]:
    """Serving alerts over the engine metrics (the reference ships infra
    CPU/mem/disk rules; these are the serving-level equivalents)."""
    err_ratio = (
        f'sum by (deployment_name) (rate({REQUESTS_TOTAL}{{code=~"5.."}}[5m]))'
        f" / sum by (deployment_name) (rate({REQUESTS_TOTAL}[5m]))"
    )
    p99 = (
        "histogram_quantile(0.99, sum by (deployment_name, le) "
        f"(rate({REQUESTS_SECONDS}_bucket[5m])))"
    )
    return {
        "groups": [
            {
                "name": "seldon-serving",
                "rules": [
                    {
                        "alert": "SeldonHighErrorRate",
                        "expr": f"({err_ratio}) > 0.05",
                        "for": "5m",
                        "labels": {"severity": "critical"},
                        "annotations": {
                            "summary": "{{ $labels.deployment_name }}: >5% of requests failing",
                        },
                    },
                    {
                        "alert": "SeldonHighLatencyP99",
                        "expr": f"({p99}) > 1",
                        "for": "10m",
                        "labels": {"severity": "warning"},
                        "annotations": {
                            "summary": "{{ $labels.deployment_name }}: p99 latency above 1s",
                        },
                    },
                    {
                        "alert": "SeldonNoTraffic",
                        "expr": f"sum by (deployment_name) (rate({REQUESTS_TOTAL}[15m])) == 0",
                        "for": "30m",
                        "labels": {"severity": "info"},
                        "annotations": {
                            "summary": "{{ $labels.deployment_name }}: no requests for 30m",
                        },
                    },
                    {
                        "alert": "SeldonEngineDown",
                        "expr": 'up{job="seldon-engines"} == 0',
                        "for": "2m",
                        "labels": {"severity": "critical"},
                        "annotations": {"summary": "engine target down"},
                    },
                ],
            }
        ]
    }


def _panel(panel_id: int, title: str, exprs: List[Dict[str, str]], y: int, x: int = 0,
           w: int = 12, h: int = 8, unit: str = "short") -> Dict[str, Any]:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": h, "w": w, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [
            {"expr": t["expr"], "legendFormat": t.get("legend", ""), "refId": chr(65 + i)}
            for i, t in enumerate(exprs)
        ],
    }


def predictions_dashboard() -> Dict[str, Any]:
    """The predictions-analytics dashboard over the real metric names."""
    sel = '{deployment_name=~"$deployment"}'
    sel_5xx = '{deployment_name=~"$deployment", code=~"5.."}'
    panels = [
        _panel(1, "Request rate", [
            {"expr": f"sum by (deployment_name, method) (rate({REQUESTS_TOTAL}{sel}[1m]))",
             "legend": "{{deployment_name}} {{method}}"},
        ], y=0, unit="reqps"),
        _panel(2, "Error rate (5xx)", [
            {"expr": f"sum by (deployment_name) (rate({REQUESTS_TOTAL}{sel_5xx}[1m]))",
             "legend": "{{deployment_name}}"},
        ], y=0, x=12, unit="reqps"),
        _panel(3, "Latency percentiles", [
            {"expr": "histogram_quantile(0.5, sum by (le) "
                     f"(rate({REQUESTS_SECONDS}_bucket{sel}[5m])))", "legend": "p50"},
            {"expr": "histogram_quantile(0.9, sum by (le) "
                     f"(rate({REQUESTS_SECONDS}_bucket{sel}[5m])))", "legend": "p90"},
            {"expr": "histogram_quantile(0.99, sum by (le) "
                     f"(rate({REQUESTS_SECONDS}_bucket{sel}[5m])))", "legend": "p99"},
        ], y=8, unit="s"),
        _panel(4, "Mean latency", [
            {"expr": f"sum by (deployment_name) (rate({REQUESTS_SECONDS}_sum{sel}[5m]))"
                     f" / sum by (deployment_name) (rate({REQUESTS_SECONDS}_count{sel}[5m]))",
             "legend": "{{deployment_name}}"},
        ], y=8, x=12, unit="s"),
        _panel(5, "Feedback events", [
            {"expr": f"sum by (deployment_name) (rate({FEEDBACK_TOTAL}{sel}[5m]))",
             "legend": "{{deployment_name}}"},
        ], y=16),
        _panel(6, "Cumulative reward", [
            {"expr": f"sum by (deployment_name) ({FEEDBACK_REWARD}{sel})",
             "legend": "{{deployment_name}}"},
        ], y=16, x=12),
        # Request timeline (PR 10): the aggregate view of what the
        # flight-recorder timelines show per request — TTFT vs worst-gap
        # percentiles are the pair tail sampling keys on, and the
        # retained/dropped counters say whether the trace pipeline itself
        # is healthy (an exporter outage shows up HERE, not as silence)
        _panel(7, "Serving timeline: TTFT / inter-token gap", [
            {"expr": "histogram_quantile(0.5, sum by (le) "
                     f"(rate({TTFT_SECONDS}_bucket{sel}[5m])))",
             "legend": "TTFT p50"},
            {"expr": "histogram_quantile(0.99, sum by (le) "
                     f"(rate({TTFT_SECONDS}_bucket{sel}[5m])))",
             "legend": "TTFT p99"},
            {"expr": "histogram_quantile(0.99, sum by (le) "
                     f"(rate({INTER_TOKEN_SECONDS}_bucket{sel}[5m])))",
             "legend": "inter-token p99"},
        ], y=24, unit="s"),
        _panel(8, "Traces retained / spans dropped", [
            {"expr": f"sum by (deployment_name, mode) (rate({TRACES_RETAINED}{sel}[5m]))",
             "legend": "retained {{mode}}"},
            {"expr": f"sum by (deployment_name) (rate({TRACE_SPANS_DROPPED}{sel}[5m]))",
             "legend": "spans dropped"},
            {"expr": "histogram_quantile(0.95, sum by (le) "
                     f"(rate({TRACE_EXPORT_SECONDS}_bucket{sel}[5m])))",
             "legend": "export p95 (s)"},
        ], y=24, x=12),
    ]
    return {
        "title": "Seldon TPU — Predictions Analytics",
        "uid": "seldon-tpu-predictions",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-1h", "to": "now"},
        "templating": {
            "list": [
                {"name": "datasource", "type": "datasource", "query": "prometheus"},
                {
                    "name": "deployment",
                    "type": "query",
                    "datasource": {"type": "prometheus", "uid": "${datasource}"},
                    "query": f"label_values({REQUESTS_TOTAL}, deployment_name)",
                    "includeAll": True,
                    "multi": True,
                },
            ]
        },
        "panels": panels,
    }


def write_artifacts(out_dir: str) -> List[str]:
    import os

    import yaml

    os.makedirs(os.path.join(out_dir, "rules"), exist_ok=True)
    written = []

    def dump_yaml(rel: str, obj: Any) -> None:
        path = os.path.join(out_dir, rel)
        with open(path, "w") as f:
            yaml.safe_dump(obj, f, sort_keys=False)
        written.append(path)

    dump_yaml("prometheus-config.yaml", prometheus_scrape_config())
    dump_yaml(os.path.join("rules", "seldon-alerts.yaml"), prometheus_alert_rules())
    dash = os.path.join(out_dir, "predictions-dashboard.json")
    with open(dash, "w") as f:
        json.dump(predictions_dashboard(), f, indent=2)
    written.append(dash)
    return written
