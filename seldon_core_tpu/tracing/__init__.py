"""Distributed tracing.

The reference uses Jaeger/OpenTracing end-to-end, enabled by env TRACING=1
(`engine/.../tracing/TracingProvider.java:25-52`, `python/seldon_core/
microservice.py:116-151`). The opentelemetry SDK is not installed in this
image, so this module ships a small native tracer with the same span topology
(server span -> per-node child spans) and W3C traceparent propagation;
``export`` hooks let deployments forward finished spans to a collector.

Request-scoped serving timelines (the batcher flight recorder,
runtime/flight.py) materialize into the same span model: one tree per
request, rooted at the transport ingress, fed through this tracer's buffer
to the OTLP exporter. Sampling is two-stage: the W3C ``sampled`` flag from
the inbound ``traceparent`` is the head decision, and the flight recorder
may still RETAIN an unsampled request whose TTFT or worst inter-token gap
exceeds the tail thresholds (``TRACING_TAIL_TTFT_MS`` /
``TRACING_TAIL_GAP_MS``) — the slow outliers are exactly the traces an
operator needs, and head sampling is blind to latency by construction.

Clock discipline: span timestamps come from :func:`now` — a monotonic clock
anchored to the wall clock once at module import (re-anchor explicitly via
:func:`anchor`, only while quiescent). ``time.time()`` at both ends of a span made
durations wrong, possibly negative, whenever NTP stepped the wall clock
mid-span; the anchored clock keeps durations exact under any wall step and
only ever pays the anchor's one-time offset in absolute timestamps.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("seldon.tracing")

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "seldon_current_span", default=None
)

# ---------------------------------------------------------------------------
# Anchored monotonic clock
# ---------------------------------------------------------------------------

_mono = time.monotonic
_wall_anchor = time.time()
_mono_anchor = time.monotonic()


def anchor(wall=time.time, mono=time.monotonic) -> None:
    """(Re-)anchor the span clock: absolute time = wall-at-anchor plus
    monotonic elapsed since the anchor. Called once at module import; only
    re-anchor while no spans are open (a shift mid-span would move that
    span's duration by the drift). Tests inject fake ``wall``/``mono``
    sources (e.g. a FaultClock) to step the clocks deterministically."""
    global _mono, _wall_anchor, _mono_anchor
    _mono = mono
    _wall_anchor = wall()
    _mono_anchor = mono()


def now() -> float:
    """Wall-anchored monotonic seconds — the span timestamp source. A wall
    clock step between a span's start and finish cannot change its
    duration (the delta is purely monotonic)."""
    return _wall_anchor + (_mono() - _mono_anchor)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float = field(default_factory=now)
    end: Optional[float] = None
    tags: Dict[str, Any] = field(default_factory=dict)
    # W3C sampled flag: unsampled spans propagate context but are never
    # buffered/exported (unless flight-recorder tail sampling retains the
    # whole request tree — runtime/flight.py)
    sampled: bool = True
    # set by Tracer.flush when an export failure re-enqueued this span once
    # already; a second failure drops it (bounded retry, never a loop)
    requeued: bool = False

    def finish(self) -> None:
        self.end = now()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "startUs": int(self.start * 1e6),
            "durationUs": int(((self.end or now()) - self.start) * 1e6),
            "tags": self.tags,
        }

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"


@dataclass
class TraceContext:
    """A request's trace identity, carried from the transport ingress into
    the batcher (and onward to prefill workers): what the flight recorder
    needs to root one span tree per request. ``parent_span_id`` is the
    remote caller's span when the request arrived with a ``traceparent``
    header; ``sampled`` is the head-sampling decision that tail sampling
    may override."""

    trace_id: str
    parent_span_id: Optional[str] = None
    sampled: bool = True
    ingress: str = ""

    @classmethod
    def from_traceparent(cls, header: Optional[str],
                         ingress: str = "") -> "TraceContext":
        """Context from an inbound W3C traceparent header; absent or
        malformed headers start a fresh (sampled) trace."""
        parsed = _parse_traceparent(header) if header else None
        if parsed is None:
            return cls(trace_id=secrets.token_hex(16), parent_span_id=None,
                       sampled=True, ingress=ingress)
        trace_id, span_id, sampled = parsed
        return cls(trace_id=trace_id, parent_span_id=span_id,
                   sampled=sampled, ingress=ingress)


def current_traceparent() -> Optional[str]:
    """The active span's outbound traceparent header value (None outside
    any span) — what remote hops attach so downstream services join this
    trace."""
    s = _current_span.get()
    return s.traceparent() if s is not None else None


def ingress_trace(tracer: "Tracer", header: Optional[str],
                  ingress: str) -> Optional[TraceContext]:
    """The transports' ONE trace-setup path (REST /v1/generate and gRPC
    GenerateStream both call this): None when tracing is off, else a
    context from the inbound W3C header rooted at this ingress. Shared so
    the enablement gate and header handling cannot drift between the
    mirrored transports."""
    if not tracer.enabled:
        return None
    return TraceContext.from_traceparent(header, ingress=ingress)


def current_trace_context(ingress: str = "") -> Optional[TraceContext]:
    """A TraceContext hanging under the ACTIVE span (None outside any
    span): how interior layers (engine dispatch) hand the transport's
    server span down into the batcher's flight recorder, so the request's
    timeline joins the same trace as the node spans instead of starting a
    fresh 'internal' one. ``ingress`` defaults to the active span's NAME —
    the same request can arrive as 'predict', 'grpc:predict' or
    'predictions', and a hardcoded label would point the operator at a
    transport hop that does not exist."""
    s = _current_span.get()
    if s is None:
        return None
    return TraceContext(trace_id=s.trace_id, parent_span_id=s.span_id,
                        sampled=s.sampled, ingress=ingress or s.name)


class Tracer:
    def __init__(self, service_name: str = "seldon-tpu", enabled: bool = False, max_buffer: int = 4096):
        self.service_name = service_name
        self.enabled = enabled
        self._buffer: List[Span] = []
        self._lock = threading.Lock()
        self._max_buffer = max_buffer
        self.exporter = None  # callable(List[Span]) or None
        # export observability (metrics/registry.py sync_tracing drains
        # these at /metrics scrape time): spans dropped by export failures
        # (a batch is re-enqueued ONCE; the second failure drops it),
        # per-flush export latency, and flight-recorder retention counts
        # by sampling mode
        self.spans_dropped_total = 0
        from collections import deque

        self._export_times: Any = deque(maxlen=512)
        self.retained_total: Dict[str, int] = {"head": 0, "tail": 0}
        # NOTE: deliberately no anchor() here. The span clock anchors once
        # at module import; re-anchoring from an instance constructor would
        # shift the duration of every span OPEN across the construction by
        # the accumulated wall-vs-monotonic drift — reintroducing the
        # clock-step bug the anchored clock exists to fix. Deployments that
        # fix NTP late call tracing.anchor() explicitly, while quiescent.

    @contextlib.contextmanager
    def span(self, name: str, traceparent: Optional[str] = None, **tags: Any):
        if not self.enabled:
            yield None
            return
        parent = _current_span.get()
        sampled = True
        if traceparent and parent is None:
            parsed = _parse_traceparent(traceparent)
            if parsed is None:
                trace_id, parent_id = secrets.token_hex(16), None
            else:
                trace_id, parent_id, sampled = parsed
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
            sampled = parent.sampled
        else:
            trace_id, parent_id = secrets.token_hex(16), None
        s = Span(name=name, trace_id=trace_id, span_id=secrets.token_hex(8),
                 parent_id=parent_id, tags=dict(tags), sampled=sampled)
        token = _current_span.set(s)
        try:
            yield s
        finally:
            s.finish()
            _current_span.reset(token)
            self._record(s)

    def _append(self, spans: List[Span]) -> None:
        """Shared buffering that NEVER does network I/O on the recording
        thread: with an exporter installed, the background PeriodicFlusher
        owns the (possibly blocking) HTTP flush — an inline flush would
        park the batcher loop / a transport handler behind a 5s connect
        timeout (the exact stall class the flight recorder exists to
        diagnose), so this path only buffers, dropping-and-counting
        whatever a full buffer cannot hold. Without an exporter, flush is
        local (TRACING_LOG or discard) and stays inline so log mode keeps
        emitting."""
        flush_now = False
        with self._lock:
            if self.exporter is not None:
                # NEVER flush from here when an exporter is installed —
                # not even when this very append crosses the threshold:
                # the recording thread is the batcher loop / a transport
                # handler, and exporter() blocks on the network. Buffer
                # what fits, drop-and-count the rest; the PeriodicFlusher
                # drains on its own thread.
                space = self._max_buffer - len(self._buffer)
                kept = spans[:space] if space > 0 else []
                self._buffer.extend(kept)
                self.spans_dropped_total += len(spans) - len(kept)
                return
            self._buffer.extend(spans)
            flush_now = len(self._buffer) >= self._max_buffer
        if flush_now:  # outside the lock: flush() re-acquires it
            self.flush()

    def _record(self, s: Span) -> None:
        if not s.sampled:
            # head-sampling: unsampled spans propagate context only (the
            # flight recorder's tail path records its trees via
            # record_spans with sampled flipped on retention)
            return
        self._append([s])

    def record_spans(self, spans: List[Span]) -> None:
        """Batch-append finished spans (the flight recorder's materialized
        request trees). The caller already decided retention — sampled
        flags are taken as-is."""
        if not self.enabled or not spans:
            return
        self._append(list(spans))

    def count_retained(self, mode: str) -> None:
        """One request trace retained, by sampling mode ('head' = the W3C
        flag said keep; 'tail' = retained past an unsampled flag because
        TTFT / worst-gap crossed the tail thresholds)."""
        with self._lock:
            self.retained_total[mode] = self.retained_total.get(mode, 0) + 1

    def flush(self) -> None:
        with self._lock:
            spans, self._buffer = self._buffer, []
        if not spans:
            return
        if self.exporter is not None:
            t0 = time.perf_counter()
            try:
                self.exporter(spans)
            except Exception:
                logger.exception("trace export failed")
                # bounded re-enqueue: a transient collector blip must not
                # lose a whole flush window, but a dead collector must not
                # grow the buffer forever — each span gets ONE retry, and
                # re-enqueueing never pushes the buffer past max_buffer
                retry = [s for s in spans if not s.requeued]
                dropped = len(spans) - len(retry)
                for s in retry:
                    s.requeued = True
                with self._lock:
                    space = max(self._max_buffer - len(self._buffer), 0)
                    kept, overflow = retry[:space], retry[space:]
                    # front of the buffer: re-enqueued spans keep arrival
                    # order ahead of spans recorded since
                    self._buffer[:0] = kept
                    self.spans_dropped_total += dropped + len(overflow)
            finally:
                with self._lock:
                    self._export_times.append(time.perf_counter() - t0)
        elif os.environ.get("TRACING_LOG", ""):
            for s in spans:
                logger.info("span %s", json.dumps(s.to_dict()))

    def export_stats(self) -> Dict[str, Any]:
        """Drain-and-snapshot for MetricsRegistry.sync_tracing: per-flush
        export latencies observed since the last scrape (drained — each is
        recorded into the histogram exactly once) plus the lifetime
        dropped/retained tallies (counter catch-up idiom)."""
        with self._lock:
            times = list(self._export_times)
            self._export_times.clear()
            return {
                "export_times_s": times,
                "spans_dropped_total": self.spans_dropped_total,
                "retained_total": dict(self.retained_total),
            }

    def drain(self) -> List[Span]:
        with self._lock:
            spans, self._buffer = self._buffer, []
        return spans


def _parse_traceparent(header: str) -> Optional[Tuple[str, str, bool]]:
    """Strict W3C traceparent parse: ``version-traceid-spanid-flags`` with
    2/32/16/2 lowercase-hex fields, version != 'ff', ids not all-zero.
    Returns (trace_id, span_id, sampled) or None — malformed headers start
    a FRESH trace at the caller instead of silently adopting garbage ids
    (which would stitch unrelated requests into one trace)."""
    if not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    # future versions may append fields (the first four keep their
    # meaning); version 00 is REQUIRED to have exactly four
    if len(parts) < 4:
        return None
    if parts[0] == "00" and len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    # charset check, not int(x, 16): int() tolerates '+'/'-' signs and
    # whitespace, which would adopt (and re-emit downstream) ids that
    # spec-compliant parsers reject — severing the trace at the next hop
    hexdigits = set("0123456789abcdefABCDEF")
    if not all(set(field) <= hexdigits
               for field in (version, trace_id, span_id, flags)):
        return None
    flag_bits = int(flags, 16)
    if version.lower() == "ff":
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id.lower(), span_id.lower(), bool(flag_bits & 0x01)


# Tail-sampling thresholds (seconds; None = that signal never tail-retains).
# Read once per recorder from the environment: requests whose TTFT or worst
# inter-token gap exceeds a threshold are retained even when head sampling
# (the inbound traceparent's flag) said drop — docs/observability.md.
def tail_thresholds(env: Optional[dict] = None) -> Tuple[Optional[float], Optional[float]]:
    env = env if env is not None else os.environ

    def ms(key: str) -> Optional[float]:
        raw = env.get(key, "")
        if not raw:
            return None
        try:
            v = float(raw)
        except (TypeError, ValueError):
            return None
        return v / 1000.0 if v >= 0 else None

    return ms("TRACING_TAIL_TTFT_MS"), ms("TRACING_TAIL_GAP_MS")


_tracer: Optional[Tracer] = None


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        _tracer = Tracer(
            service_name=os.environ.get("JAEGER_SERVICE_NAME", "seldon-tpu"),
            enabled=os.environ.get("TRACING", "0") == "1",
        )
        from seldon_core_tpu.tracing import export as _export

        flusher = _export.install_from_env(_tracer)
        if flusher is not None:
            import atexit

            # final flush at shutdown: the drain-window spans are exactly the
            # ones an operator debugging a rollout needs
            atexit.register(flusher.stop)
    return _tracer


def set_tracer(tracer: Tracer) -> None:
    global _tracer
    _tracer = tracer
