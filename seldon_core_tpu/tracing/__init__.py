"""Distributed tracing.

The reference uses Jaeger/OpenTracing end-to-end, enabled by env TRACING=1
(`engine/.../tracing/TracingProvider.java:25-52`, `python/seldon_core/
microservice.py:116-151`). The opentelemetry SDK is not installed in this
image, so this module ships a small native tracer with the same span topology
(server span -> per-node child spans) and W3C traceparent propagation;
``export`` hooks let deployments forward finished spans to a collector.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger("seldon.tracing")

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "seldon_current_span", default=None
)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float = field(default_factory=time.time)
    end: Optional[float] = None
    tags: Dict[str, Any] = field(default_factory=dict)

    def finish(self) -> None:
        self.end = time.time()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "startUs": int(self.start * 1e6),
            "durationUs": int(((self.end or time.time()) - self.start) * 1e6),
            "tags": self.tags,
        }

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


class Tracer:
    def __init__(self, service_name: str = "seldon-tpu", enabled: bool = False, max_buffer: int = 4096):
        self.service_name = service_name
        self.enabled = enabled
        self._buffer: List[Span] = []
        self._lock = threading.Lock()
        self._max_buffer = max_buffer
        self.exporter = None  # callable(List[Span]) or None

    @contextlib.contextmanager
    def span(self, name: str, traceparent: Optional[str] = None, **tags: Any):
        if not self.enabled:
            yield None
            return
        parent = _current_span.get()
        if traceparent and parent is None:
            trace_id, parent_id = _parse_traceparent(traceparent)
        elif parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = secrets.token_hex(16), None
        s = Span(name=name, trace_id=trace_id, span_id=secrets.token_hex(8), parent_id=parent_id, tags=dict(tags))
        token = _current_span.set(s)
        try:
            yield s
        finally:
            s.finish()
            _current_span.reset(token)
            self._record(s)

    def _record(self, s: Span) -> None:
        flush_now = False
        with self._lock:
            self._buffer.append(s)
            flush_now = len(self._buffer) >= self._max_buffer
        if flush_now:  # outside the lock: flush() re-acquires it
            self.flush()

    def flush(self) -> None:
        with self._lock:
            spans, self._buffer = self._buffer, []
        if not spans:
            return
        if self.exporter is not None:
            try:
                self.exporter(spans)
            except Exception:
                logger.exception("trace export failed")
        elif os.environ.get("TRACING_LOG", ""):
            for s in spans:
                logger.info("span %s", json.dumps(s.to_dict()))

    def drain(self) -> List[Span]:
        with self._lock:
            spans, self._buffer = self._buffer, []
        return spans


def _parse_traceparent(header: str):
    try:
        parts = header.split("-")
        return parts[1], parts[2]
    except (IndexError, AttributeError):
        return secrets.token_hex(16), None


_tracer: Optional[Tracer] = None


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        _tracer = Tracer(
            service_name=os.environ.get("JAEGER_SERVICE_NAME", "seldon-tpu"),
            enabled=os.environ.get("TRACING", "0") == "1",
        )
        from seldon_core_tpu.tracing import export as _export

        flusher = _export.install_from_env(_tracer)
        if flusher is not None:
            import atexit

            # final flush at shutdown: the drain-window spans are exactly the
            # ones an operator debugging a rollout needs
            atexit.register(flusher.stop)
    return _tracer


def set_tracer(tracer: Tracer) -> None:
    global _tracer
    _tracer = tracer
