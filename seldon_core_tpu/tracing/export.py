"""Span exporters: ship the native tracer's buffer to a collector.

The reference exports spans to Jaeger via the opentracing client
(`engine/.../tracing/TracingProvider.java:25-52`). The opentelemetry SDK is
not in this image, so the OTLP/HTTP JSON envelope is built by hand — Jaeger
(and every OTel collector) accepts it natively on ``/v1/traces`` (port 4318).

Wiring: ``TRACING=1`` + ``OTEL_EXPORTER_OTLP_ENDPOINT=http://host:4318``
(the standard OTel env var; ``TRACING_OTLP_ENDPOINT`` also accepted) installs
the exporter on the global tracer with a background flush loop.

Failure accounting lives in ``Tracer.flush`` (tracing/__init__.py): a
failed export re-enqueues the batch exactly once (a transient collector
blip loses nothing), a second failure drops it into
``seldon_trace_spans_dropped_total``, and every flush's latency lands in
``seldon_trace_export_seconds`` — an exporter outage is a counter on the
dashboard, never silence (docs/observability.md).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import List, Optional

from seldon_core_tpu.tracing import Span, Tracer

logger = logging.getLogger("seldon.tracing.export")


def spans_to_otlp(spans: List[Span], service_name: str) -> dict:
    """Native spans -> OTLP/HTTP JSON (trace service request envelope)."""

    def attr(key: str, value) -> dict:
        if isinstance(value, bool):
            return {"key": key, "value": {"boolValue": value}}
        if isinstance(value, int):
            return {"key": key, "value": {"intValue": str(value)}}
        if isinstance(value, float):
            return {"key": key, "value": {"doubleValue": value}}
        return {"key": key, "value": {"stringValue": str(value)}}

    otlp_spans = []
    for s in spans:
        start_ns = int(s.start * 1e9)
        end_ns = max(int((s.end if s.end is not None else s.start) * 1e9), start_ns)
        span = {
            "traceId": s.trace_id,
            "spanId": s.span_id,
            "name": s.name,
            "kind": 2,  # SPAN_KIND_SERVER: request-scoped spans
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [attr(k, v) for k, v in s.tags.items()],
        }
        if s.parent_id:
            span["parentSpanId"] = s.parent_id
        otlp_spans.append(span)

    return {
        "resourceSpans": [
            {
                "resource": {"attributes": [attr("service.name", service_name)]},
                "scopeSpans": [
                    {"scope": {"name": "seldon-core-tpu"}, "spans": otlp_spans}
                ],
            }
        ]
    }


class OTLPExporter:
    """callable(List[Span]) for Tracer.exporter: POST OTLP JSON over HTTP."""

    def __init__(self, endpoint: str, service_name: str = "seldon-tpu", timeout_s: float = 5.0):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.timeout_s = timeout_s

    def __call__(self, spans: List[Span]) -> None:
        body = json.dumps(spans_to_otlp(spans, self.service_name)).encode()
        req = urllib.request.Request(
            self.url, data=body, headers={"Content-Type": "application/json"}, method="POST"
        )
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            if resp.status >= 300:
                raise RuntimeError(f"OTLP export HTTP {resp.status}")


class PeriodicFlusher:
    """Background thread flushing the tracer buffer every ``interval_s``."""

    def __init__(self, tracer: Tracer, interval_s: float = 5.0):
        self.tracer = tracer
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PeriodicFlusher":
        self._thread = threading.Thread(target=self._run, daemon=True, name="seldon-trace-flush")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tracer.flush()
        self.tracer.flush()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self.interval_s + 1)


def install_from_env(tracer: Tracer, env: Optional[dict] = None) -> Optional[PeriodicFlusher]:
    """If an OTLP endpoint is configured, attach an exporter + flusher."""
    import os

    env = env if env is not None else dict(os.environ)
    endpoint = env.get("OTEL_EXPORTER_OTLP_ENDPOINT") or env.get("TRACING_OTLP_ENDPOINT")
    if not endpoint or not tracer.enabled:
        return None
    tracer.exporter = OTLPExporter(endpoint, service_name=tracer.service_name)
    logger.info("OTLP trace export -> %s", tracer.exporter.url)
    return PeriodicFlusher(tracer).start()
