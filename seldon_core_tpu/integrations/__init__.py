"""External inference-server integrations.

The reference delegates native-performance serving to external engines
behind thin proxies (`integrations/{tfserving,nvidia-inference-server,
sagemaker}`). Here the native path is in-process (servers/jaxserver.py), so
this package holds only the genuinely-external integrations: the TF-Serving
proxy lives in servers/tfproxy.py (selected by TENSORFLOW_SERVER), and the
SageMaker proxy below.
"""

from seldon_core_tpu.integrations.sagemaker import SageMakerProxy

__all__ = ["SageMakerProxy"]
