"""SageMaker serving proxy.

Parity with `integrations/sagemaker/SagemakerProxy.py:33` in the reference:
a MODEL component that forwards the feature batch to a SageMaker container's
``/invocations`` endpoint and returns the decoded result — so a SageMaker-
hosted model slots into an inference graph like any other unit. The
reference depends on the ``sagemaker_containers`` codec package; this
implementation speaks the same wire contract (JSON in, JSON or CSV out)
with no extra dependency.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Sequence

import numpy as np

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.payload import SeldonError


class SageMakerProxy(SeldonComponent):
    def __init__(self, endpoint: str = "", timeout_s: float = 10.0, **kwargs: Any):
        super().__init__(**kwargs)
        if not endpoint:
            raise SeldonError("SageMakerProxy needs endpoint=<container url>", status_code=500)
        self.endpoint = endpoint.rstrip("/")
        self.timeout_s = float(timeout_s)
        self._session = None  # pooled connections; rebuilt after unpickling

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_session"] = None
        return state

    def _http(self):
        if self._session is None:
            import requests

            self._session = requests.Session()
        return self._session

    def predict(self, X, names: Sequence[str], meta: Optional[Dict] = None) -> np.ndarray:
        X = np.asarray(X)
        r = self._http().post(
            self.endpoint + "/invocations",
            json=X.tolist(),
            timeout=self.timeout_s,
        )
        if r.status_code != 200:
            raise SeldonError(
                f"SageMaker endpoint error {r.status_code}: {r.text[:200]}",
                reason="MICROSERVICE_BAD_RESPONSE",
                status_code=502,
            )
        content_type = r.headers.get("content-type", "application/json")
        if "csv" in content_type:
            rows = [
                [float(v) for v in line.split(",")]
                for line in r.text.strip().splitlines()
                if line
            ]
            result = np.asarray(rows)
        else:
            result = np.asarray(json.loads(r.content))
        # a flat list of one prediction per input row must stay row-aligned,
        # not transpose into a single (1, N) row
        if result.ndim == 1 and X.ndim >= 2 and len(result) == X.shape[0] > 1:
            return result[:, None]
        return np.atleast_2d(result)
