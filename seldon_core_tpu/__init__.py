"""seldon_core_tpu — a TPU-native model-serving framework.

Capability surface mirrors Seldon Core (reference: /root/reference, v0.4.0 era):
declarative inference graphs (MODEL / ROUTER / COMBINER / TRANSFORMER /
OUTPUT_TRANSFORMER nodes), a single component contract for heterogeneous model
runtimes, REST + gRPC transports sharing one payload schema, in-band custom
metrics, tracing, feedback-driven routing (A/B, bandits), prepackaged model
servers, cloud-storage model fetching and a load-testing harness.

Architecture differs deliberately: where the reference orchestrates one
microservice per graph node over HTTP/gRPC (engine/src/main/java/io/seldon/
engine/predictors/PredictiveUnitBean.java:113-193 — a network hop + JSON<->proto
codec per node), this framework executes the whole predictor graph in one
process per replica. Graph nodes are composable JAX/XLA-compiled functions,
request tensors are staged as device buffers at ingress, and large models shard
over a TPU slice via jax.sharding meshes (ICI/DCN collectives) instead of
service replicas.
"""

from seldon_core_tpu.version import __version__

__all__ = ["__version__"]
