from seldon_core_tpu.models.registry import get_model, register_model

__all__ = ["get_model", "register_model"]
