"""Dedicated ResNet serving forward over folded-BN params.

`resnet_serve_forward` is a pure function over the param dict produced by
``fold_batchnorm`` (models/resnet.py) — no flax module tracing on the hot
path — with an optional Pallas tier: consecutive *identity* bottleneck
blocks (the 12 of 16 blocks in ResNet-50 with no projection/stride) run as
single fused kernels (`ops/fused_resnet.fused_identity_chain`), one HBM
read + one write per chain instead of XLA's per-op elementwise round trips
(`benchmarks/profile_summary.json` attributes ~79% of device time there).

Numerics match the ``fused=True`` flax module: bf16 conv compute, bf16 bias
adds, f32 head. Parity-tested against ``model.apply`` in
tests/test_fused_resnet.py.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from seldon_core_tpu.ops.fused_resnet import (
    _is_identity_block,
    folded_block_params,
    fused_identity_chain,
)

# Preferred images-per-program by spatial size: keeps the fused kernel's
# matmul M dimension MXU-sized as the activations shrink, while the
# per-program VMEM footprint stays ~1.6 MB (56x56x256 ~= 2x 28x28x512 ...).
_PREFERRED_GROUP = {56: 1, 28: 2, 14: 4, 7: 8}


def _largest_group(batch: int, preferred: int) -> int:
    g = min(preferred, batch)
    while batch % g:
        g -= 1
    return g


def _conv(x, kernel, bias, strides=(1, 1), padding=((0, 0), (0, 0))):
    dtype = x.dtype
    y = jax.lax.conv_general_dilated(
        x,
        kernel.astype(dtype),
        strides,
        padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + bias.astype(dtype)


def _bottleneck(x, scope, strides):
    y = jnp.maximum(_conv(x, scope["Conv_0"]["kernel"], scope["Conv_0"]["bias"]), 0)
    y = jnp.maximum(
        _conv(y, scope["Conv_1"]["kernel"], scope["Conv_1"]["bias"], strides,
              ((1, 1), (1, 1))),
        0,
    )
    y = _conv(y, scope["Conv_2"]["kernel"], scope["Conv_2"]["bias"])
    residual = x
    if "conv_proj" in scope:
        residual = _conv(x, scope["conv_proj"]["kernel"], scope["conv_proj"]["bias"],
                         strides)
    return jnp.maximum(residual + y, 0)


def resnet_serve_forward(
    variables: dict,
    x: jax.Array,
    *,
    stage_sizes: Sequence[int] = (3, 4, 6, 3),
    dtype=jnp.bfloat16,
    pallas_stages: Sequence[int] = (),
    interpret: bool = False,
) -> jax.Array:
    """Forward pass over ``fold_batchnorm`` params (ResNet-50 default).

    pallas_stages: stage indices (0-based) whose identity blocks run as
    fused Pallas chains; () reproduces the pure-XLA folded graph.
    """
    params = variables["params"]
    x = x.astype(dtype)
    x = _conv(x, params["conv_init"]["kernel"], params["conv_init"]["bias"],
              (2, 2), ((3, 3), (3, 3)))
    x = jnp.maximum(x, 0)
    x = jax.lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(dtype, jnp.floating) else 0,
        jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        ((0, 0), (1, 1), (1, 1), (0, 0)),
    )

    block_idx = 0
    for i, n_blocks in enumerate(stage_sizes):
        scopes = [params[f"BottleneckBlock_{block_idx + j}"] for j in range(n_blocks)]
        block_idx += n_blocks
        # Opening block always projects (channel widening; stride 2 for i>0).
        x = _bottleneck(x, scopes[0], (2, 2) if i > 0 else (1, 1))
        identity = scopes[1:]
        if i in pallas_stages and identity:
            if not all(_is_identity_block(s) for s in identity):
                raise ValueError(
                    f"stage {i}: pallas_stages requires projection-free "
                    "non-opening blocks; a conv_proj would be silently "
                    "dropped by the fused kernel"
                )
            group = _largest_group(x.shape[0], _PREFERRED_GROUP.get(x.shape[1], 1))
            x = fused_identity_chain(
                x, [folded_block_params(s) for s in identity], group=group,
                interpret=interpret,
            )
        else:
            for scope in identity:
                x = _bottleneck(x, scope, (1, 1))

    x = jnp.mean(x, axis=(1, 2))
    head = params["head"]
    return x.astype(jnp.float32) @ head["kernel"].astype(jnp.float32) + head[
        "bias"
    ].astype(jnp.float32)


__all__ = ["resnet_serve_forward"]
