"""Decoder-only transformer (Llama-family) in Flax, sharding-aware.

Serves the BASELINE.json stretch config (Llama-2-7B on a v5e-8 pod). Written
TPU-first:

- all weights carry flax *logical* partitioning names; the parallel module
  maps them onto a device mesh (tp over 'model', dp over 'data', sequence
  parallel over 'seq') — XLA/GSPMD inserts the collectives over ICI.
- GQA attention, rotary embeddings, RMSNorm, SwiGLU — bfloat16 on the MXU.
- decode path uses a static-shape KV cache (scatter at position index), so
  jit compiles one program per bucketed cache length.
- optional mixture-of-experts FFN (expert-parallel 'expert' axis) for EP.

No reference counterpart: the reference (a serving platform) has no model code
at all; this is the native model family the TPU build adds (SURVEY.md §5
"Long-context / sequence parallelism: absent — design from scratch").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

from seldon_core_tpu.models.registry import register_model

param_with_axes = nn_partitioning.param_with_axes
with_sharding_constraint = nn_partitioning.with_sharding_constraint

# Sentinel position for empty/padded cache slots and padded prompt tokens:
# larger than any real position, so causal masks (key_pos <= query_pos)
# exclude them; small enough that rotary angles stay finite.
PAD_POS = 1 << 28

# KV-cache storage formats. "bf16" stores K/V in the model compute dtype
# (the historical layout, named for the production config); "int8" stores
# symmetric per-head, per-position int8 values plus f32 scales — the decode
# attention read then streams half the bytes (benchmarks/DECODE_NOTES.md:
# KV reads are the term that grows 2.71x from b1 to b8).
KV_CACHE_DTYPES = ("bf16", "int8")
_KV_QMAX = 127.0

# KV-cache layouts. "dense" is the historical per-slot [b, max_len, ...]
# allocation; "paged" stores KV in a global pool of fixed-size pages
# ([pages, page_size, ...]) addressed through per-sequence block tables —
# the vLLM/PagedAttention design (Kwon et al., SOSP 2023), which bills HBM
# for pages actually written instead of max_len per slot.
KV_CACHE_LAYOUTS = ("dense", "paged")

# Reserved page ids in every paged pool. NULL_PAGE backs unallocated
# block-table tail entries: its position row is PAD_POS forever (writes
# through a NULL entry are redirected device-side), so gathering it always
# reads as "masked, never attended". TRASH_PAGE absorbs garbage writes —
# inactive batcher slots ride along in the static-shape decode step, and
# their stale writes must land somewhere no live block table points.
NULL_PAGE = 0
TRASH_PAGE = 1
RESERVED_PAGES = 2


def normalize_kv_cache_dtype(value) -> str:
    """Canonical kv_cache_dtype ("bf16" or "int8"); raises ValueError on
    anything else so misconfiguration fails at load() time, not inside jit."""
    v = str(value or "bf16").strip().lower()
    if v in ("bf16", "bfloat16", "model", "default"):
        return "bf16"
    if v == "int8":
        return "int8"
    raise ValueError(
        f"unknown kv_cache_dtype {value!r}: expected one of {KV_CACHE_DTYPES}"
    )


def normalize_kv_cache_layout(value) -> str:
    """Canonical kv_cache_layout ("dense" or "paged"); raises ValueError on
    anything else so misconfiguration fails at load() time, not inside jit."""
    v = str(value or "paged").strip().lower()
    if v in ("paged", "page", "block"):
        return "paged"
    if v in ("dense", "slot", "flat"):
        return "dense"
    raise ValueError(
        f"unknown kv_cache_layout {value!r}: expected one of {KV_CACHE_LAYOUTS}"
    )


def quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization over the last (head_dim) axis:
    x [..., hd] float -> (q int8 [..., hd], scale f32 [...]). One scale per
    head per position — finer than per-tensor, so attention logits survive
    outlier keys; zero vectors get scale 1 (dequantize to exact zeros)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.where(amax > 0, amax / _KV_QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -128, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of quantize_kv, used INSIDE the attention read so XLA fuses
    the convert+multiply into the consuming einsum (int8 stays the HBM
    format; dequant happens on the fly in VMEM)."""
    return q.astype(dtype) * scale[..., None].astype(dtype)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    # Llama-3.x frequency rescaling: tuple of (key, value) pairs (hashable
    # frozen-dataclass field) with factor / low_freq_factor /
    # high_freq_factor / original_max_position_embeddings; None = plain RoPE.
    rope_scaling: Any = None
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # Llama-2 uses an untied lm_head; tie only for small/test configs.
    tie_embeddings: bool = False
    # MoE: 0 = dense FFN; otherwise number of experts with top-2 routing.
    n_experts: int = 0
    n_experts_per_token: int = 2
    # "full" = dense attention (GSPMD gathers KV when seq-sharded);
    # "ring" = sequence-parallel ring attention over mesh axis 'seq'
    # (ops.ring_attention) for long-context cache-less forward/training.
    # Any call that passes a KV cache (prefill/decode serving) uses the dense
    # path regardless — ring needs seq-sharded KV, caches are slot-indexed.
    attention_impl: str = "full"
    # KV-cache storage: "bf16" (model dtype) or "int8" (quantized, per-head
    # per-position scales). Attention dispatches on the cache STRUCTURE, so
    # this field only picks the init_kv_caches default — one compiled module
    # serves either layout.
    kv_cache_dtype: str = "bf16"
    # Fuse each block's residual-add + ffn RMSNorm into one Pallas pass
    # (ops/fused_norm.py; falls back to the identical XLA expression off-TPU).
    fused_norm: bool = False
    mesh: Any = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (norm * weight).astype(x.dtype)


def _llama3_scaled_freqs(freqs: jnp.ndarray, scaling: dict) -> jnp.ndarray:
    """Llama-3.1 frequency rescaling (parity with transformers'
    _compute_llama3_parameters): low-frequency bands divide by ``factor``,
    high-frequency bands pass through, the middle band interpolates."""
    import math

    factor = float(scaling["factor"])
    lo = float(scaling["low_freq_factor"])
    hi = float(scaling["high_freq_factor"])
    old_len = float(scaling["original_max_position_embeddings"])

    wavelen = 2.0 * math.pi / freqs
    scaled = jnp.where(wavelen > old_len / lo, freqs / factor, freqs)
    smooth = (old_len / wavelen - lo) / (hi - lo)
    smoothed = (1.0 - smooth) * scaled / factor + smooth * scaled
    is_medium = (wavelen >= old_len / hi) & (wavelen <= old_len / lo)
    return jnp.where(is_medium, smoothed, scaled)


def rotary_embedding(
    positions: jnp.ndarray, head_dim: int, theta: float, rope_scaling=None
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for the given absolute positions: [..., seq, head_dim/2]."""
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    if rope_scaling:
        freqs = _llama3_scaled_freqs(freqs, dict(rope_scaling))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., seq, hd/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rotary(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [batch, seq, heads, head_dim]; cos/sin: [batch, seq, head_dim/2]."""
    cos = cos[:, :, None, :]  # broadcast over heads
    sin = sin[:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


class RMSNorm(nn.Module):
    dim: int
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x=None):
        """x=None returns the bare weight (same param path, so fused callers
        share checkpoints with the unfused graph)."""
        w = param_with_axes("weight", nn.initializers.ones_init(), (self.dim,), jnp.float32, axes=("embed",))
        if x is None:
            return w
        return rms_norm(x, w, self.eps)


def paged_write_targets(block_tables: jnp.ndarray, positions: jnp.ndarray,
                        page_size: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(page, offset) pool coordinates for writing each token's KV.

    ``block_tables``: [b, n_pages] page ids; ``positions``: [b, s] absolute
    token positions (PAD_POS for padding). Tokens whose position falls past
    the table, or whose table entry is NULL_PAGE (unallocated — the host
    failed to provision, or an inactive batcher slot riding along in the
    static-shape step), are redirected to TRASH_PAGE: the null page's
    PAD_POS position row is a device-side invariant no write may break."""
    p = positions.astype(jnp.int32)
    n_pages = block_tables.shape[1]
    page_idx = p // page_size
    valid = (p >= 0) & (page_idx < n_pages)
    entry = jnp.take_along_axis(
        block_tables, jnp.clip(page_idx, 0, n_pages - 1), axis=1)
    entry = jnp.where(valid & (entry != NULL_PAGE), entry, TRASH_PAGE)
    return entry, p % page_size


def gather_paged_view(cache, block_tables: jnp.ndarray, dtype):
    """Gather a paged pool back into the per-sequence logical view:
    (k_all, v_all, pos_view) of [b, n_pages*page_size, kvh, hd] / [b, L].

    The ONE copy of the block-table read semantics: both the attention
    fallback below and ops/paged_attention.py's ``paged_attention_ref``
    (the kernel's parity oracle) address the pool through this gather, so
    a change to the page addressing can never desynchronize them. int8
    pools (5-tuple) dequantize here — the gather moves bytes, never
    arithmetic, so the view feeds any downstream einsum exactly as the
    dense layout would."""
    bt = jnp.asarray(block_tables, jnp.int32)
    b = bt.shape[0]
    ps = cache[0].shape[1]
    L = bt.shape[1] * ps
    if len(cache) == 5:
        kq_pool, ks_pool, vq_pool, vs_pool, pos_pool = cache
        kvh, hd = kq_pool.shape[2], kq_pool.shape[3]
        k_all = dequantize_kv(kq_pool[bt].reshape(b, L, kvh, hd),
                              ks_pool[bt].reshape(b, L, kvh), dtype)
        v_all = dequantize_kv(vq_pool[bt].reshape(b, L, kvh, hd),
                              vs_pool[bt].reshape(b, L, kvh), dtype)
    else:
        k_pool, v_pool, pos_pool = cache
        kvh, hd = k_pool.shape[2], k_pool.shape[3]
        k_all = k_pool[bt].reshape(b, L, kvh, hd)
        v_all = v_pool[bt].reshape(b, L, kvh, hd)
    return k_all, v_all, pos_pool[bt].reshape(b, L)


def lora_delta(x: jnp.ndarray, A: jnp.ndarray, B: jnp.ndarray,
               adapter_ids: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Batched low-rank delta for one adapted projection (S-LoRA /
    Punica-style): gather each sequence's factors from the dense adapter
    pool by its slot's ``adapter_ids`` entry, then one einsum pair —
    ``(x @ A[id]) @ B[id] * scale[id]``.

    x: [b, s, d_in]; A: [n_adapters, d_in, r]; B: [n_adapters, r, d_out];
    adapter_ids: [b] int32; scale: [n_adapters] f32 (alpha / rank).
    Row 0 is the reserved identity (zero factors, zero scale), so a batch
    of untenanted slots computes an exact-zero delta through the SAME
    program — ``base + 0`` is bitwise ``base``, which is what lets one
    compiled step serve adapted and base traffic with identical outputs
    for the base slots (runtime/adapters.py)."""
    dt = x.dtype
    a = A[adapter_ids]                      # [b, d_in, r]   (the gather)
    b = B[adapter_ids]                      # [b, r, d_out]
    s = scale[adapter_ids].astype(dt)       # [b]
    h = jnp.einsum("bsd,bdr->bsr", x, a.astype(dt))
    return jnp.einsum("bsr,bro->bso", h, b.astype(dt)) * s[:, None, None]


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, cache: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
                 cache_index: Optional[jnp.ndarray] = None,
                 block_tables: Optional[jnp.ndarray] = None,
                 adapters: Optional[dict] = None,
                 adapter_ids: Optional[jnp.ndarray] = None):
        """x: [b, s, d]. With cache=(k_cache, v_cache, pos_cache) of
        [b, max_len, kvh, hd] / [b, max_len] — or the int8 layout
        (k_q, k_scale, v_q, v_scale, pos_cache) with int8 values and
        f32 [b, max_len, kvh] scales — runs incremental decode and
        returns (out, new_cache). cache_index is the write offset: a scalar
        (same slot for the whole batch — prefill) or a [b] vector
        (per-sequence slots — continuous batching decode; s == 1 writes at
        the vector index, while s > 1 — the speculative K-token verify —
        writes every token at its own ``positions`` entry, dropping PAD_POS
        columns). pos_cache holds each slot's absolute position (PAD_POS
        when empty), so causal masking is exact under right-padding:
        empty/pad slots are never attended.

        With ``block_tables`` ([b, n_pages] int32) the cache tuple is a PAGED
        pool — [pages, page_size, kvh, hd] buffers (same bf16 3-tuple / int8
        5-tuple structure, leading dims [pages, page_size] instead of
        [b, max_len]) shared by all sequences. Each token writes at the pool
        coordinate its block table maps its position to, and attention reads
        gather the per-sequence logical view back through the table — the
        gathered view feeds the IDENTICAL masked einsum as the dense path,
        so paged and dense decode are bit-exact (tests/test_paged_kv.py).
        cache_index is ignored (positions alone address the pool).
        Without a cache: full causal attention, returns (out, (k, v))."""
        cfg = self.cfg
        b, s, _ = x.shape
        hd = cfg.head_dim

        wq = param_with_axes(
            "wq", nn.initializers.lecun_normal(), (cfg.dim, cfg.n_heads * hd), jnp.float32,
            axes=("embed", "heads"),
        )
        wk = param_with_axes(
            "wk", nn.initializers.lecun_normal(), (cfg.dim, cfg.n_kv_heads * hd), jnp.float32,
            axes=("embed", "kv_heads"),
        )
        wv = param_with_axes(
            "wv", nn.initializers.lecun_normal(), (cfg.dim, cfg.n_kv_heads * hd), jnp.float32,
            axes=("embed", "kv_heads"),
        )
        wo = param_with_axes(
            "wo", nn.initializers.lecun_normal(), (cfg.n_heads * hd, cfg.dim), jnp.float32,
            axes=("heads", "embed"),
        )

        dt = cfg.dtype
        q_flat = x @ wq.astype(dt)
        if adapters is not None:
            # batched LoRA (runtime/adapters.py): per-slot low-rank delta
            # on q and (below) o — NEVER on k/v, so the KV written from a
            # given hidden state is base-model-pure for every tenant and
            # the paged pool/prefix machinery stays tenant-agnostic
            q_flat = q_flat + lora_delta(x, *adapters["wq"], adapter_ids,
                                         adapters["scale"])
        q = q_flat.reshape(b, s, cfg.n_heads, hd)
        k = (x @ wk.astype(dt)).reshape(b, s, cfg.n_kv_heads, hd)
        v = (x @ wv.astype(dt)).reshape(b, s, cfg.n_kv_heads, hd)

        cos, sin = rotary_embedding(positions, hd, cfg.rope_theta, cfg.rope_scaling)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)

        use_paged_kernel = False
        if cache is not None and block_tables is not None:
            # Paged pool: write each token's K/V at the (page, offset) its
            # block table maps its position to; read by gathering the pages
            # back into the per-sequence logical [b, n_pages*ps, ...] view.
            bt = jnp.asarray(block_tables, jnp.int32)
            ps = cache[0].shape[1]
            entry, off = paged_write_targets(bt, positions, ps)
            if len(cache) == 5:
                kq_pool, ks_pool, vq_pool, vs_pool, pos_pool = cache
                kq, ks = quantize_kv(k)
                vq, vs = quantize_kv(v)
                kq_pool = kq_pool.at[entry, off].set(kq)
                ks_pool = ks_pool.at[entry, off].set(ks)
                vq_pool = vq_pool.at[entry, off].set(vq)
                vs_pool = vs_pool.at[entry, off].set(vs)
                pos_pool = pos_pool.at[entry, off].set(
                    positions.astype(pos_pool.dtype))
                new_cache = (kq_pool, ks_pool, vq_pool, vs_pool, pos_pool)
            else:
                k_pool, v_pool, pos_pool = cache
                k_pool = k_pool.at[entry, off].set(k.astype(k_pool.dtype))
                v_pool = v_pool.at[entry, off].set(v.astype(v_pool.dtype))
                pos_pool = pos_pool.at[entry, off].set(
                    positions.astype(pos_pool.dtype))
                new_cache = (k_pool, v_pool, pos_pool)
            from seldon_core_tpu.ops.paged_attention import paged_kernel_viable

            use_paged_kernel = s == 1 and paged_kernel_viable()
            if not use_paged_kernel:
                # pure-gather fallback: reconstruct the logical view and fall
                # through to the SAME masked einsum the dense layout uses —
                # paged == dense bit-for-bit (masked positions contribute
                # exact zeros).
                k_all, v_all, pos_view = gather_paged_view(new_cache, bt, dt)
                mask = pos_view[:, None, :] <= positions[:, :, None]
        elif cache is not None and len(cache) == 5:
            # int8 cache: (k_q, k_scale, v_q, v_scale, pos). Quantize-on-write
            # (new K/V rows become int8 + per-head scales before the scatter),
            # dequant fused into the attention read below.
            kq_cache, ks_cache, vq_cache, vs_cache, pos_cache = cache
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            idx = jnp.asarray(cache_index, dtype=jnp.int32)
            if idx.ndim == 0:
                kq_cache = jax.lax.dynamic_update_slice(kq_cache, kq, (0, idx, 0, 0))
                ks_cache = jax.lax.dynamic_update_slice(ks_cache, ks, (0, idx, 0))
                vq_cache = jax.lax.dynamic_update_slice(vq_cache, vq, (0, idx, 0, 0))
                vs_cache = jax.lax.dynamic_update_slice(vs_cache, vs, (0, idx, 0))
                pos_cache = jax.lax.dynamic_update_slice(
                    pos_cache, positions.astype(pos_cache.dtype), (0, idx)
                )
            elif s == 1:
                # per-sequence write offsets (continuous batching): s == 1
                bidx = jnp.arange(b)
                kq_cache = kq_cache.at[bidx, idx].set(kq[:, 0])
                ks_cache = ks_cache.at[bidx, idx].set(ks[:, 0])
                vq_cache = vq_cache.at[bidx, idx].set(vq[:, 0])
                vs_cache = vs_cache.at[bidx, idx].set(vs[:, 0])
                pos_cache = pos_cache.at[bidx, idx].set(positions[:, 0].astype(pos_cache.dtype))
            else:
                # per-sequence K-token writes (speculative verify): every
                # token scatters at its own absolute position. Padded draft
                # columns carry PAD_POS positions — far past max_len — and
                # mode="drop" discards those writes, so a short draft never
                # touches the cache (the dense analog of the paged layout's
                # TRASH_PAGE redirect).
                bidx2 = jnp.arange(b)[:, None]
                wp = positions.astype(jnp.int32)
                kq_cache = kq_cache.at[bidx2, wp].set(kq, mode="drop")
                ks_cache = ks_cache.at[bidx2, wp].set(ks, mode="drop")
                vq_cache = vq_cache.at[bidx2, wp].set(vq, mode="drop")
                vs_cache = vs_cache.at[bidx2, wp].set(vs, mode="drop")
                pos_cache = pos_cache.at[bidx2, wp].set(
                    positions.astype(pos_cache.dtype), mode="drop")
            # the int8 buffers are what streams from HBM; XLA fuses this
            # convert+multiply into the attention einsums (VMEM dequant)
            k_all = dequantize_kv(kq_cache, ks_cache, dt)
            v_all = dequantize_kv(vq_cache, vs_cache, dt)
            mask = pos_cache[:, None, :] <= positions[:, :, None]  # [b, s, kv]
            new_cache = (kq_cache, ks_cache, vq_cache, vs_cache, pos_cache)
        elif cache is not None:
            k_cache, v_cache, pos_cache = cache
            idx = jnp.asarray(cache_index, dtype=jnp.int32)
            if idx.ndim == 0:
                k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, idx, 0, 0))
                v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, idx, 0, 0))
                pos_cache = jax.lax.dynamic_update_slice(
                    pos_cache, positions.astype(pos_cache.dtype), (0, idx)
                )
            elif s == 1:
                # per-sequence write offsets (continuous batching): s == 1
                bidx = jnp.arange(b)
                k_cache = k_cache.at[bidx, idx].set(k[:, 0].astype(k_cache.dtype))
                v_cache = v_cache.at[bidx, idx].set(v[:, 0].astype(v_cache.dtype))
                pos_cache = pos_cache.at[bidx, idx].set(positions[:, 0].astype(pos_cache.dtype))
            else:
                # per-sequence K-token writes (speculative verify): see the
                # int8 branch above — positions address the cache directly,
                # PAD_POS columns drop.
                bidx2 = jnp.arange(b)[:, None]
                wp = positions.astype(jnp.int32)
                k_cache = k_cache.at[bidx2, wp].set(
                    k.astype(k_cache.dtype), mode="drop")
                v_cache = v_cache.at[bidx2, wp].set(
                    v.astype(v_cache.dtype), mode="drop")
                pos_cache = pos_cache.at[bidx2, wp].set(
                    positions.astype(pos_cache.dtype), mode="drop")
            k_all, v_all = k_cache, v_cache
            # pos_cache marks empty slots with PAD_POS, so one predicate covers
            # causality, the unfilled suffix, and right-padding garbage.
            mask = pos_cache[:, None, :] <= positions[:, :, None]  # [b, s, kv]
            new_cache = (k_cache, v_cache, pos_cache)
        else:
            k_all, v_all = k, v
            mask = positions[:, None, :] <= positions[:, :, None]  # [b, s, kv]
            new_cache = (k, v)

        if use_paged_kernel:
            # TPU decode fast path: one Pallas pass streams ONLY the pages
            # each sequence's block table names (probe-gated; every other
            # platform took the gather fallback above).
            from seldon_core_tpu.ops.paged_attention import paged_attention

            out = paged_attention(q, new_cache, bt, positions)
        elif cache is None and cfg.attention_impl == "ring":
            from seldon_core_tpu.ops.ring_attention import ring_attention

            # ring is GQA-aware: unrepeated KV rides the ring
            out = ring_attention(
                q, k_all.astype(dt), v_all.astype(dt), positions, positions, mesh=cfg.mesh
            )
        else:
            # GQA: repeat kv heads up to n_heads for the dense einsum
            if cfg.n_kv_heads != cfg.n_heads:
                rep = cfg.n_heads // cfg.n_kv_heads
                k_all = jnp.repeat(k_all, rep, axis=2)
                v_all = jnp.repeat(v_all, rep, axis=2)
            scale = hd**-0.5
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_all.astype(dt)) * scale
            logits = logits.astype(jnp.float32)
            logits = jnp.where(mask[:, None, :, :], logits, jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(logits, axis=-1).astype(dt)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v_all.astype(dt))
        out = out.reshape(b, s, cfg.n_heads * hd)
        proj = out @ wo.astype(dt)
        if adapters is not None:
            proj = proj + lora_delta(out, *adapters["wo"], adapter_ids,
                                     adapters["scale"])
        return proj, new_cache


class DenseFFN(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, adapters: Optional[dict] = None,
                 adapter_ids: Optional[jnp.ndarray] = None):
        cfg = self.cfg
        w1 = param_with_axes("w1", nn.initializers.lecun_normal(), (cfg.dim, cfg.ffn_dim), jnp.float32,
                             axes=("embed", "mlp"))
        w2 = param_with_axes("w2", nn.initializers.lecun_normal(), (cfg.ffn_dim, cfg.dim), jnp.float32,
                             axes=("mlp", "embed"))
        w3 = param_with_axes("w3", nn.initializers.lecun_normal(), (cfg.dim, cfg.ffn_dim), jnp.float32,
                             axes=("embed", "mlp"))
        dt = cfg.dtype
        up = x @ w1.astype(dt)
        gate = x @ w3.astype(dt)
        if adapters is not None:
            up = up + lora_delta(x, *adapters["w1"], adapter_ids,
                                 adapters["scale"])
            gate = gate + lora_delta(x, *adapters["w3"], adapter_ids,
                                     adapters["scale"])
        h = jax.nn.silu(up) * gate
        down = h @ w2.astype(dt)
        if adapters is not None:
            down = down + lora_delta(h, *adapters["w2"], adapter_ids,
                                     adapters["scale"])
        return down


class MoEFFN(nn.Module):
    """Top-k token-choice MoE with an 'expert' partition axis (EP). Dense
    einsum formulation — every expert computes every token, weighted by the
    router — which is XLA-friendly at small expert counts and shards cleanly
    over the expert axis."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e = cfg.n_experts
        dt = cfg.dtype
        router = param_with_axes("router", nn.initializers.lecun_normal(), (cfg.dim, e), jnp.float32,
                                 axes=("embed", "expert"))
        w1 = param_with_axes("w1", nn.initializers.lecun_normal(), (e, cfg.dim, cfg.ffn_dim), jnp.float32,
                             axes=("expert", "embed", "mlp"))
        w2 = param_with_axes("w2", nn.initializers.lecun_normal(), (e, cfg.ffn_dim, cfg.dim), jnp.float32,
                             axes=("expert", "mlp", "embed"))
        w3 = param_with_axes("w3", nn.initializers.lecun_normal(), (e, cfg.dim, cfg.ffn_dim), jnp.float32,
                             axes=("expert", "embed", "mlp"))

        gate_logits = (x.astype(jnp.float32) @ router)  # [b, s, e]
        k = min(cfg.n_experts_per_token, e)
        topv, topi = jax.lax.top_k(gate_logits, k)
        gates = jax.nn.softmax(topv, axis=-1)  # [b, s, k]
        # dense weights [b, s, e]: scatter top-k gates
        dense_gates = jnp.zeros_like(gate_logits).at[
            jnp.arange(x.shape[0])[:, None, None],
            jnp.arange(x.shape[1])[None, :, None],
            topi,
        ].set(gates)
        h = jax.nn.silu(jnp.einsum("bsd,edf->bsef", x, w1.astype(dt))) * jnp.einsum(
            "bsd,edf->bsef", x, w3.astype(dt)
        )
        y = jnp.einsum("bsef,efd->bsed", h, w2.astype(dt))
        return jnp.einsum("bsed,bse->bsd", y, dense_gates.astype(dt))


class TransformerBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, cache=None, cache_index=None,
                 block_tables=None, adapters=None, adapter_ids=None):
        cfg = self.cfg
        h, new_cache = Attention(cfg, name="attention")(
            RMSNorm(cfg.dim, cfg.norm_eps, name="attention_norm")(x), positions, cache, cache_index,
            block_tables, adapters, adapter_ids,
        )
        ffn_norm = RMSNorm(cfg.dim, cfg.norm_eps, name="ffn_norm")
        if cfg.fused_norm:
            # residual-add + RMSNorm in one HBM pass (ops/fused_norm.py):
            # collapses the per-layer norm chains the decode profile flags
            # (~7.5 us each on [8, 2048] tensors — DECODE_NOTES.md). Off-TPU
            # this lowers to the identical XLA expression.
            from seldon_core_tpu.ops.fused_norm import fused_residual_rmsnorm

            x, ffn_in = fused_residual_rmsnorm(x, h, ffn_norm(), cfg.norm_eps)
        else:
            x = x + h
            ffn_in = ffn_norm(x)
        if cfg.n_experts > 0:
            f = MoEFFN(cfg, name="moe")(ffn_in)
        else:
            f = DenseFFN(cfg, name="ffn")(ffn_in, adapters, adapter_ids)
        return x + f, new_cache


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, caches=None, cache_index=None,
                 block_tables=None, adapters=None, adapter_ids=None):
        """tokens: [b, s] int32. Returns (logits [b, s, vocab], new_caches).
        ``block_tables`` ([b, n_pages] int32, shared by every layer) switches
        the caches to the paged-pool layout — see Attention.

        ``adapters`` (the dense LoRA pool pytree from
        runtime/adapters.py: {proj: (A [N, L, d_in, r], B [N, L, r,
        d_out]), "scale": [N]}) plus ``adapter_ids`` ([b] int32) turn on
        per-sequence batched low-rank deltas on the q/o/FFN projections —
        each layer slices its own factors out of the pool and applies one
        gather+einsum pair per adapted projection (``lora_delta``).
        adapter id 0 is the reserved zero-delta identity."""
        cfg = self.cfg
        b, s = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        if adapters is not None and adapter_ids is None:
            raise ValueError("adapters need adapter_ids (one id per "
                             "sequence; 0 = identity)")
        emb = param_with_axes(
            "tok_embeddings", nn.initializers.normal(stddev=0.02), (cfg.vocab_size, cfg.dim),
            jnp.float32, axes=("vocab", "embed"),
        )
        x = emb.astype(cfg.dtype)[tokens]
        x = with_sharding_constraint(x, ("batch", "seq", "embed"))
        new_caches = []
        for i in range(cfg.n_layers):
            layer_cache = caches[i] if caches is not None else None
            layer_adapters = None
            if adapters is not None:
                # slice this layer's factors: [N, L, ...] -> [N, ...]
                layer_adapters = {
                    proj: (ab[0][:, i], ab[1][:, i])
                    for proj, ab in adapters.items() if proj != "scale"
                }
                layer_adapters["scale"] = adapters["scale"]
            x, nc = TransformerBlock(cfg, name=f"layer_{i}")(
                x, positions, layer_cache, cache_index, block_tables,
                layer_adapters, adapter_ids)
            new_caches.append(nc)
        x = RMSNorm(cfg.dim, cfg.norm_eps, name="norm")(x)
        if cfg.tie_embeddings:
            logits = x.astype(jnp.float32) @ emb.T
        else:
            lm_head = param_with_axes(
                "lm_head", nn.initializers.normal(stddev=0.02), (cfg.dim, cfg.vocab_size),
                jnp.float32, axes=("embed", "vocab"),
            )
            logits = x.astype(jnp.float32) @ lm_head
        return logits, new_caches


def init_kv_caches(cfg: TransformerConfig, batch: int, max_len: int,
                   kv_cache_dtype: Optional[str] = None):
    """Static-shape KV caches: one (k, v, pos) triple per layer —
    [b, max_len, kvh, hd] buffers plus a [b, max_len] position map whose empty
    slots hold PAD_POS (never attended). With kv_cache_dtype="int8" each
    layer is a (k_q, k_scale, v_q, v_scale, pos) 5-tuple: int8 values plus
    f32 [b, max_len, kvh] per-head per-position scales (initialised to 1 so
    empty slots dequantize to exact zeros)."""
    kvd = normalize_kv_cache_dtype(kv_cache_dtype or cfg.kv_cache_dtype)
    shape = (batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    if kvd == "int8":
        scale_shape = (batch, max_len, cfg.n_kv_heads)
        return [
            (
                jnp.zeros(shape, dtype=jnp.int8),
                jnp.ones(scale_shape, dtype=jnp.float32),
                jnp.zeros(shape, dtype=jnp.int8),
                jnp.ones(scale_shape, dtype=jnp.float32),
                jnp.full((batch, max_len), PAD_POS, dtype=jnp.int32),
            )
            for _ in range(cfg.n_layers)
        ]
    return [
        (
            jnp.zeros(shape, dtype=cfg.dtype),
            jnp.zeros(shape, dtype=cfg.dtype),
            jnp.full((batch, max_len), PAD_POS, dtype=jnp.int32),
        )
        for _ in range(cfg.n_layers)
    ]


def init_paged_kv_caches(cfg: TransformerConfig, num_pages: int,
                         page_size: int, kv_cache_dtype: Optional[str] = None):
    """Paged KV pools: one (k, v, pos) triple per layer with leading dims
    [num_pages, page_size] instead of [batch, max_len] — pages are shared by
    every sequence through per-sequence block tables. Pages 0 and 1 are
    reserved (NULL_PAGE / TRASH_PAGE; see module constants), so a pool of
    ``num_pages`` serves ``num_pages - RESERVED_PAGES`` tokens' worth of
    allocatable KV. Position rows initialise to PAD_POS (never attended);
    int8 pools carry f32 [num_pages, page_size, kvh] scale planes
    initialised to 1 (empty slots dequantize to exact zeros)."""
    if num_pages <= RESERVED_PAGES:
        raise ValueError(
            f"paged KV pool needs > {RESERVED_PAGES} pages "
            f"(got {num_pages}; pages 0/1 are reserved)")
    kvd = normalize_kv_cache_dtype(kv_cache_dtype or cfg.kv_cache_dtype)
    shape = (num_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    if kvd == "int8":
        scale_shape = (num_pages, page_size, cfg.n_kv_heads)
        return [
            (
                jnp.zeros(shape, dtype=jnp.int8),
                jnp.ones(scale_shape, dtype=jnp.float32),
                jnp.zeros(shape, dtype=jnp.int8),
                jnp.ones(scale_shape, dtype=jnp.float32),
                jnp.full((num_pages, page_size), PAD_POS, dtype=jnp.int32),
            )
            for _ in range(cfg.n_layers)
        ]
    return [
        (
            jnp.zeros(shape, dtype=cfg.dtype),
            jnp.zeros(shape, dtype=cfg.dtype),
            jnp.full((num_pages, page_size), PAD_POS, dtype=jnp.int32),
        )
        for _ in range(cfg.n_layers)
    ]


def kv_cache_bytes_per_token(cfg: TransformerConfig,
                             kv_cache_dtype: Optional[str] = None) -> int:
    """HBM bytes one cached token position costs across all layers (K + V
    values, int8 scales when quantized, and the int32 position map). Decode
    attention reads the whole static cache every step, so
    bytes/step ~= batch * cache_len * this. Reported by the LLM benches so
    BENCH rounds can attribute bandwidth regressions."""
    kvd = normalize_kv_cache_dtype(kv_cache_dtype or cfg.kv_cache_dtype)
    per_pos = cfg.n_kv_heads * cfg.head_dim
    if kvd == "int8":
        per_layer = 2 * (per_pos * 1 + cfg.n_kv_heads * 4)  # int8 + f32 scale
    else:
        per_layer = 2 * per_pos * jnp.dtype(cfg.dtype).itemsize
    return cfg.n_layers * (per_layer + 4)  # + int32 pos slot


@register_model("transformer")
def make_transformer(**kwargs):
    dtype = kwargs.pop("dtype", "bfloat16")
    scaling = kwargs.pop("rope_scaling", None)
    if isinstance(scaling, dict):  # normalize to a hashable config field
        scaling = tuple(sorted(scaling.items()))
    kvd = normalize_kv_cache_dtype(kwargs.pop("kv_cache_dtype", "bf16"))
    cfg = TransformerConfig(dtype=jnp.dtype(dtype), rope_scaling=scaling,
                            kv_cache_dtype=kvd, **kwargs)
    return Transformer(cfg)


@register_model("llama2-7b")
def make_llama2_7b(dtype: str = "bfloat16"):
    cfg = TransformerConfig(
        vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=32,
        ffn_dim=11008, max_seq_len=4096, dtype=jnp.dtype(dtype),
    )
    return Transformer(cfg)


@register_model("llama-tiny")
def make_llama_tiny(dtype: str = "float32", **kwargs):
    """Small config for tests and the multi-chip dry run."""
    cfg = TransformerConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, dtype=jnp.dtype(dtype),
        tie_embeddings=True, **kwargs,
    )
    return Transformer(cfg)
