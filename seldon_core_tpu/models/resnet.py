"""ResNet family (v1.5 bottleneck) in Flax — the BASELINE.json north-star
model (ResNet-50 on v5e). Designed for the MXU: NHWC layout, bfloat16 compute,
f32 batch-norm statistics, no data-dependent control flow, so XLA fuses the
conv+BN+relu chains."""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from seldon_core_tpu.models.registry import register_model

ModuleDef = Any


class _NoNorm(nn.Module):
    """Identity stand-in for BatchNorm in the folded inference variant."""

    @nn.compact
    def __call__(self, x):
        return x


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    # Inference-only folded variant: convs carry a bias and BatchNorm sites
    # are identity — run it with params from fold_batchnorm(). Removes every
    # BN stats read + f32 affine chain from the serving graph (HBM traffic),
    # leaving pure conv+bias+relu for XLA to fuse.
    fused: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.fused and train:
            raise ValueError("fused=True is inference-only (BN is folded away)")
        conv = partial(nn.Conv, use_bias=self.fused, dtype=self.dtype)
        if self.fused:
            norm = lambda **kw: _NoNorm()  # noqa: E731 (name kwarg dropped)
        else:
            norm = partial(
                nn.BatchNorm,
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
            )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


_BN_EPS = 1e-5  # must match the BatchNorm epsilon above


def fold_batchnorm(variables):
    """Fold BatchNorm into the adjacent convs: trained {'params',
    'batch_stats'} -> {'params'} for the ``fused=True`` module.

    BN(conv(x)) = conv(x)*s + b with s = gamma/rsqrt(var+eps) and
    b = beta - mean*s; s scales the conv kernel's output channels and b
    becomes the conv bias. Pairs: conv_init<->bn_init, Conv_j<->BatchNorm_j,
    conv_proj<->norm_proj; the classifier head passes through. Numerics: the
    fold runs in f32 regardless of serving dtype."""
    import jax.numpy as jnp

    params = variables["params"]
    stats = variables["batch_stats"]

    def fold_pair(conv, bn, bn_stats):
        s = bn["scale"].astype(jnp.float32) * jax.lax.rsqrt(
            bn_stats["var"].astype(jnp.float32) + _BN_EPS
        )
        b = bn["bias"].astype(jnp.float32) - bn_stats["mean"].astype(jnp.float32) * s
        kernel = conv["kernel"].astype(jnp.float32) * s  # [..., out] broadcast
        return {"kernel": kernel.astype(conv["kernel"].dtype), "bias": b}

    out = {}
    for key, scope in params.items():
        if key == "conv_init":
            out[key] = fold_pair(scope, params["bn_init"], stats["bn_init"])
        elif key.startswith("BottleneckBlock_"):
            block_out = {}
            for ck, cv in scope.items():
                if ck.startswith("Conv_"):
                    bn_key = "BatchNorm_" + ck.split("_")[1]
                    block_out[ck] = fold_pair(cv, scope[bn_key], stats[key][bn_key])
                elif ck == "conv_proj":
                    block_out[ck] = fold_pair(cv, scope["norm_proj"], stats[key]["norm_proj"])
            out[key] = block_out
        elif key in ("bn_init",) or key.startswith("BatchNorm") or key == "norm_proj":
            continue
        else:  # head and anything param-only
            out[key] = scope
    return {"params": out}


@register_model("resnet50")
def make_resnet50(num_classes: int = 1000, dtype: str = "bfloat16", fused: bool = False):
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                  dtype=jnp.dtype(dtype), fused=fused)


@register_model("resnet18")
def make_resnet18(num_classes: int = 1000, dtype: str = "bfloat16", fused: bool = False):
    # 18-layer variant uses the same bottleneck stack shrunk to (2,2,2,2);
    # kept bottleneck (not basic-block) for MXU-friendly 1x1 convs.
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes,
                  dtype=jnp.dtype(dtype), fused=fused)


@register_model("resnet101")
def make_resnet101(num_classes: int = 1000, dtype: str = "bfloat16", fused: bool = False):
    return ResNet(stage_sizes=(3, 4, 23, 3), num_classes=num_classes,
                  dtype=jnp.dtype(dtype), fused=fused)
