"""ResNet family (v1.5 bottleneck) in Flax — the BASELINE.json north-star
model (ResNet-50 on v5e). Designed for the MXU: NHWC layout, bfloat16 compute,
f32 batch-norm statistics, no data-dependent control flow, so XLA fuses the
conv+BN+relu chains."""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from seldon_core_tpu.models.registry import register_model

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


@register_model("resnet50")
def make_resnet50(num_classes: int = 1000, dtype: str = "bfloat16"):
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, dtype=jnp.dtype(dtype))


@register_model("resnet18")
def make_resnet18(num_classes: int = 1000, dtype: str = "bfloat16"):
    # 18-layer variant uses the same bottleneck stack shrunk to (2,2,2,2);
    # kept bottleneck (not basic-block) for MXU-friendly 1x1 convs.
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes, dtype=jnp.dtype(dtype))


@register_model("resnet101")
def make_resnet101(num_classes: int = 1000, dtype: str = "bfloat16"):
    return ResNet(stage_sizes=(3, 4, 23, 3), num_classes=num_classes, dtype=jnp.dtype(dtype))
