"""ResNet family (v1.5 bottleneck) in Flax — the BASELINE.json north-star
model (ResNet-50 on v5e). Designed for the MXU: NHWC layout, bfloat16 compute,
f32 batch-norm statistics, no data-dependent control flow, so XLA fuses the
conv+BN+relu chains."""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from seldon_core_tpu.models.registry import register_model

ModuleDef = Any


class _NoNorm(nn.Module):
    """Identity stand-in for BatchNorm in the folded inference variant."""

    @nn.compact
    def __call__(self, x):
        return x


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth(x, block: int = 2):
    """(B, H, W, C) -> (B, H/b, W/b, b*b*C), channel order (di, dj, c).

    Pure data-layout transform; the classic TPU ResNet stem trick (the
    MLPerf-era space-to-depth input pipeline): the 7x7/s2 stem conv over a
    3-channel image packs the MXU at 3/128 input channels, while the same
    arithmetic expressed as a 4x4/s1 conv over the 2x2-packed 12-channel
    image packs it 4x denser — see fold_space_to_depth for the exact weight
    refold. Runs fine on host (numpy) or device (jnp)."""
    b, h, w, c = x.shape
    xp = np if isinstance(x, np.ndarray) else jnp
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = xp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, h // block, w // block, block * block * c)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    # Inference-only folded variant: convs carry a bias and BatchNorm sites
    # are identity — run it with params from fold_batchnorm(). Removes every
    # BN stats read + f32 affine chain from the serving graph (HBM traffic),
    # leaving pure conv+bias+relu for XLA to fuse.
    fused: bool = False
    # Inference-only space-to-depth stem (requires fused=True): the input is
    # 2x2-packed to (B, H/2, W/2, 12) and the 7x7/s2 stem conv becomes a
    # bit-equivalent 4x4/s1 conv named conv_init_s2d — params come from
    # fold_space_to_depth(fold_batchnorm(vars)). The packing itself happens
    # inside __call__ (device-side) unless the caller stages pre-packed
    # (B, H/2, W/2, 12) input, which is detected by channel count.
    stem_s2d: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.fused and train:
            raise ValueError("fused=True is inference-only (BN is folded away)")
        if self.stem_s2d and not self.fused:
            raise ValueError("stem_s2d=True requires fused=True (inference-only)")
        conv = partial(nn.Conv, use_bias=self.fused, dtype=self.dtype)
        if self.fused:
            norm = lambda **kw: _NoNorm()  # noqa: E731 (name kwarg dropped)
        else:
            norm = partial(
                nn.BatchNorm,
                use_running_average=not train,
                momentum=0.9,
                epsilon=1e-5,
                dtype=self.dtype,
            )
        x = x.astype(self.dtype)
        if self.stem_s2d:
            if x.shape[-1] == 3:
                x = space_to_depth(x)
            # offsets: s2d row u holds original rows {2u, 2u+1}; output i of
            # the 7x7/s2 conv needs original rows 2i-3..2i+3, i.e. s2d rows
            # i-2..i+1 -> kernel 4, stride 1, padding (2, 1).
            x = conv(
                self.num_filters, (4, 4), (1, 1), padding=[(2, 1), (2, 1)],
                name="conv_init_s2d",
            )(x)
        else:
            x = conv(
                self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init"
            )(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


_BN_EPS = 1e-5  # must match the BatchNorm epsilon above


def fold_batchnorm(variables):
    """Fold BatchNorm into the adjacent convs: trained {'params',
    'batch_stats'} -> {'params'} for the ``fused=True`` module.

    BN(conv(x)) = conv(x)*s + b with s = gamma/rsqrt(var+eps) and
    b = beta - mean*s; s scales the conv kernel's output channels and b
    becomes the conv bias. Pairs: conv_init<->bn_init, Conv_j<->BatchNorm_j,
    conv_proj<->norm_proj; the classifier head passes through. Numerics: the
    fold runs in f32 regardless of serving dtype."""
    import jax.numpy as jnp

    params = variables["params"]
    stats = variables["batch_stats"]

    def fold_pair(conv, bn, bn_stats):
        s = bn["scale"].astype(jnp.float32) * jax.lax.rsqrt(
            bn_stats["var"].astype(jnp.float32) + _BN_EPS
        )
        b = bn["bias"].astype(jnp.float32) - bn_stats["mean"].astype(jnp.float32) * s
        kernel = conv["kernel"].astype(jnp.float32) * s  # [..., out] broadcast
        return {"kernel": kernel.astype(conv["kernel"].dtype), "bias": b}

    out = {}
    for key, scope in params.items():
        if key == "conv_init":
            out[key] = fold_pair(scope, params["bn_init"], stats["bn_init"])
        elif key.startswith("BottleneckBlock_"):
            block_out = {}
            for ck, cv in scope.items():
                if ck.startswith("Conv_"):
                    bn_key = "BatchNorm_" + ck.split("_")[1]
                    block_out[ck] = fold_pair(cv, scope[bn_key], stats[key][bn_key])
                elif ck == "conv_proj":
                    block_out[ck] = fold_pair(cv, scope["norm_proj"], stats[key]["norm_proj"])
            out[key] = block_out
        elif key in ("bn_init",) or key.startswith("BatchNorm") or key == "norm_proj":
            continue
        else:  # head and anything param-only
            out[key] = scope
    return {"params": out}


def fold_space_to_depth(variables):
    """Refold a folded-BN conv_init (7,7,3,F) kernel into the equivalent
    conv_init_s2d (4,4,12,F) kernel for the ``stem_s2d=True`` module.

    Derivation: output i of the 7x7/s2 conv reads original rows 2i-3..2i+3.
    With 2x2 space-to-depth, s2d row u = i-2+a (a=0..3) carries original
    rows 2u+di (di=0,1), i.e. original offset index p' = 2a+di over the
    8-row window starting at 2i-4. Pad the kernel's 7 taps to 8 with a zero
    at the FRONT (offset -4 is never read by the original conv), then
    K[a, b, (di, dj, c), f] = Wpad[2a+di, 2b+dj, c, f] — exactly a reshape
    (8,8,3,F)->(4,2,4,2,3,F) + transpose to (4,4,2,2,3,F) + channel merge,
    matching space_to_depth's (di, dj, c) packing order. Zero extra FLOPs
    beyond the 4 dead taps; numerics identical up to summation order."""
    params = {k: v for k, v in variables["params"].items()}
    conv = params.pop("conv_init")
    w = conv["kernel"]  # (7, 7, C, F)
    kh, kw, c, f = w.shape
    if (kh, kw) != (7, 7):
        raise ValueError(f"fold_space_to_depth expects a 7x7 stem, got {(kh, kw)}")
    xp = np if isinstance(w, np.ndarray) else jnp
    wpad = xp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))  # zero tap at offset -4
    k = wpad.reshape(4, 2, 4, 2, c, f)  # (a, di, b, dj, c, f)
    k = xp.transpose(k, (0, 2, 1, 3, 4, 5)).reshape(4, 4, 4 * c, f)
    params["conv_init_s2d"] = {"kernel": k, "bias": conv["bias"]}
    return {"params": params}


@register_model("resnet50")
def make_resnet50(num_classes: int = 1000, dtype: str = "bfloat16", fused: bool = False,
                  stem_s2d: bool = False):
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                  dtype=jnp.dtype(dtype), fused=fused, stem_s2d=stem_s2d)


@register_model("resnet18")
def make_resnet18(num_classes: int = 1000, dtype: str = "bfloat16", fused: bool = False,
                  stem_s2d: bool = False):
    # 18-layer variant uses the same bottleneck stack shrunk to (2,2,2,2);
    # kept bottleneck (not basic-block) for MXU-friendly 1x1 convs.
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes,
                  dtype=jnp.dtype(dtype), fused=fused, stem_s2d=stem_s2d)


@register_model("resnet101")
def make_resnet101(num_classes: int = 1000, dtype: str = "bfloat16", fused: bool = False,
                   stem_s2d: bool = False):
    return ResNet(stage_sizes=(3, 4, 23, 3), num_classes=num_classes,
                  dtype=jnp.dtype(dtype), fused=fused, stem_s2d=stem_s2d)
