"""MLP classifier — the minimal end-to-end model family (SURVEY.md §7 stage 4:
"a Flax MLP served REST+gRPC"). bfloat16 matmuls by default so XLA tiles them
onto the MXU."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from seldon_core_tpu.models.registry import register_model


class MLP(nn.Module):
    features: Sequence[int] = (128, 128)
    num_classes: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for f in self.features:
            x = nn.Dense(f, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return nn.softmax(x.astype(jnp.float32))


@register_model("mlp")
def make_mlp(features: Sequence[int] = (128, 128), num_classes: int = 3, dtype: str = "bfloat16"):
    return MLP(features=tuple(features), num_classes=num_classes, dtype=jnp.dtype(dtype))
