"""Vision Transformer family — MXU-first image classification.

No reference counterpart (the reference is model-agnostic and ships no
models); this is the second vision family beside ResNet. The design plays
to the MXU harder than convs do: patchify is ONE strided conv (equivalently
a reshaped matmul), after which the entire network is large batched matmuls
(attention + MLP) in bfloat16 with f32 layernorm statistics — no im2col, no
spatial loops. Logical axis names ride param_with_axes so the GSPMD rules in
parallel/sharding.py shard it exactly like the language models: heads/mlp
over 'model', batch over 'data'.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

from seldon_core_tpu.models.registry import register_model

param_with_axes = nn_partitioning.param_with_axes


class _Mlp(nn.Module):
    dim: int
    hidden: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        w1 = param_with_axes("w1", nn.initializers.xavier_uniform(), (self.dim, self.hidden),
                             jnp.float32, axes=("embed", "mlp"))
        b1 = param_with_axes("b1", nn.initializers.zeros_init(), (self.hidden,),
                             jnp.float32, axes=("mlp",))
        w2 = param_with_axes("w2", nn.initializers.xavier_uniform(), (self.hidden, self.dim),
                             jnp.float32, axes=("mlp", "embed"))
        b2 = param_with_axes("b2", nn.initializers.zeros_init(), (self.dim,),
                             jnp.float32, axes=("embed",))
        dt = self.dtype
        h = nn.gelu(x @ w1.astype(dt) + b1.astype(dt))
        return h @ w2.astype(dt) + b2.astype(dt)


class _Attention(nn.Module):
    dim: int
    n_heads: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        hd = self.dim // self.n_heads
        dt = self.dtype
        wqkv = param_with_axes("wqkv", nn.initializers.xavier_uniform(),
                               (self.dim, 3 * self.dim), jnp.float32, axes=("embed", "heads"))
        wo = param_with_axes("wo", nn.initializers.xavier_uniform(),
                             (self.dim, self.dim), jnp.float32, axes=("heads", "embed"))
        b, s, _ = x.shape
        qkv = (x @ wqkv.astype(dt)).reshape(b, s, 3, self.n_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * hd**-0.5
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(dt)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, self.dim)
        return out @ wo.astype(dt)


class ViT(nn.Module):
    patch: int = 16
    dim: int = 768
    depth: int = 12
    n_heads: int = 12
    mlp_ratio: int = 4
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        # ``train`` keeps the vision-family calling convention (ResNet needs
        # it for BN); this ViT config has no train-only ops (no dropout), so
        # the flag is accepted and intentionally unused.
        del train
        dt = self.dtype
        x = x.astype(dt)
        # patchify: one strided conv = a [p*p*c, dim] matmul on the MXU
        x = nn.Conv(self.dim, (self.patch, self.patch), strides=(self.patch, self.patch),
                    dtype=dt, name="patch_embed")(x)
        b, h, w, _ = x.shape
        x = x.reshape(b, h * w, self.dim)

        cls = param_with_axes("cls", nn.initializers.zeros_init(), (1, 1, self.dim),
                              jnp.float32, axes=(None, None, "embed"))
        pos = param_with_axes("pos_embed", nn.initializers.normal(stddev=0.02),
                              (1, h * w + 1, self.dim), jnp.float32,
                              axes=(None, None, "embed"))
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(dt), (b, 1, self.dim)), x], axis=1)
        x = x + pos.astype(dt)

        for i in range(self.depth):
            y = nn.LayerNorm(dtype=dt, name=f"ln1_{i}")(x)
            x = x + _Attention(self.dim, self.n_heads, dt, name=f"attn_{i}")(y)
            y = nn.LayerNorm(dtype=dt, name=f"ln2_{i}")(x)
            x = x + _Mlp(self.dim, self.dim * self.mlp_ratio, dt, name=f"mlp_{i}")(y)

        x = nn.LayerNorm(dtype=dt, name="ln_final")(x)
        head = param_with_axes("head", nn.initializers.zeros_init(),
                               (self.dim, self.num_classes), jnp.float32,
                               axes=("embed", "vocab"))
        return x[:, 0].astype(jnp.float32) @ head


@register_model("vit-b16")
def make_vit_b16(num_classes: int = 1000, dtype: str = "bfloat16"):
    return ViT(num_classes=num_classes, dtype=jnp.dtype(dtype))


@register_model("vit-tiny")
def make_vit_tiny(num_classes: int = 10, dtype: str = "float32", **kwargs):
    """Small config for tests."""
    return ViT(patch=4, dim=32, depth=2, n_heads=2, num_classes=num_classes,
               dtype=jnp.dtype(dtype), **kwargs)
