"""Hugging Face Llama checkpoint -> native transformer params.

A user of the reference serves pretrained models from standard artifact
formats; the native equivalent is importing HF Llama weights into
models/transformer.py and exporting a JAXServer/LLMServer-servable
checkpoint. Layout notes:

- torch Linear stores [out, in]; our matmuls are x @ W with W [in, out], so
  every projection transposes;
- RoPE conventions already agree (both rotate-half with the same inverse
  frequencies), so q/k need no head-permutation;
- lm_head maps to the untied output head; if the HF checkpoint ties word
  embeddings, ``tie_embeddings`` is set instead.

The parity test (tests/test_convert.py) holds this module to the canonical
implementation: a converted model must reproduce transformers' logits.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np


def config_kwargs_from_hf(hf_config: Any) -> Dict[str, Any]:
    """TransformerConfig kwargs from a transformers LlamaConfig. Refuses
    configs the native transformer cannot represent — silent acceptance
    would convert cleanly and serve wrong logits."""
    scaling = getattr(hf_config, "rope_scaling", None)
    rope_scaling = None
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type", "default"))
        if rope_type == "llama3":
            # supported natively (models/transformer._llama3_scaled_freqs,
            # parity-tested against transformers)
            required = ("factor", "low_freq_factor", "high_freq_factor",
                        "original_max_position_embeddings")
            missing = [k for k in required if k not in scaling]
            if missing:
                raise ValueError(f"llama3 rope_scaling missing keys {missing}: {scaling!r}")
            rope_scaling = {k: scaling[k] for k in required}
        elif rope_type != "default":
            raise ValueError(
                f"rope_scaling type {rope_type!r} is not supported by the "
                "native transformer (plain RoPE and llama3 scaling only); "
                "converting would silently diverge from HF at long positions"
            )
    head_dim = getattr(hf_config, "head_dim", None)
    derived = hf_config.hidden_size // hf_config.num_attention_heads
    if head_dim is not None and head_dim != derived:
        raise ValueError(
            f"explicit head_dim={head_dim} != hidden_size/num_heads={derived}; "
            "the native transformer derives head_dim from dim//n_heads"
        )
    if getattr(hf_config, "attention_bias", False) or getattr(hf_config, "mlp_bias", False):
        raise ValueError("attention/mlp biases are not supported by the native transformer")
    return {
        "vocab_size": hf_config.vocab_size,
        "dim": hf_config.hidden_size,
        "n_layers": hf_config.num_hidden_layers,
        "n_heads": hf_config.num_attention_heads,
        "n_kv_heads": getattr(hf_config, "num_key_value_heads", None)
        or hf_config.num_attention_heads,
        "ffn_dim": hf_config.intermediate_size,
        "max_seq_len": hf_config.max_position_embeddings,
        "rope_theta": getattr(hf_config, "rope_theta", 10000.0),
        "norm_eps": hf_config.rms_norm_eps,
        "tie_embeddings": bool(getattr(hf_config, "tie_word_embeddings", False)),
        **({"rope_scaling": rope_scaling} if rope_scaling else {}),
    }


def _np_dtype(name: str):
    """numpy dtype by name, including the ml_dtypes families (bfloat16,
    float8_*) that plain np.dtype() rejects."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def convert_llama_state_dict(
    state_dict: Dict[str, Any],
    n_layers: int,
    dtype: str = "float32",
    tie_embeddings: bool = False,
) -> Dict[str, Any]:
    """HF Llama state dict -> our flax param tree ({"params": ...}).
    ``tie_embeddings`` must mirror the HF config: tied checkpoints still
    carry an lm_head entry in state_dict(), but exporting it would add a
    vocab*dim param the module doesn't define (breaking sharding-spec
    alignment for tensor parallelism)."""
    np_dtype = _np_dtype(dtype)
    consumed = set()

    def t(key: str) -> np.ndarray:
        consumed.add(key)
        w = state_dict[key]
        if hasattr(w, "detach"):  # torch tensor
            w = w.detach().to("cpu").float().numpy()
        return np.asarray(w).astype(np_dtype)

    params: Dict[str, Any] = {
        "tok_embeddings": t("model.embed_tokens.weight"),  # [vocab, dim]
        "norm": {"weight": t("model.norm.weight")},
    }
    for i in range(n_layers):
        hf = f"model.layers.{i}"
        params[f"layer_{i}"] = {
            "attention": {
                "wq": t(f"{hf}.self_attn.q_proj.weight").T,
                "wk": t(f"{hf}.self_attn.k_proj.weight").T,
                "wv": t(f"{hf}.self_attn.v_proj.weight").T,
                "wo": t(f"{hf}.self_attn.o_proj.weight").T,
            },
            "ffn": {
                "w1": t(f"{hf}.mlp.gate_proj.weight").T,
                "w2": t(f"{hf}.mlp.down_proj.weight").T,
                "w3": t(f"{hf}.mlp.up_proj.weight").T,
            },
            "attention_norm": {"weight": t(f"{hf}.input_layernorm.weight")},
            "ffn_norm": {"weight": t(f"{hf}.post_attention_layernorm.weight")},
        }
    if not tie_embeddings and "lm_head.weight" in state_dict:
        params["lm_head"] = t("lm_head.weight").T  # [dim, vocab]

    # a weight we didn't map (e.g. projection biases in a fine-tune) would
    # silently change the served model — refuse instead
    def ignorable(k: str) -> bool:
        return (k.endswith(".inv_freq") or k.endswith("rotary_emb.inv_freq")
                or (tie_embeddings and k == "lm_head.weight"))

    leftover = [k for k in state_dict if k not in consumed and not ignorable(k)]
    if leftover:
        raise ValueError(
            f"unmapped weights in state dict (conversion would drop them): {leftover[:8]}"
        )
    return {"params": params}


def convert_hf_model(hf_model: Any) -> Tuple[Any, Dict[str, Any]]:
    """In-memory transformers LlamaForCausalLM -> (our module, variables)."""
    from seldon_core_tpu.models import get_model

    kwargs = config_kwargs_from_hf(hf_model.config)
    variables = convert_llama_state_dict(
        hf_model.state_dict(), n_layers=kwargs["n_layers"],
        tie_embeddings=kwargs["tie_embeddings"],
    )
    module = get_model("transformer", dtype="float32", **kwargs)
    return module, variables


def convert_checkpoint(hf_path: str, out_dir: str, dtype: str = "bfloat16") -> str:
    """HF checkpoint directory -> LLMServer/JAXServer-servable directory
    (config.json + orbax params). Loads on CPU; works fully offline against
    a local HF snapshot."""
    import torch
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_config = AutoConfig.from_pretrained(hf_path)
    model = AutoModelForCausalLM.from_pretrained(
        hf_path, torch_dtype=torch.float32, low_cpu_mem_usage=True
    )
    kwargs = config_kwargs_from_hf(hf_config)
    # weights stored in the serving dtype (bf16 halves checkpoint size vs f32)
    variables = convert_llama_state_dict(
        model.state_dict(), n_layers=kwargs["n_layers"], dtype=dtype,
        tie_embeddings=kwargs["tie_embeddings"],
    )

    from seldon_core_tpu.servers.jaxserver import export_checkpoint

    return export_checkpoint(
        out_dir,
        model="transformer",
        params=variables,
        kwargs={**kwargs, "dtype": dtype},
        input_dtype="int32",
    )
