"""Model registry: names -> Flax module constructors.

The JAX_SERVER prepackaged server resolves the ``model`` key of a checkpoint's
config.json here; users register their own architectures with
``register_model``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

_REGISTRY: Dict[str, Callable[..., Any]] = {}


def register_model(name: str, ctor: Callable[..., Any] = None):
    """Register a model constructor; usable as a decorator."""

    def _register(fn):
        _REGISTRY[name] = fn
        return fn

    if ctor is not None:
        return _register(ctor)
    return _register


def get_model(name: str, **kwargs: Any):
    if name not in _REGISTRY:
        # Import built-in model families lazily so registry import stays light.
        import seldon_core_tpu.models.mlp  # noqa: F401
        import seldon_core_tpu.models.resnet  # noqa: F401
        import seldon_core_tpu.models.transformer  # noqa: F401
        import seldon_core_tpu.models.vit  # noqa: F401
    if name not in _REGISTRY:
        raise KeyError(f"Unknown model {name!r}; registered: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)
