"""SLO-aware weighted-fair scheduling (ISSUE 15 tentpole,
runtime/scheduler.py): admission ordering, quotas, preemption, tenant
accounting, header threading, and the deterministic SLO-isolation
scenario the bench's phase L measures under wall-clock load.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from seldon_core_tpu.runtime.batcher import BatcherService, ContinuousBatcher
from seldon_core_tpu.runtime.resilience import ShedError
from seldon_core_tpu.runtime.scheduler import (
    BATCH,
    INTERACTIVE,
    PendingRequest,
    WeightedFairScheduler,
    normalize_slo_class,
)
from seldon_core_tpu.servers.llmserver import LLMServer

KW = dict(vocab_size=96, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
          ffn_dim=64, max_seq_len=96)


def make_server(**extra) -> LLMServer:
    base = dict(model="transformer", model_kwargs=KW, init_random=True,
                max_new_tokens=8, len_buckets=(16,), batch_buckets=(1,),
                temperature=0.0, eos_id=-1, seed=3)
    base.update(extra)
    s = LLMServer(**base)
    s.load()
    return s


_SHARED = {}


def shared_server() -> LLMServer:
    """One default-kwargs server for the batcher-integration tests that
    only READ server config (each private LLMServer.load() + program
    compile costs seconds against the tier-1 870s budget; sharing also
    shares the per-server jit caches across same-shape batchers). Tests
    that mutate server-level state (llm_stats TTFT drains, quota knobs)
    keep their own make_server()."""
    if "s" not in _SHARED:
        _SHARED["s"] = make_server()
    return _SHARED["s"]


def req(tenant="", cls=INTERACTIVE, deadline=None, seq_ids=(1,)):
    return PendingRequest(ids=list(seq_ids), max_new=4, fut=None,
                          tenant=tenant, slo_class=cls, deadline_t=deadline)


def drain_order(s):
    out = []
    while len(s):
        r = s.next_request()
        s.commit(r)
        out.append(r)
    return out


# ---------------------------------------------------------------------------
# pure scheduler semantics
# ---------------------------------------------------------------------------

def test_normalize_slo_class():
    assert normalize_slo_class(None) == INTERACTIVE
    assert normalize_slo_class("") == INTERACTIVE
    assert normalize_slo_class("Batch") == BATCH
    assert normalize_slo_class("throughput") == BATCH
    with pytest.raises(ValueError):
        normalize_slo_class("gold")


def test_interactive_jumps_a_batch_flood():
    s = WeightedFairScheduler()
    flood = [req("bulk", BATCH) for _ in range(12)]
    for r in flood:
        assert s.push(r)
    late = req("chat", INTERACTIVE)
    s.push(late)
    assert drain_order(s)[0] is late


def test_class_weights_hold_the_admission_ratio():
    """4:1 default — of any 10 picks with both queues backlogged, 8 are
    interactive; neither class ever starves."""
    s = WeightedFairScheduler()
    for _ in range(40):
        s.push(req("a", INTERACTIVE))
        s.push(req("b", BATCH))
    picks = [r.slo_class for r in drain_order(s)[:20]]
    assert picks.count(INTERACTIVE) == 16
    assert picks.count(BATCH) == 4
    # custom weights flip the ratio
    s2 = WeightedFairScheduler(class_weights={INTERACTIVE: 1, BATCH: 1})
    for _ in range(10):
        s2.push(req("a", INTERACTIVE))
        s2.push(req("b", BATCH))
    picks2 = [r.slo_class for r in drain_order(s2)[:10]]
    assert picks2.count(INTERACTIVE) == 5


def test_tenant_weights_within_a_class():
    s = WeightedFairScheduler(tenant_weights={"gold": 3.0, "iron": 1.0})
    for _ in range(20):
        s.push(req("gold", BATCH))
        s.push(req("iron", BATCH))
    picks = [r.tenant for r in drain_order(s)[:8]]
    assert picks.count("gold") == 6 and picks.count("iron") == 2


def test_idle_class_banks_no_credit():
    """A class that sat empty must not monopolize on return: after 100
    interactive-only admissions, a fresh batch arrival does not get 100
    back-pay picks."""
    s = WeightedFairScheduler()
    for _ in range(100):
        s.push(req("a", INTERACTIVE))
    for _ in range(100):
        s.commit(s.next_request())
    for _ in range(10):
        s.push(req("a", INTERACTIVE))
        s.push(req("b", BATCH))
    picks = [r.slo_class for r in drain_order(s)[:10]]
    assert picks.count(BATCH) <= 3  # ~1 in 5, not a monopoly


def test_deadline_edf_within_tenant():
    s = WeightedFairScheduler()
    r_none = req("t", INTERACTIVE)
    r_late = req("t", INTERACTIVE, deadline=9.0)
    r_soon = req("t", INTERACTIVE, deadline=1.0)
    for r in (r_none, r_late, r_soon):
        s.push(r)
    assert [r is x for r, x in zip(drain_order(s),
                                   (r_soon, r_late, r_none))] == [True] * 3


def test_quota_sheds_and_counts():
    s = WeightedFairScheduler(tenant_quota=2,
                              tenant_quotas={"vip": 4})
    assert all(s.push(req("noisy", BATCH)) for _ in range(2))
    assert not s.push(req("noisy", BATCH))           # over global quota
    assert all(s.push(req("vip", BATCH)) for _ in range(4))
    assert not s.push(req("vip", BATCH))             # over its override
    rows = {(r["tenant"], r["slo_class"]): r for r in s.counters()}
    assert rows[("noisy", BATCH)]["shed"] == 1
    assert rows[("vip", BATCH)]["shed"] == 1
    assert rows[("noisy", BATCH)]["queued"] == 2


def test_tenant_cardinality_bounded_by_overflow_bucket():
    """The tenant header is client-controlled: past MAX_TENANT_SERIES
    distinct tallies, unseen tenants fold into the shared overflow
    bucket, so a cardinality flood cannot grow the tally map (or the
    Prometheus series counters() feeds) without bound — and emptied
    per-tenant queues prune their heap/virtual-time map entries."""
    from seldon_core_tpu.runtime.scheduler import (MAX_TENANT_SERIES,
                                                   OVERFLOW_TENANT)

    s = WeightedFairScheduler()
    n = MAX_TENANT_SERIES + 50
    reqs = [req(f"flood-{i}", BATCH) for i in range(n)]
    for r in reqs:
        assert s.push(r)
    rows = {r["tenant"] for r in s.counters()}
    assert len(rows) <= MAX_TENANT_SERIES + 1
    assert OVERFLOW_TENANT in rows
    over = [r for r in s.counters() if r["tenant"] == OVERFLOW_TENANT]
    assert over[0]["queued"] == 50                 # the folded tail
    # known tenants (configured or seen before the cap) keep their own row
    assert "flood-0" in rows
    # draining everything prunes the per-tenant queue/vt maps entirely
    while True:
        nxt = s.next_request()
        if nxt is None:
            break
        s.commit(nxt)
    assert len(s) == 0
    assert s._queues == {} and s._tenant_vt == {}


def test_requeue_restores_position_and_marks_preempted():
    s = WeightedFairScheduler()
    first = req("t", BATCH)
    second = req("t", BATCH)
    s.push(first)
    s.push(second)
    s.commit(first)  # staged...
    s.push(first, requeue=True)  # ...then preempted back
    assert first.preempted is True
    # original seq: it re-enters AHEAD of second
    assert drain_order(s)[0] is first
    rows = {(r["tenant"], r["slo_class"]): r for r in s.counters()}
    assert rows[("t", BATCH)]["preempted"] == 1


def test_commit_by_identity_survives_interleaved_push():
    """The peek-try-commit idiom: a push landing between peek and commit
    (same loop, different coroutine) must not make commit remove the
    wrong request."""
    s = WeightedFairScheduler()
    a = req("t", INTERACTIVE)
    s.push(a)
    peeked = s.next_request()
    assert peeked is a
    b = req("t", INTERACTIVE, deadline=0.1)  # jumps ahead of a
    s.push(b)
    s.commit(a)                               # still removes a, not b
    assert s.next_request() is b


def test_drain_all_returns_everything_in_seq_order():
    s = WeightedFairScheduler()
    rs = [req("x", BATCH), req("y", INTERACTIVE), req("x", INTERACTIVE)]
    for r in rs:
        s.push(r)
    drained = s.drain_all()
    assert drained == sorted(drained, key=lambda r: r.seq)
    assert len(drained) == 3 and len(s) == 0
    assert s.depths() == {INTERACTIVE: 0, BATCH: 0}


# ---------------------------------------------------------------------------
# batcher integration
# ---------------------------------------------------------------------------

def test_interactive_preempts_staged_batch_prefill_never_active():
    """The preemption contract: with the only slot held by a STAGED
    batch-class chunked prefill, an interactive arrival preempts it
    (the batch request requeues, finishes later, is preempted at most
    once); an ACTIVE slot is never preempted."""
    s = shared_server()
    long_prompt = list(np.random.default_rng(0).integers(1, 90, size=14))

    async def go():
        b = ContinuousBatcher(s, max_slots=1, max_len=48, len_buckets=(16,),
                              layout="paged", page_size=4, prefill_chunk=2)
        batch_fut = asyncio.ensure_future(
            b.submit(long_prompt, max_new_tokens=4, tenant="bulk",
                     slo_class="batch"))
        # wait until the batch job is STAGED (slot reserved, prefilling)
        for _ in range(400):
            if b._prefill is not None:
                break
            await asyncio.sleep(0.002)
        assert b._prefill is not None
        inter = await b.submit([3, 5], max_new_tokens=3, tenant="chat",
                               slo_class="interactive")
        batch_out = await batch_fut
        ctrs = {(r["tenant"], r["slo_class"]): r
                for r in b._pending.counters()}
        await b.close()
        return inter, batch_out, ctrs

    inter, batch_out, ctrs = asyncio.run(go())
    assert len(inter) == 3
    assert len(batch_out) == 4                      # preempted, not dropped
    assert ctrs[("bulk", "batch")]["preempted"] == 1
    assert ctrs[("bulk", "batch")]["admitted"] >= 1
    assert ctrs[("chat", "interactive")]["admitted"] == 1


def test_batch_outputs_unchanged_by_preemption():
    """A preempted batch request re-prefills and generates the IDENTICAL
    tokens it would have unpreempted — preemption moves time, never
    content."""
    s = shared_server()
    prompt = [7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47]

    async def once(preempt: bool):
        b = ContinuousBatcher(s, max_slots=1, max_len=48, len_buckets=(16,),
                              layout="paged", page_size=4, prefill_chunk=2)
        fut = asyncio.ensure_future(
            b.submit(prompt, max_new_tokens=5, slo_class="batch"))
        if preempt:
            for _ in range(400):
                if b._prefill is not None:
                    break
                await asyncio.sleep(0.002)
            await b.submit([2, 4], max_new_tokens=2,
                           slo_class="interactive")
        out = await fut
        await b.close()
        return out

    plain = asyncio.run(once(False))
    preempted = asyncio.run(once(True))
    assert plain == preempted


def test_per_class_ttft_and_tenant_tokens_flow_metrics():
    """The whole flow: batcher tallies -> llm_stats -> sync_llm ->
    Prometheus text. llm_stats' TTFT drain is one-shot (scrape
    semantics), so the direct-surface asserts read the FIRST scrape and
    the /metrics text a second scrape fed by fresh requests."""
    s = make_server(continuous_batching=2)

    async def go():
        from seldon_core_tpu.metrics.registry import MetricsRegistry

        b = ContinuousBatcher(s, max_slots=2, max_len=40, len_buckets=(8,),
                              layout="paged", page_size=8)
        s._batcher_service = type("Svc", (), {"batcher": b})()
        try:
            await b.submit([5, 9], max_new_tokens=4, tenant="acme",
                           slo_class="batch")
            await b.submit([5, 9], max_new_tokens=4, tenant="chat")
            stats = s.llm_stats()
            # second round feeds the REGISTRY scrape (the first drained
            # the per-class TTFT deque, as any scrape does)
            await b.submit([5, 9], max_new_tokens=4, tenant="acme",
                           slo_class="batch")
            await b.submit([5, 9], max_new_tokens=4, tenant="chat")
            m = MetricsRegistry(deployment="d", predictor="p")
            m.sync_llm(s)
            text = m.expose().decode()
        finally:
            await b.close()
            del s._batcher_service
        return stats, text

    stats, text = asyncio.run(go())
    rows = {(r["tenant"], r["slo_class"]): r
            for r in stats["tenant_counters"]}
    assert rows[("acme", "batch")]["tokens"] == 4
    assert rows[("chat", "interactive")]["tokens"] == 4
    classes = [c for c, _ in stats["ttft_by_class"]]
    assert sorted(classes) == ["batch", "interactive"]
    assert 'seldon_tenant_tokens_total{' in text
    assert 'tenant="acme"' in text
    assert 'seldon_llm_tenant_ttft_seconds_bucket' in text
    assert 'slo_class="interactive"' in text and 'slo_class="batch"' in text


def test_quota_shed_is_503_with_retry_after():
    s = make_server(tenant_quota=1)

    async def go():
        b = ContinuousBatcher(s, max_slots=1, max_len=40, len_buckets=(8,),
                              layout="paged", page_size=8)
        futs = [asyncio.ensure_future(
            b.submit([5, 9], max_new_tokens=4, tenant="noisy",
                     slo_class="batch")) for _ in range(5)]
        done = await asyncio.gather(*futs, return_exceptions=True)
        ctrs = {(r["tenant"], r["slo_class"]): r
                for r in b._pending.counters()}
        await b.close()
        return done, ctrs

    done, ctrs = asyncio.run(go())
    sheds = [d for d in done if isinstance(d, ShedError)]
    assert sheds, "over-quota submits must shed"
    assert all(d.status_code == 503 and d.retry_after_s >= 1.0
               for d in sheds)
    assert ctrs[("noisy", "batch")]["shed"] == len(sheds)


def test_scaling_snapshot_reports_queue_by_class():
    from seldon_core_tpu.observability.timeline import scaling_snapshot

    s = shared_server()

    async def go():
        b = ContinuousBatcher(s, max_slots=1, max_len=40, len_buckets=(8,),
                              layout="paged", page_size=8)
        for r in [PendingRequest(ids=[1], max_new=1, fut=None,
                                 slo_class=cls)
                  for cls in (INTERACTIVE, INTERACTIVE, BATCH)]:
            b._pending.push(r)
        snap = scaling_snapshot(object(), batcher=b)
        for r in b._pending.drain_all():
            pass
        await b.close()
        return snap

    snap = asyncio.run(go())
    assert snap["queue_by_class"] == {INTERACTIVE: 2, BATCH: 1}
    assert snap["queue_depth"] == 3


def test_flight_timeline_carries_tenant_tags():
    s = shared_server()

    async def go():
        b = ContinuousBatcher(s, max_slots=1, max_len=40, len_buckets=(8,),
                              layout="paged", page_size=8, tracing=True)
        await b.submit([5, 9, 2], max_new_tokens=3, tenant="acme",
                       slo_class="batch")
        await b.submit([5, 9, 2], max_new_tokens=3)
        tls = b._flight.timelines(4)
        await b.close()
        return tls

    tls = asyncio.run(go())
    tagged = [t for t in tls if "request_tags" in t]
    assert len(tagged) == 1
    assert tagged[0]["request_tags"] == {
        "tenant": "acme", "slo_class": "batch", "adapter_id": 0}


# ---------------------------------------------------------------------------
# transport threading (headers -> submit)
# ---------------------------------------------------------------------------

def test_rest_headers_thread_into_scheduler():
    from aiohttp.test_utils import TestClient, TestServer

    from seldon_core_tpu.transport.rest import make_component_app

    s = make_server(continuous_batching=2, tenant_quota=0)
    app = make_component_app(s)

    async def go():
        async with TestClient(TestServer(app)) as client:
            resp = await client.post(
                "/v1/generate",
                json={"prompt": [5, 9, 2], "max_new_tokens": 3},
                headers={"Seldon-Tenant": "acme",
                         "Seldon-SLO-Class": "batch"})
            assert resp.status == 200
            body = await resp.json()
            assert len(body["tokens"]) == 3
            # unknown class -> 400, not a silent default
            resp = await client.post(
                "/v1/generate", json={"prompt": [5], "max_new_tokens": 2},
                headers={"Seldon-SLO-Class": "gold"})
            assert resp.status == 400
            # ...including on the NON-batched branch (per-request
            # temperature routes around the batcher and its validation)
            resp = await client.post(
                "/v1/generate",
                json={"prompt": [5], "max_new_tokens": 2,
                      "temperature": 0.7},
                headers={"Seldon-SLO-Class": "gold"})
            assert resp.status == 400
            # unknown adapter -> 400
            resp = await client.post(
                "/v1/generate",
                json={"prompt": [5], "max_new_tokens": 2,
                      "adapter": "ghost"})
            assert resp.status == 400
        svc = s._batcher_service
        rows = {(r["tenant"], r["slo_class"]): r
                for r in svc.batcher._pending.counters()}
        assert rows[("acme", "batch")]["admitted"] == 1
        svc.close()

    asyncio.run(go())


def test_slo_isolation_under_deterministic_load():
    """The SLO-isolation acceptance shape, deterministically: a
    batch-class tenant floods a 2-slot batcher; interactive requests
    submitted after the flood still admit within the first
    weighted-fair wave (their queue position, not wall clock, is the
    deterministic proxy phase L measures as TTFT p95), and the batch
    tenant still finishes everything (no starvation)."""
    s = shared_server()

    async def go():
        b = ContinuousBatcher(s, max_slots=2, max_len=40, len_buckets=(8,),
                              layout="paged", page_size=8)
        flood = [asyncio.ensure_future(
            b.submit([9, 9, 9], max_new_tokens=6, tenant="bulk",
                     slo_class="batch")) for _ in range(8)]
        await asyncio.sleep(0)  # flood queued first
        inter = [asyncio.ensure_future(
            b.submit([3, 5, 7], max_new_tokens=3, tenant="chat",
                     slo_class="interactive")) for _ in range(2)]
        inter_out = await asyncio.gather(*inter)
        # when the LAST interactive token lands, most of the flood must
        # still be queued/in-flight — interactive did not wait it out
        pending_batch = sum(1 for f in flood if not f.done())
        flood_out = await asyncio.gather(*flood)
        ctrs = {(r["tenant"], r["slo_class"]): r
                for r in b._pending.counters()}
        await b.close()
        return inter_out, flood_out, pending_batch, ctrs

    inter_out, flood_out, pending_batch, ctrs = asyncio.run(go())
    assert all(len(t) == 3 for t in inter_out)
    assert all(len(t) == 6 for t in flood_out)      # zero starvation
    assert pending_batch >= 4, (
        "interactive completed while most of the batch flood was still "
        "queued — isolation held")
    assert ctrs[("chat", "interactive")]["admitted"] == 2
    assert ctrs[("bulk", "batch")]["admitted"] == 8
