"""KubectlCluster against a faked kubectl binary (VERDICT r2 item 9): the
backend must classify created/updated/unchanged/error from exit codes and
JSON output only — never from kubectl's human messages."""

from __future__ import annotations

import json
import os
import stat
import textwrap

import pytest

from seldon_core_tpu.controlplane.operator import KubectlCluster

MANIFEST = {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "m", "namespace": "ns"}}


def fake_kubectl(tmp_path, script_body: str) -> str:
    """A stand-in kubectl: python script dispatching on argv."""
    path = tmp_path / "kubectl"
    path.write_text("#!/usr/bin/env python3\n" + textwrap.dedent(script_body))
    path.chmod(path.stat().st_mode | stat.S_IEXEC)
    return str(path)


def test_apply_created(tmp_path):
    k = fake_kubectl(tmp_path, """
        import json, sys
        if sys.argv[1] == "get":
            sys.exit(0)  # --ignore-not-found: absent = rc 0, no output
        if sys.argv[1] == "apply":
            print(json.dumps({"metadata": {"resourceVersion": "101"}}))
            sys.exit(0)
        sys.exit(2)
    """)
    assert KubectlCluster(k).apply(MANIFEST) == "created"


def test_apply_updated(tmp_path):
    k = fake_kubectl(tmp_path, """
        import json, sys
        if sys.argv[1] == "get":
            print("41", end="")
            sys.exit(0)
        if sys.argv[1] == "apply":
            print(json.dumps({"metadata": {"resourceVersion": "42"}}))
            sys.exit(0)
        sys.exit(2)
    """)
    assert KubectlCluster(k).apply(MANIFEST) == "updated"


def test_apply_unchanged(tmp_path):
    k = fake_kubectl(tmp_path, """
        import json, sys
        if sys.argv[1] == "get":
            print("41", end="")
            sys.exit(0)
        if sys.argv[1] == "apply":
            print(json.dumps({"metadata": {"resourceVersion": "41"}}))
            sys.exit(0)
        sys.exit(2)
    """)
    assert KubectlCluster(k).apply(MANIFEST) == "unchanged"


def test_apply_error_raises_with_stderr(tmp_path):
    k = fake_kubectl(tmp_path, """
        import sys
        if sys.argv[1] == "get":
            sys.exit(0)
        sys.stderr.write("the server rejected it")
        sys.exit(1)
    """)
    with pytest.raises(RuntimeError, match="rejected"):
        KubectlCluster(k).apply(MANIFEST)


def test_apply_non_json_output_raises(tmp_path):
    k = fake_kubectl(tmp_path, """
        import sys
        if sys.argv[1] == "get":
            sys.exit(0)
        print("deployment.apps/m created")  # human text, not -o json
        sys.exit(0)
    """)
    with pytest.raises(RuntimeError, match="non-JSON"):
        KubectlCluster(k).apply(MANIFEST)


def test_delete_found_and_not_found_and_error(tmp_path):
    k = fake_kubectl(tmp_path, """
        import sys
        name = sys.argv[3]  # argv: kubectl delete <kind> <name> ...
        if name == "gone":
            sys.exit(0)  # --ignore-not-found: rc 0, no output
        if name == "broken":
            sys.exit(1)
        print("deployment.apps/" + name)
        sys.exit(0)
    """)
    c = KubectlCluster(k)
    assert c.delete("Deployment", "ns", "exists") is True
    assert c.delete("Deployment", "ns", "gone") is False
    assert c.delete("Deployment", "ns", "broken") is False


def test_list_merges_and_survives_missing_istio_crd(tmp_path):
    k = fake_kubectl(tmp_path, """
        import json, sys
        kinds = sys.argv[2]
        if "virtualservices" in kinds:
            sys.stderr.write("the server doesn't have a resource type")
            sys.exit(1)
        assert "-l" in sys.argv and sys.argv[sys.argv.index("-l") + 1] == "owner=me"
        print(json.dumps({"items": [{"kind": "Deployment",
                                     "metadata": {"name": "d1"}}]}))
        sys.exit(0)
    """)
    items = KubectlCluster(k).list(label="owner", value="me")
    assert [i["metadata"]["name"] for i in items] == ["d1"]


def test_apply_transient_get_error_raises_not_created(tmp_path):
    """An apiserver timeout on the pre-apply get must surface as an error,
    never be classified as 'the object is absent' -> 'created'."""
    k = fake_kubectl(tmp_path, """
        import sys
        if sys.argv[1] == "get":
            sys.stderr.write("Unable to connect to the server: timeout")
            sys.exit(1)
        sys.exit(0)
    """)
    with pytest.raises(RuntimeError, match="kubectl get failed"):
        KubectlCluster(k).apply(MANIFEST)


def test_get_omits_namespace_flag_when_manifest_has_none(tmp_path):
    k = fake_kubectl(tmp_path, """
        import json, sys
        if sys.argv[1] == "get":
            assert "-n" not in sys.argv, sys.argv
            sys.exit(0)
        if sys.argv[1] == "apply":
            print(json.dumps({"metadata": {"resourceVersion": "1"}}))
            sys.exit(0)
        sys.exit(2)
    """)
    m = {"apiVersion": "v1", "kind": "Service", "metadata": {"name": "s"}}
    assert KubectlCluster(k).apply(m) == "created"
