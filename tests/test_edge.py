"""Native edge server (native/edge.cc): response parity with the Python
engine, error paths, drain, metrics, and the ring-fallback mode.

The edge is the compiled orchestrator hot path (reference parity: the Java
engine's in-process stub units, `engine/.../SimpleModelUnit.java:33-64`,
behind `RestClientController.java:76-245`); these tests hold it to the Python
engine's exact response contract.
"""

import json
import os
import socket
import subprocess
import time
import urllib.request

import pytest

from seldon_core_tpu.contracts.graph import PredictorSpec
from seldon_core_tpu.contracts.payload import SeldonMessage
from seldon_core_tpu.runtime.edgeprogram import (
    EDGE_BINARY,
    build_edge_binaries,
    compile_edge_program,
    write_program,
)
from seldon_core_tpu.runtime.engine import GraphEngine

pytestmark = pytest.mark.skipif(not build_edge_binaries(), reason="no C++ toolchain")

SINGLE = {"name": "p", "graph": {"name": "m", "type": "MODEL", "implementation": "SIMPLE_MODEL"}}
AB_FORCED = {
    "name": "p",
    "graph": {
        "name": "ab", "type": "ROUTER", "implementation": "RANDOM_ABTEST",
        "parameters": [{"name": "ratioA", "value": "1.0", "type": "FLOAT"}],
        "children": [
            {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
            {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        ],
    },
}
COMBINER = {
    "name": "p",
    "graph": {
        "name": "c", "type": "COMBINER", "implementation": "AVERAGE_COMBINER",
        "children": [
            {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
            {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        ],
    },
}
CHAIN = {
    "name": "p",
    "graph": {
        "name": "m1", "type": "MODEL", "implementation": "SIMPLE_MODEL",
        "children": [{"name": "m2", "type": "MODEL", "implementation": "SIMPLE_MODEL"}],
    },
}

REQUESTS = [
    {"data": {"ndarray": [[1.0, 2.0], [3.0, 4.0]]}},
    {"data": {"ndarray": [1.0, 2.0]}},
    {"data": {"tensor": {"shape": [2, 2], "values": [1.0, 2.0, 3.0, 4.0]}}},
    {"strData": "hello"},
    {"binData": "aGVsbG8="},
    {
        "meta": {"puid": "PUID123", "tags": {"t1": "v", "n": 5}, "routing": {"x": 7},
                 "requestPath": {"x": "X"},
                 "metrics": [{"key": "k", "type": "GAUGE", "value": 1.5}]},
        "data": {"ndarray": [[1.0]]},
    },
    {"data": {"names": ["f1", "f2"], "ndarray": [[1.0, 2.0]]}},
]


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def post(port, path, body, timeout=10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if isinstance(body, dict) else body,
        method="POST",
    )

    def decode(raw):
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return raw

    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, decode(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, decode(e.read())


def get(port, path, timeout=10.0):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def wait_ready(port, proc, deadline_s=30.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        assert proc.poll() is None, "edge process died"
        try:
            status, _ = get(port, "/live", timeout=1.0)
            if status == 200:
                return
        except Exception:
            time.sleep(0.05)
    raise AssertionError("edge never became live")


@pytest.fixture(scope="module")
def edge(tmp_path_factory):
    """One edge process per graph, torn down at module end."""
    procs = {}
    tmp = tmp_path_factory.mktemp("edge")

    def start(key, spec_dict):
        if key in procs:
            return procs[key][1]
        spec = PredictorSpec.from_dict(spec_dict)
        program = compile_edge_program(spec)
        assert program is not None
        path = write_program(program, str(tmp / f"{key}.json"))
        port = free_port()
        proc = subprocess.Popen(
            [EDGE_BINARY, "--program", path, "--port", str(port)],
            stderr=subprocess.DEVNULL,
        )
        wait_ready(port, proc)
        procs[key] = (proc, port)
        return port

    yield start
    for proc, _ in procs.values():
        proc.terminate()
        proc.wait(timeout=10)


def strip_puid(d):
    d = json.loads(json.dumps(d))
    if "meta" in d:
        d["meta"].pop("puid", None)
    return d


@pytest.mark.parametrize("graph_key,spec", [
    ("single", SINGLE), ("ab", AB_FORCED), ("comb", COMBINER), ("chain", CHAIN),
])
@pytest.mark.parametrize("req_idx", range(len(REQUESTS)))
def test_parity_with_python_engine(edge, graph_key, spec, req_idx):
    """Edge responses must match the Python engine response-for-response."""
    from seldon_core_tpu.contracts.payload import SeldonError

    req = REQUESTS[req_idx]
    engine = GraphEngine(PredictorSpec.from_dict(spec))
    port = edge(graph_key, spec)
    try:
        expected = engine.predict_sync(SeldonMessage.from_dict(json.loads(json.dumps(req))))
    except Exception as e:
        # Python raised: the edge must report the same failure class
        # (SeldonError keeps its status code; anything else is a 500)
        want = e.status_code if isinstance(e, SeldonError) else 500
        status, got = post(port, "/api/v0.1/predictions", req)
        assert status == want
        assert got["status"]["status"] == "FAILURE"
        return
    status, got = post(port, "/api/v0.1/predictions", req)
    assert status == 200
    assert strip_puid(got) == strip_puid(expected.to_dict())
    if (req.get("meta") or {}).get("puid"):
        assert got["meta"]["puid"] == req["meta"]["puid"]
    else:
        assert len(got["meta"]["puid"]) == 32


def test_error_paths(edge):
    port = edge("single", SINGLE)
    status, body = post(port, "/api/v0.1/predictions", b"not json")
    assert status == 400 and body["status"]["reason"] == "MICROSERVICE_BAD_DATA"
    status, body = post(port, "/api/v0.1/predictions", {})
    assert status == 400 and "Unknown data type" in body["status"]["info"]
    status, body = post(
        port, "/api/v0.1/predictions", {"data": {"tensor": {"shape": [2, 2], "values": [1.0]}}}
    )
    assert status == 400 and "tensor values do not fit shape" in body["status"]["info"]
    status, body = post(port, "/api/v0.1/predictions", {"jsonData": {"a": 1}})
    assert status == 500


def test_feedback_and_metrics(edge):
    port = edge("single", SINGLE)
    status, body = post(
        port, "/api/v0.1/feedback",
        {"request": {"data": {"ndarray": [[1.0]]}}, "response": {"meta": {}}, "reward": 0.5},
    )
    assert status == 200 and body == {"meta": {}}
    post(port, "/api/v0.1/predictions", {"data": {"ndarray": [[1.0]]}})
    status, text = get(port, "/metrics")
    text = text.decode()
    assert status == 200
    assert "seldon_api_executor_server_requests_total" in text
    assert "seldon_api_model_feedback_total" in text
    assert "mycounter_total" in text


def test_pause_drain(edge):
    port = edge("single", SINGLE)
    try:
        assert get(port, "/ready")[0] == 200
        assert get(port, "/ping")[1] == b"pong"
        status, _ = post(port, "/pause", {})
        assert status == 200
        assert get(port, "/ready")[0] == 503
        status, body = post(port, "/api/v0.1/predictions", {"data": {"ndarray": [[1.0]]}})
        assert status == 503 and body["status"]["info"] == "paused"
    finally:
        post(port, "/unpause", {})
    assert get(port, "/ready")[0] == 200
    status, _ = post(port, "/api/v0.1/predictions", {"data": {"ndarray": [[1.0]]}})
    assert status == 200


def test_keepalive_many_requests(edge):
    """One connection, many sequential requests (keep-alive reuse)."""
    import http.client

    port = edge("single", SINGLE)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    body = json.dumps({"data": {"ndarray": [[1.0]]}})
    puids = set()
    for _ in range(200):
        conn.request("POST", "/predict", body)
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200
        puids.add(out["meta"]["puid"])
    conn.close()
    assert len(puids) == 200  # unique puid per request


def test_fallback_mode_serves_python_engine(tmp_path):
    """A graph pinned off the native plane (python_routing=true — every
    seeded router is native now, so the pin is the remaining fallback
    vehicle) is served by the Python engine behind the shared-memory ring,
    edge as frontend."""
    spec = {
        "name": "p",
        "graph": {
            "name": "eg", "type": "ROUTER", "implementation": "THOMPSON_SAMPLING",
            "parameters": [{"name": "n_branches", "value": "2", "type": "INT"},
                           {"name": "seed", "value": "7", "type": "INT"},
                           {"name": "python_routing", "value": "true", "type": "BOOL"}],
            "children": [
                {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
            ],
        },
    }
    assert compile_edge_program(PredictorSpec.from_dict(spec)) is None
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))
    port = free_port()
    env = dict(os.environ)
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-m", "seldon_core_tpu.transport.cli", "edge",
         "--spec", str(spec_path), "--port", str(port)],
        env=env, stderr=subprocess.DEVNULL,
    )
    try:
        wait_ready(port, proc, deadline_s=60)
        status, got = post(port, "/api/v0.1/predictions", {"data": {"ndarray": [[1.0]]}}, timeout=30)
        assert status == 200
        assert got["meta"]["routing"]["eg"] in (0, 1)
        assert got["meta"]["tags"]["bandit"] == "ThompsonSampling"
        assert got["data"]["ndarray"][0] == pytest.approx([0.1, 0.9, 0.5], rel=1e-6)
    finally:
        proc.terminate()
        proc.wait(timeout=15)


@pytest.mark.parametrize("graph_key,spec", [
    ("single", SINGLE), ("ab", AB_FORCED), ("comb", COMBINER), ("chain", CHAIN),
])
def test_parity_fuzz_random_payloads(edge, graph_key, spec):
    """Randomized parity sweep: 48 generated payloads per topology —
    random tensor/ndarray shapes (1-D, 2-D, singletons), extreme values,
    strData/binData/jsonData, optional meta — must produce byte-identical
    success responses (minus puid) from the C++ edge and the Python engine,
    and matching status codes on failures."""
    import base64 as b64
    import zlib

    import numpy as np

    from seldon_core_tpu.contracts.payload import SeldonError

    # crc32, not hash(): str hashes are salted per process, which would
    # make a failing fuzz case unreproducible
    rng = np.random.default_rng(zlib.crc32(graph_key.encode()))
    engine = GraphEngine(PredictorSpec.from_dict(spec))
    port = edge(graph_key, spec)

    def gen_request(i):
        kind = i % 6
        if kind == 0:  # tensor, random shape
            rows = int(rng.integers(1, 5))
            cols = int(rng.integers(1, 6))
            vals = rng.normal(0, 10.0 ** float(rng.integers(-3, 4)), size=rows * cols)
            return {"data": {"tensor": {"shape": [rows, cols],
                                        "values": [float(v) for v in vals]}}}
        if kind == 1:  # ndarray
            rows = int(rng.integers(1, 4))
            cols = int(rng.integers(1, 4))
            return {"data": {"ndarray": rng.uniform(-1e6, 1e6, (rows, cols)).tolist()}}
        if kind == 2:  # 1-D tensor
            n = int(rng.integers(1, 8))
            return {"data": {"tensor": {"shape": [n], "values": [float(v) for v in rng.normal(size=n)]}}}
        if kind == 3:
            return {"strData": "".join(chr(int(c)) for c in rng.integers(32, 127, 16))}
        if kind == 4:
            return {"jsonData": {"k": int(rng.integers(0, 100)), "v": [1, 2.5, "s"]}}
        raw = bytes(int(b) for b in rng.integers(0, 256, int(rng.integers(1, 24))))
        return {"binData": b64.b64encode(raw).decode()}

    for i in range(48):
        req = gen_request(i)
        if rng.random() < 0.3:
            req["meta"] = {"puid": f"fuzz{i:04d}", "tags": {"fuzz": True}}
        try:
            expected = engine.predict_sync(
                SeldonMessage.from_dict(json.loads(json.dumps(req))))
            want_status, want_body = 200, strip_puid(expected.to_dict())
        except SeldonError as e:
            want_status, want_body = e.status_code, None
        except Exception:
            want_status, want_body = 500, None
        status, got = post(port, "/api/v0.1/predictions", req)
        assert status == want_status, (i, req, status, got)
        if want_body is not None:
            assert strip_puid(got) == want_body, (i, req)
        else:
            assert got["status"]["status"] == "FAILURE", (i, req)


# ---------------------------------------------------------------------------
# Native bandit routers (EPSILON_GREEDY / THOMPSON_SAMPLING in edge.cc)
# ---------------------------------------------------------------------------

EG_EXPLOIT = {
    "name": "p",
    "graph": {
        "name": "eg", "type": "ROUTER", "implementation": "EPSILON_GREEDY",
        "parameters": [
            {"name": "n_branches", "value": "2", "type": "INT"},
            {"name": "epsilon", "value": "0.0", "type": "FLOAT"},
            {"name": "best_branch", "value": "1", "type": "INT"},
        ],
        "children": [
            {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
            {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        ],
    },
}
TS_SPEC = {
    "name": "p",
    "graph": {
        "name": "ts", "type": "ROUTER", "implementation": "THOMPSON_SAMPLING",
        "parameters": [{"name": "n_branches", "value": "2", "type": "INT"}],
        "children": [
            {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
            {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        ],
    },
}


def test_bandit_compiles_native():
    for spec in (EG_EXPLOIT, TS_SPEC):
        prog = compile_edge_program(PredictorSpec.from_dict(spec))
        assert prog is not None and prog["native"]
    # every seeded bandit compiles NATIVE: the edge replays numpy's PCG64 +
    # Lemire integers (epsilon-greedy) and the ziggurat gamma/beta chain
    # (Thompson) bit-exactly — native/np_rng.h
    seeded = json.loads(json.dumps(EG_EXPLOIT))
    seeded["graph"]["parameters"].append({"name": "seed", "value": "3", "type": "INT"})
    prog = compile_edge_program(PredictorSpec.from_dict(seeded))
    assert prog is not None and prog["native"]
    assert prog["units"][prog["root"]]["seed"] == 3
    seeded_ts = json.loads(json.dumps(TS_SPEC))
    seeded_ts["graph"]["parameters"].append({"name": "seed", "value": "3", "type": "INT"})
    prog = compile_edge_program(PredictorSpec.from_dict(seeded_ts))
    assert prog is not None and prog["native"]
    assert prog["units"][prog["root"]]["seed"] == 3
    # seeds outside [0, 2^53) keep Python semantics (program JSON is doubles)
    big = json.loads(json.dumps(EG_EXPLOIT))
    big["graph"]["parameters"].append({"name": "seed", "value": str(2**60), "type": "INT"})
    assert compile_edge_program(PredictorSpec.from_dict(big)) is None
    # invalid params -> fallback so the Python engine raises the build error
    bad = json.loads(json.dumps(EG_EXPLOIT))
    bad["graph"]["parameters"][1] = {"name": "epsilon", "value": "1.5", "type": "FLOAT"}
    assert compile_edge_program(PredictorSpec.from_dict(bad)) is None


def test_numpy_parity_probe_gates_seeded_native(monkeypatch):
    """Seeded-native routing only enables when the installed numpy replays
    the recorded 2.0.2 streams bit-exactly (ADVICE r5: an unpinned numpy
    that changes distributions.c must not silently desync planes)."""
    from seldon_core_tpu.runtime import edgeprogram as ep

    # this image carries the known-good numpy: probe passes (and caches)
    monkeypatch.setattr(ep, "_numpy_parity_cache", None)
    assert ep.numpy_stream_parity_ok() is True

    # simulate a drifted numpy: seeded graphs fall back, unseeded stay native
    monkeypatch.setattr(ep, "_numpy_parity_cache", None)
    monkeypatch.setattr(ep, "_NUMPY_PARITY_INTEGERS", (1, 2, 3, 4))
    assert ep.numpy_stream_parity_ok() is False
    seeded_ts = json.loads(json.dumps(TS_SPEC))
    seeded_ts["graph"]["parameters"].append({"name": "seed", "value": "3", "type": "INT"})
    assert compile_edge_program(PredictorSpec.from_dict(seeded_ts)) is None
    assert compile_edge_program(PredictorSpec.from_dict(TS_SPEC)) is not None
    monkeypatch.setattr(ep, "_numpy_parity_cache", None)  # drop the cached False


def test_native_epsilon_greedy_parity_deterministic(edge):
    """epsilon=0 makes the route deterministic: native edge response must be
    byte-identical (minus puid) to the Python engine's, including the bandit
    tags fragment, both before and after an identical feedback sequence."""
    engine = GraphEngine(PredictorSpec.from_dict(EG_EXPLOIT))
    port = edge("eg_exploit", EG_EXPLOIT)
    req = {"data": {"ndarray": [[1.0, 2.0]]}}

    expected = engine.predict_sync(SeldonMessage.from_dict(json.loads(json.dumps(req))))
    status, got = post(port, "/api/v0.1/predictions", req)
    assert status == 200
    assert strip_puid(got) == strip_puid(expected.to_dict())
    assert got["meta"]["routing"]["eg"] == 1
    assert got["meta"]["tags"]["bandit"] == "EpsilonGreedy"

    # identical feedback stream on both sides: branch 0 pays 1.0 (x3),
    # branch 1 pays 0.25 (x1) -> exploit flips to branch 0
    import asyncio

    from seldon_core_tpu.contracts.payload import Feedback

    fbs = [({"eg": 0}, 1.0)] * 3 + [({"eg": 1}, 0.25)]
    for routing, reward in fbs:
        fb = {"request": req, "response": {"meta": {"routing": routing}}, "reward": reward}
        status, body = post(port, "/api/v0.1/feedback", fb)
        assert status == 200 and body == {"meta": {}}
        asyncio.run(engine.send_feedback(Feedback.from_dict(json.loads(json.dumps(fb)))))

    expected = engine.predict_sync(SeldonMessage.from_dict(json.loads(json.dumps(req))))
    status, got = post(port, "/api/v0.1/predictions", req)
    assert status == 200
    assert strip_puid(got) == strip_puid(expected.to_dict())
    assert got["meta"]["routing"]["eg"] == 0
    assert got["meta"]["tags"]["branch_means"] == [1.0, 0.25]

    # bad feedback routing -> 400 BAD_ROUTING, matching the engine's raise
    bad = {"request": req, "response": {"meta": {"routing": {"eg": 5}}}, "reward": 1.0}
    status, body = post(port, "/api/v0.1/feedback", bad)
    assert status == 400 and body["status"]["reason"] == "BAD_ROUTING"

    # learned state surfaces on /metrics
    status, text = get(port, "/metrics")
    assert b'bandit_branch_mean_reward{router="eg",branch="0"} 1.0' in text
    assert b'bandit_branch_pulls_total{router="eg",branch="1"} 1' in text


def test_feedback_routing_value_coercion_parity(edge):
    """Meta.from_dict applies int(v) to routing values, so the Python engine
    accepts numeric strings and booleans; the native edge must coerce the
    same set and 400 the same set (non-integer strings, null, arrays)."""
    import asyncio

    from seldon_core_tpu.contracts.payload import Feedback

    engine = GraphEngine(PredictorSpec.from_dict(EG_EXPLOIT))
    port = edge("eg_exploit", EG_EXPLOIT)
    req = {"data": {"ndarray": [[1.0, 2.0]]}}

    # "2000000000" fits int; 1e300 / "9999999999" clamp to INT_MAX natively
    # and int() fine in python — both sides then 400 BAD_ROUTING (branch
    # outside children), asserted below via the out-of-range check
    for routing_val in ("1", " 1 ", "+1", True, False, 1.9):
        fb = {"request": req, "response": {"meta": {"routing": {"eg": routing_val}}},
              "reward": 1.0}
        # python engine accepts (int(v) succeeds)
        asyncio.run(engine.send_feedback(Feedback.from_dict(json.loads(json.dumps(fb)))))
        status, body = post(port, "/api/v0.1/feedback", fb)
        assert status == 200 and body == {"meta": {}}, (routing_val, body)

    for routing_val in ("1.5", "x", None, [1], {"a": 1}, "", "1__0", "_1", "1_"):
        fb = {"request": req, "response": {"meta": {"routing": {"eg": routing_val}}},
              "reward": 1.0}
        with pytest.raises(Exception):
            asyncio.run(engine.send_feedback(
                Feedback.from_dict(json.loads(json.dumps(fb)))))
        status, body = post(port, "/api/v0.1/feedback", fb)
        assert status == 400, (routing_val, body)

    # int()-acceptable but out of any branch range: both sides 400 BAD_ROUTING
    # (1e300 would be UB in a raw double->int cast; the edge clamps instead)
    for routing_val in (1e300, -1e300, "2000000000", "9999999999999", 2**31, "1_0"):
        fb = {"request": req, "response": {"meta": {"routing": {"eg": routing_val}}},
              "reward": 1.0}
        with pytest.raises(Exception):
            asyncio.run(engine.send_feedback(
                Feedback.from_dict(json.loads(json.dumps(fb)))))
        status, body = post(port, "/api/v0.1/feedback", fb)
        assert status == 400 and body["status"]["reason"] == "BAD_ROUTING", \
            (routing_val, body)


def test_native_thompson_learns(edge):
    """Unseeded Thompson: route is stochastic, so assert distributional
    behavior — after heavy one-sided feedback the posterior argmax must
    overwhelmingly pick the rewarded branch."""
    port = edge("ts", TS_SPEC)
    req = {"data": {"ndarray": [[1.0]]}}
    for _ in range(40):
        fb = {"request": req, "response": {"meta": {"routing": {"ts": 1}}}, "reward": 1.0}
        assert post(port, "/api/v0.1/feedback", fb)[0] == 200
    for _ in range(10):
        fb = {"request": req, "response": {"meta": {"routing": {"ts": 0}}}, "reward": 0.0}
        assert post(port, "/api/v0.1/feedback", fb)[0] == 200
    picks = [post(port, "/api/v0.1/predictions", req)[1]["meta"]["routing"]["ts"]
             for _ in range(30)]
    # Beta(41,1) vs Beta(1,11): P(branch 1) > 0.999 per draw
    assert sum(picks) >= 28
    status, got = post(port, "/api/v0.1/predictions", req)
    assert got["meta"]["tags"]["bandit"] == "ThompsonSampling"


def test_bandit_feedback_hardening(edge):
    """Review regressions: negative routing branches and non-integer routing
    values must be rejected (the engine raises), never index children or
    train an arm."""
    port = edge("eg_exploit", EG_EXPLOIT)
    req = {"data": {"ndarray": [[1.0]]}}
    for bad_branch in (-2, -100):
        fb = {"request": req, "response": {"meta": {"routing": {"eg": bad_branch}}},
              "reward": 1.0}
        status, body = post(port, "/api/v0.1/feedback", fb)
        assert status == 400 and body["status"]["reason"] == "BAD_ROUTING", bad_branch
    fb = {"request": req, "response": {"meta": {"routing": {"eg": "oops"}}}, "reward": 1.0}
    status, body = post(port, "/api/v0.1/feedback", fb)
    assert status == 400 and body["status"]["reason"] == "MICROSERVICE_BAD_DATA"
    # -1 (explicit fan-out) stays accepted, matching engine._feedback
    fb = {"request": req, "response": {"meta": {"routing": {"eg": -1}}}, "reward": 1.0}
    assert post(port, "/api/v0.1/feedback", fb)[0] == 200


def test_bandit_foreign_params_stay_native():
    """A foreign parameter the component would ignore must not cost native
    execution (review finding: cross-kind validation forced ring fallback)."""
    spec = json.loads(json.dumps(EG_EXPLOIT))
    spec["graph"]["parameters"].append({"name": "alpha", "value": "0.0", "type": "FLOAT"})
    prog = compile_edge_program(PredictorSpec.from_dict(spec))
    assert prog is not None and prog["native"]
    ts = json.loads(json.dumps(TS_SPEC))
    ts["graph"]["parameters"].append({"name": "epsilon", "value": "1.5", "type": "FLOAT"})
    prog = compile_edge_program(PredictorSpec.from_dict(ts))
    assert prog is not None and prog["native"]


def _seeded_spec(impl, name, seed, n_branches=3, extra=()):
    children = [{"name": f"m{i}", "type": "MODEL", "implementation": "SIMPLE_MODEL"}
                for i in range(n_branches)]
    return {"name": "p", "graph": {
        "name": name, "type": "ROUTER", "implementation": impl,
        "parameters": [{"name": "n_branches", "value": str(n_branches), "type": "INT"},
                       {"name": "seed", "value": str(seed), "type": "INT"},
                       *extra],
        "children": children}}


@pytest.mark.parametrize("impl,name,extra", [
    ("EPSILON_GREEDY", "eg", ({"name": "epsilon", "value": "0.6", "type": "FLOAT"},)),
    ("RANDOM_ABTEST", "ab", ()),
    ("THOMPSON_SAMPLING", "ts", ()),
])
def test_seeded_router_native_routing_parity(edge, impl, name, extra):
    """A SEEDED router graph served natively must reproduce the Python
    engine's routing decisions request-for-request — the edge replays
    numpy's PCG64 (epsilon-greedy), CPython's MT19937 (AB-test), and
    Generator.beta's ziggurat gamma chain (Thompson) streams bit-exactly,
    including through feedback-driven state changes."""
    import asyncio as aio

    from seldon_core_tpu.contracts.payload import Feedback
    from seldon_core_tpu.runtime.engine import GraphEngine

    spec = _seeded_spec(impl, name, seed=11, extra=list(extra))
    prog = compile_edge_program(PredictorSpec.from_dict(spec))
    assert prog is not None and prog["native"], impl
    port = edge(f"seeded_{name}", spec)
    oracle = GraphEngine(PredictorSpec.from_dict(spec))
    req = {"data": {"ndarray": [[1.0]]}}

    def oracle_route():
        out = oracle.predict_sync(SeldonMessage.from_dict(json.loads(json.dumps(req))))
        return out.to_dict()["meta"]["routing"][name]

    def edge_route():
        status, body = post(port, "/api/v0.1/predictions", req)
        assert status == 200
        return body["meta"]["routing"][name]

    seq_native = [edge_route() for _ in range(40)]
    seq_oracle = [oracle_route() for _ in range(40)]
    assert seq_native == seq_oracle
    if impl in ("EPSILON_GREEDY", "THOMPSON_SAMPLING"):
        # feedback changes the routing state on BOTH sides (exploit arm /
        # Beta posteriors); the streams must stay aligned through it. For
        # Thompson, reward mass pushes the posteriors off the Johnk path
        # into the Marsaglia-Tsang + exponential-ziggurat gamma chain.
        for reward, branch in ((1.0, 2), (0.0, 1), (2.5, 2)):
            fb = {"request": req,
                  "response": {"meta": {"routing": {name: branch}}},
                  "reward": reward}
            assert post(port, "/api/v0.1/feedback", fb)[0] == 200
            aio.run(oracle.send_feedback(
                Feedback.from_dict(json.loads(json.dumps(fb)))))
        assert [edge_route() for _ in range(30)] == [oracle_route() for _ in range(30)]
