"""Payload schema wire-compat tests (reference: python/tests/test_utils.py
shapes and proto/prediction.proto JSON forms)."""

import numpy as np
import pytest

from seldon_core_tpu.contracts.payload import (
    Feedback,
    Meta,
    Metric,
    SeldonError,
    SeldonMessage,
    SeldonMessageList,
    Status,
)


def test_tensor_roundtrip():
    d = {"data": {"names": ["a", "b"], "tensor": {"shape": [2, 2], "values": [1.0, 2.0, 3.0, 4.0]}}}
    msg = SeldonMessage.from_dict(d)
    assert msg.which == "data"
    arr = msg.payload()
    assert arr.shape == (2, 2)
    np.testing.assert_array_equal(arr, [[1.0, 2.0], [3.0, 4.0]])
    out = msg.to_dict()
    assert out["data"]["tensor"] == {"shape": [2, 2], "values": [1.0, 2.0, 3.0, 4.0]}
    assert out["data"]["names"] == ["a", "b"]


def test_ndarray_roundtrip():
    d = {"data": {"ndarray": [[1, 2], [3, 4]]}}
    msg = SeldonMessage.from_dict(d)
    arr = msg.payload()
    assert arr.shape == (2, 2)
    assert msg.to_dict()["data"]["ndarray"] == [[1, 2], [3, 4]]


def test_ndarray_strings():
    d = {"data": {"ndarray": [["a", "b"], ["c", "d"]]}}
    msg = SeldonMessage.from_dict(d)
    assert msg.to_dict()["data"]["ndarray"] == [["a", "b"], ["c", "d"]]


def test_bin_data_roundtrip():
    import base64

    payload = b"\x00\x01binary"
    d = {"binData": base64.b64encode(payload).decode()}
    msg = SeldonMessage.from_dict(d)
    assert msg.payload() == payload
    assert msg.to_dict()["binData"] == base64.b64encode(payload).decode()


def test_str_data_roundtrip():
    msg = SeldonMessage.from_dict({"strData": "hello"})
    assert msg.payload() == "hello"
    assert msg.to_dict()["strData"] == "hello"


def test_json_data_roundtrip():
    payload = {"nested": [1, 2, {"x": True}]}
    msg = SeldonMessage.from_dict({"jsonData": payload})
    assert msg.payload() == payload
    assert msg.to_dict()["jsonData"] == payload


def test_meta_roundtrip():
    d = {
        "meta": {
            "puid": "abc123",
            "tags": {"t": 1},
            "routing": {"router": 1},
            "requestPath": {"model": "img:1"},
            "metrics": [{"key": "c", "type": "COUNTER", "value": 2.0}],
        },
        "data": {"ndarray": [1]},
    }
    msg = SeldonMessage.from_dict(d)
    assert msg.meta.puid == "abc123"
    assert msg.meta.routing == {"router": 1}
    assert msg.meta.metrics[0].key == "c"
    out = msg.to_dict()["meta"]
    assert out["requestPath"] == {"model": "img:1"}
    assert out["metrics"][0]["type"] == "COUNTER"


def test_tensor_shape_mismatch_raises():
    with pytest.raises(SeldonError):
        SeldonMessage.from_dict({"data": {"tensor": {"shape": [3, 3], "values": [1.0, 2.0]}}})


def test_tftensor_rejected_cleanly():
    with pytest.raises(SeldonError, match="tensorflow"):
        SeldonMessage.from_dict({"data": {"tftensor": {}}})


def test_empty_data_raises():
    with pytest.raises(SeldonError):
        SeldonMessage.from_dict({"data": {}})


def test_feedback_roundtrip():
    fb = Feedback.from_dict(
        {
            "request": {"data": {"ndarray": [[1.0]]}},
            "response": {"data": {"ndarray": [[0.9]]}, "meta": {"routing": {"eg-router": 1}}},
            "reward": 1.0,
        }
    )
    assert fb.reward == 1.0
    assert fb.response.meta.routing == {"eg-router": 1}
    out = fb.to_dict()
    assert out["reward"] == 1.0
    assert out["response"]["meta"]["routing"] == {"eg-router": 1}


def test_message_list_roundtrip():
    lst = SeldonMessageList.from_dict(
        {"seldonMessages": [{"data": {"ndarray": [1]}}, {"strData": "x"}]}
    )
    assert len(lst.messages) == 2
    assert lst.to_dict()["seldonMessages"][1]["strData"] == "x"


def test_status():
    s = Status.from_dict({"code": 400, "info": "bad", "status": "FAILURE"})
    assert s.code == 400
    assert s.to_dict()["status"] == "FAILURE"
