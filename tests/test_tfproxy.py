"""TENSORFLOW_SERVER proxy: REST forwarding against a fake TF-Serving HTTP
endpoint, and the gRPC stub path (reference `TfServingProxy.py:35-89`)
against a generic grpc server — the request/response TensorProto wire bytes
are hand-encoded, so this also pins the codec."""

import json
import struct
import threading
from concurrent import futures
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

from seldon_core_tpu.contracts.payload import SeldonError
from seldon_core_tpu.codec.tensorproto import (
    _iter_fields,
    _varint,
    decode_predict_response,
    decode_tensor_proto,
    encode_predict_request,
)
from seldon_core_tpu.servers.tfproxy import TFServingProxy


def test_tensor_proto_roundtrip_f32_f64():
    for arr in (np.arange(6, dtype=np.float32).reshape(2, 3),
                np.arange(4, dtype=np.float64).reshape(2, 2)):
        req = encode_predict_request(arr, "m", "sig", "inputs")
        # pull the TensorProto back out of the inputs map and decode it
        tensor = None
        spec = {}
        for field, wire, val in _iter_fields(req):
            if field == 2 and wire == 2:
                for f2, w2, v2 in _iter_fields(val):
                    if f2 == 2 and w2 == 2:
                        tensor = v2
            elif field == 1 and wire == 2:
                for f2, w2, v2 in _iter_fields(val):
                    spec[f2] = v2
        assert spec[1] == b"m" and spec[3] == b"sig"
        got = decode_tensor_proto(tensor)
        assert got.dtype == arr.dtype
        np.testing.assert_array_equal(got, arr)


def _fake_tf_grpc_server():
    """Generic grpc server answering PredictionService/Predict: decodes the
    request, computes 2*x + 1, answers under the requested output name."""
    import grpc

    seen = {}

    def predict(request_bytes, context):
        tensor = None
        for field, wire, val in _iter_fields(request_bytes):
            if field == 1 and wire == 2:
                for f2, _w2, v2 in _iter_fields(val):
                    seen[f2] = v2
            elif field == 2 and wire == 2:
                entry = dict()
                for f2, w2, v2 in _iter_fields(val):
                    entry[f2] = v2
                seen["input_name"] = entry[1]
                tensor = entry[2]
        arr = decode_tensor_proto(tensor)
        out = (2.0 * arr + 1.0).astype(np.float32)
        # reuse the request encoder, then strip to a bare outputs map
        req = encode_predict_request(out, "", "", "scores")
        # drop the leading model_spec submessage (field 1)
        fields = list(_iter_fields(req))
        # rebuild: outputs map is field 1 in PredictResponse
        entry = None
        for field, wire, val in fields:
            if field == 2 and wire == 2:
                entry = val
        out_bytes = bytes([0x0A]) + _varint(len(entry)) + entry
        return out_bytes

    handler = grpc.method_handlers_generic_handler(
        "tensorflow.serving.PredictionService",
        {"Predict": grpc.unary_unary_rpc_method_handler(
            predict,
            request_deserializer=lambda b: b,
            response_serializer=lambda b: b,
        )},
    )
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((handler,))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    return server, port, seen


def test_grpc_forwarding_roundtrip():
    pytest.importorskip("grpc")
    server, port, seen = _fake_tf_grpc_server()
    try:
        proxy = TFServingProxy(
            grpc_endpoint=f"127.0.0.1:{port}", model_name="half_plus_two",
            signature_name="serving_default", model_input="x",
            model_output="scores")
        X = np.asarray([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
        out = proxy.predict(X, [])
        np.testing.assert_allclose(out, 2.0 * X + 1.0)
        # model_spec + input name propagated on the wire
        assert seen[1] == b"half_plus_two"
        assert seen[3] == b"serving_default"
        assert seen["input_name"] == b"x"
    finally:
        server.stop(None)


def test_grpc_upstream_error_maps_to_seldon_error():
    pytest.importorskip("grpc")
    proxy = TFServingProxy(grpc_endpoint="127.0.0.1:1")  # nothing listening
    with pytest.raises(SeldonError) as e:
        proxy.predict(np.ones((1, 2), np.float32), [])
    assert e.value.status_code == 502


class _FakeTFRest(BaseHTTPRequestHandler):
    def do_POST(self):
        body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
        instances = np.asarray(body["instances"])
        resp = json.dumps({"predictions": (instances * 3.0).tolist()}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)

    def log_message(self, *a):  # quiet
        pass


def test_rest_forwarding_roundtrip():
    httpd = HTTPServer(("127.0.0.1", 0), _FakeTFRest)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        proxy = TFServingProxy(
            rest_endpoint=f"http://127.0.0.1:{httpd.server_port}")
        X = np.asarray([[1.0, 2.0]])
        out = proxy.predict(X, [])
        np.testing.assert_allclose(out, X * 3.0)
    finally:
        httpd.shutdown()


def test_decode_missing_output_raises():
    req = encode_predict_request(np.ones((1, 1), np.float32), "", "", "a")
    # build a response with output name 'a', ask for 'b' with two outputs
    entry = None
    for field, wire, val in _iter_fields(req):
        if field == 2 and wire == 2:
            entry = val
    resp = b""
    for name in (b"a", b"c"):
        e = bytearray(entry)
        # key is the first field; rewrite it (same length names)
        e[2:3] = name
        resp += bytes([0x0A]) + _varint(len(e)) + bytes(e)
    with pytest.raises(SeldonError, match="missing output"):
        decode_predict_response(resp, "b")



def test_tensor_proto_int_roundtrip():
    """DT_INT32/DT_INT64 decode (ADVICE r4: previously silently decoded to
    an empty float32 array). The encoder itself emits these for token-id
    inputs, so encode->decode must round-trip, negatives included."""
    from seldon_core_tpu.codec.tensorproto import (
        decode_tensor_proto, encode_predict_request, _iter_fields)

    def tensor_bytes(req: bytes) -> bytes:
        for field, wire, val in _iter_fields(req):
            if field == 2 and wire == 2:  # inputs map entry
                for f2, w2, v2 in _iter_fields(val):
                    if f2 == 2 and w2 == 2:
                        return v2
        raise AssertionError("no TensorProto in request")

    for dtype in (np.int32, np.int64):
        arr = np.array([[1, -2, 3], [2**31 - 1, 0, -7]], dtype=dtype)
        out = decode_tensor_proto(tensor_bytes(
            encode_predict_request(arr, "m", "s", "in")))
        assert out.dtype == dtype
        np.testing.assert_array_equal(out, arr)


def test_tensor_proto_unsupported_dtype_raises():
    from seldon_core_tpu.contracts.payload import SeldonError
    from seldon_core_tpu.codec.tensorproto import _tag, _varint, decode_tensor_proto

    buf = _tag(1, 0) + _varint(7)  # DT_STRING: not decodable here
    with pytest.raises(SeldonError, match="dtype 7"):
        decode_tensor_proto(buf)
