"""hlolint self-tests: every contract kind proven to go RED on a mutated
fixture (drop a donation, insert a host callback, widen a KV dtype,
inflate a budget, add a collective), plus the waiver/baseline mechanics
and the CLI the CI gate relies on.

Fixtures are tiny synthetic jits — no model load — so everything here is
tier-1 except the full-registry run (marked slow; CI runs the real gate
as its own step anyway)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from functools import partial

import numpy as np
import pytest

from tools.hlolint.core import (
    Contract,
    apply_baseline,
    collective_counts_from_text,
    load_baseline,
    opcode_counts_from_text,
    run_contracts,
    save_budgets,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGETS = os.path.join(REPO, "tools", "hlolint", "budgets.json")


def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def run_one(contract, **kw):
    reported, absorbed, waived, diff, measured = run_contracts([contract], **kw)
    return reported, absorbed, waived, diff, measured


def checks_of(findings):
    return [f.check for f in findings]


# ---------------------------------------------------------------------------
# alias: donation must survive into input_output_alias
# ---------------------------------------------------------------------------

def _build_donating(donate: bool):
    def build():
        import jax

        @partial(jax.jit, donate_argnums=(0,) if donate else ())
        def step(cache, tok):
            return cache.at[0].set(tok), tok + 1

        return step, (_sds((4, 8), "float32"), _sds((8,), "float32"))

    return build


def test_alias_dropped_donation_fires():
    """Mutation: remove donate_argnums from the decode step — the alias
    contract must go red."""
    c = Contract("fix.alias", "t", _build_donating(donate=False), donated=(0,))
    reported, *_ = run_one(c)
    assert checks_of(reported) == ["alias"]
    assert "input_output_alias" in reported[0].message


def test_alias_live_donation_is_clean():
    c = Contract("fix.alias", "t", _build_donating(donate=True), donated=(0,))
    reported, *_ = run_one(c)
    assert reported == []


def test_alias_degraded_donation_fires():
    """The reason this check reads COMPILED HLO instead of the source: the
    jit below DOES declare donate_argnums=(0, 1), but arg 0's buffer can
    alias no output (shape mismatch), so XLA silently drops it — an AST
    walk sees a donation, the compiled module shows a copy."""

    def build():
        import jax

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(small, big):
            return big.at[0].set(small)

        return step, (_sds((8,), "float32"), _sds((4, 8), "float32"))

    c = Contract("fix.alias2", "t", build, donated=(0, 1))
    reported, *_ = run_one(c)
    assert checks_of(reported) == ["alias"]
    assert reported[0].detail == "arg0"  # the big buffer's donation held


# ---------------------------------------------------------------------------
# transfer: no host round-trips inside the compiled hot function
# ---------------------------------------------------------------------------

def test_transfer_host_callback_fires():
    """Mutation: a jax.debug.print-style host callback inside the step."""

    def build():
        import jax

        @jax.jit
        def step(x):
            jax.debug.print("x={x}", x=x)
            return x * 2

        return step, (_sds((4,), "float32"),)

    c = Contract("fix.transfer", "t", build)
    reported, *_ = run_one(c)
    assert "transfer" in checks_of(reported)
    assert any("callback" in f.message for f in reported)


def test_opcode_parsing_sees_tuple_typed_instructions():
    """send/recv/infeed are ALWAYS tuple-typed in HLO text, and the
    all-reduce combiner can merge same-shape collectives into one
    tuple-shaped op — the instruction parser must not be blind to either
    (review regression: a single-shape-only regex silently passed every
    send/recv)."""
    hlo = "\n".join([
        "  %s = (f32[], u32[], token[]) send(f32[] %x, token[] %t), channel_id=1",
        "  %r = (f32[4]{0}, token[]) recv(token[] %t), channel_id=2",
        "  %i = (f32[2]{0}, token[]) infeed(token[] %t)",
        "  %ar = (f32[4]{0}, f32[4]{0}) all-reduce(f32[4]{0} %a, f32[4]{0} %b), to_apply=%add",
        "  ROOT %d = f32[4]{0} dot(f32[4]{0} %a, f32[4]{0} %b)",
    ])
    counts = opcode_counts_from_text(hlo)
    assert counts == {"send": 1, "recv": 1, "infeed": 1, "all-reduce": 1,
                      "dot": 1}
    assert collective_counts_from_text(hlo) == {"all-reduce": 1}


def test_transfer_pure_step_is_clean():
    def build():
        import jax

        return jax.jit(lambda x: x * 2), (_sds((4,), "float32"),)

    reported, *_ = run_one(Contract("fix.transfer", "t", build))
    assert reported == []


# ---------------------------------------------------------------------------
# dtype: forbidden signatures + output dtypes
# ---------------------------------------------------------------------------

def _build_kv_read(widen: bool):
    def build():
        import jax
        import jax.numpy as jnp

        @jax.jit
        def read(cache, q):
            kv = cache.astype(jnp.float32) if widen else cache
            return jnp.einsum("ld,d->l", kv, q.astype(kv.dtype))

        return read, (_sds((64, 16), "bfloat16"), _sds((16,), "bfloat16"))

    return build


KV_F32 = (r"tensor<64x16xf32>", "full-cache f32 materialization")


def test_dtype_widened_kv_fires():
    """Mutation: upcast the whole KV buffer to f32 before the read."""
    c = Contract("fix.dtype", "t", _build_kv_read(widen=True),
                 forbid_dtypes=(KV_F32,))
    reported, *_ = run_one(c)
    assert checks_of(reported) == ["dtype"]
    assert "forbidden dtype" in reported[0].message


def test_dtype_native_kv_read_is_clean():
    c = Contract("fix.dtype", "t", _build_kv_read(widen=False),
                 forbid_dtypes=(KV_F32,))
    reported, *_ = run_one(c)
    assert reported == []


def test_dtype_widened_output_fires():
    def build():
        import jax
        import jax.numpy as jnp

        # mutation: the final cast back to the model dtype was dropped
        return jax.jit(lambda x: (x.astype(jnp.float32) * 2.0)), (
            _sds((4, 8), "bfloat16"),)

    c = Contract("fix.outdtype", "t", build, out_dtypes=((0, "bf16"),))
    reported, *_ = run_one(c)
    assert checks_of(reported) == ["dtype"]
    assert "output 0 is f32" in reported[0].message


# ---------------------------------------------------------------------------
# collective: exact count-per-kind budget
# ---------------------------------------------------------------------------

def _build_permute():
    def build():
        import jax
        import numpy as _np
        from jax.sharding import Mesh, PartitionSpec as P

        from seldon_core_tpu.parallel.compat import shard_map

        mesh = Mesh(_np.array(jax.devices()[:8]), ("x",))
        perm = [(i, (i + 1) % 8) for i in range(8)]
        fn = shard_map(lambda a: jax.lax.ppermute(a, "x", perm),
                       mesh=mesh, in_specs=(P("x"),), out_specs=P("x"))
        return jax.jit(fn), (_sds((8, 4), "float32"),)

    return build


def test_collective_unbudgeted_fires(eight_devices):
    """Mutation: a permute appears where the contract budgets none — the
    'stray reshard in the decode step' class."""
    c = Contract("fix.coll", "t", _build_permute(), collectives={})
    reported, *_ = run_one(c)
    assert checks_of(reported) == ["collective"]
    assert "collective-permute" in reported[0].detail


def test_collective_exact_budget_is_clean(eight_devices):
    c = Contract("fix.coll", "t", _build_permute(),
                 collectives={"collective-permute": 1})
    reported, *_ = run_one(c)
    assert reported == []


def test_collective_missing_also_fires(eight_devices):
    """The budget is exact in both directions: a vanished collective means
    the compiled program is not the one the contract describes."""

    def build():
        import jax

        return jax.jit(lambda x: x + 1), (_sds((8, 4), "float32"),)

    c = Contract("fix.coll", "t", build,
                 collectives={"collective-permute": 1})
    reported, *_ = run_one(c)
    assert checks_of(reported) == ["collective"]
    assert "missing" in reported[0].message


# ---------------------------------------------------------------------------
# cost: tolerance band around the committed budget
# ---------------------------------------------------------------------------

def _cost_contract():
    def build():
        import jax

        return jax.jit(lambda a, b: a @ b), (
            _sds((32, 32), "float32"), _sds((32, 32), "float32"))

    return Contract("fix.cost", "t", build, cost=True)


def test_cost_missing_budget_fires():
    reported, *_ = run_one(_cost_contract(), budgets={"entries": {}})
    assert checks_of(reported) == ["cost"]
    assert reported[0].detail == "missing-budget"


def test_cost_inflated_budget_fires_then_rebaseline_clears(tmp_path):
    """Mutation: the compiled cost drifts far past the committed budget ->
    red; --update-budgets writes the measured snapshot -> green."""
    budgets = {"tolerance": 0.2,
               "entries": {"fix.cost": {"flops": 1.0, "bytes_accessed": 1.0}}}
    reported, _, _, diff, measured = run_one(_cost_contract(), budgets=budgets)
    assert sorted(f.detail for f in reported) == ["bytes_accessed", "flops"]
    assert "fix.cost" in diff and diff["fix.cost"]["flops"]["budget"] == 1.0

    path = str(tmp_path / "budgets.json")
    save_budgets(path, measured, previous=budgets)
    rebased = json.loads(open(path).read())
    assert rebased["tolerance"] == 0.2  # survives re-baseline
    reported2, *_ = run_one(_cost_contract(), budgets=rebased)
    assert reported2 == []


# ---------------------------------------------------------------------------
# waiver + baseline mechanics
# ---------------------------------------------------------------------------

def test_waiver_with_reason_suppresses_and_empty_reason_fires():
    c = Contract("fix.alias", "t", _build_donating(donate=False), donated=(0,),
                 waivers={"alias:arg0": "known CPU-only fixture"})
    reported, _, waived, *_ = run_one(c)
    assert reported == [] and len(waived) == 1

    c2 = Contract("fix.alias", "t", _build_donating(donate=False), donated=(0,),
                  waivers={"alias:arg0": "   "})
    reported2, *_ = run_one(c2)
    assert "bad-waiver" in checks_of(reported2)
    assert "alias" in checks_of(reported2)  # empty reason does NOT suppress


def test_baseline_absorbs_by_fingerprint_and_dies_with_the_detail():
    c = Contract("fix.alias", "t", _build_donating(donate=False), donated=(0,))
    reported, *_ = run_one(c)
    fp = reported[0].fingerprint()
    baseline = {fp: {"fingerprint": fp, "reason": "grandfathered", "count": 1}}
    reported2, absorbed, *_ = run_one(c, baseline=baseline)
    assert reported2 == [] and len(absorbed) == 1
    # a different detail (another contract name) must NOT be absorbed
    c3 = Contract("fix.alias_v2", "t", _build_donating(donate=False), donated=(0,))
    reported3, absorbed3, *_ = run_one(c3, baseline=baseline)
    assert len(reported3) == 1 and absorbed3 == []


def test_baseline_without_reason_is_rejected(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"entries": [{"fingerprint": "abc", "reason": ""}]}))
    with pytest.raises(ValueError, match="reason"):
        load_baseline(str(p))


def test_build_error_is_a_finding_not_a_crash():
    def build():
        raise RuntimeError("model too big for this host")

    reported, *_ = run_one(Contract("fix.broken", "t", build))
    assert checks_of(reported) == ["build-error"]
    # meta findings can never be baselined away
    fp = reported[0].fingerprint()
    still, absorbed = apply_baseline(
        reported, {fp: {"fingerprint": fp, "reason": "nope", "count": 1}})
    assert len(still) == 1 and absorbed == []


# ---------------------------------------------------------------------------
# the committed registry artifacts + CLI
# ---------------------------------------------------------------------------

def test_budgets_json_covers_every_cost_contract():
    from tools.hlolint.contracts import all_contracts

    budgets = json.loads(open(BUDGETS).read())
    entries = budgets.get("entries", {})
    for c in all_contracts():
        if c.cost:
            assert c.name in entries, (
                f"{c.name} has cost=True but no committed budget — run "
                "--update-budgets and commit the reviewed snapshot")
            assert entries[c.name].get("flops", 0) > 0


def test_registry_waivers_all_carry_reasons():
    from tools.hlolint.contracts import all_contracts

    for c in all_contracts():
        for key, reason in c.waivers.items():
            assert str(reason).strip(), f"{c.name} waiver {key!r} has no reason"


def cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.hlolint", *args],
        capture_output=True, text=True, cwd=REPO)


def test_cli_list_and_usage_errors():
    res = cli("--list")
    assert res.returncode == 0
    assert "llm.decode_step_s4" in res.stdout
    assert cli("--contracts", "no.such.contract").returncode == 2
    assert cli("--checks", "no-such-check").returncode == 2
    assert cli("no/such/path").returncode == 2


def test_cli_single_cheap_contract_enforcing():
    """The fused_norm contract end-to-end through the CLI (no model load:
    this is the fast smoke of the real gate; CI runs the full registry)."""
    res = cli("--contracts", "ops.fused_norm", "--format", "json")
    assert res.returncode == 0, res.stdout + res.stderr
    payload = json.loads(res.stdout)
    assert payload["findings"] == []
    assert "ops.fused_norm" in payload["budget_diff"]


@pytest.mark.slow
def test_full_registry_is_green():
    """The CI gate, in-process: every committed contract holds on the real
    tree with the committed budgets."""
    from tools.hlolint.contracts import all_contracts
    from tools.hlolint.core import load_budgets

    reported, absorbed, waived, diff, _ = run_contracts(
        all_contracts(), budgets=load_budgets(BUDGETS))
    assert reported == [], "\n".join(f.render() for f in reported)
    # the enforcement is real: the registry carries a reasoned waiver
    # (the TP sampling all-gathers) that absorbs an actual finding
    assert waived, "expected the decode_scan_tp2 all-gather waiver to fire"
