"""Concurrent-stress tier-1 tests for the resilience state machines.

The schedule harness (tests/test_schedules.py) proves exact
interleavings; this file is the complementary blunt instrument: N real
threads hammering the REAL CircuitBreaker / AdmissionController with no
scheduler in the way, checking the invariants that must survive any
interleaving the OS produces:

- counters never go negative and never lose so many updates that
  accounting breaks (every operation is counted exactly once);
- the in-flight gauge returns to zero once every caller releases;
- the admission queue drains;
- the breaker never double-opens for one failure burst and never wedges
  half-open with a lost probe slot.

Jax-free and quick (a few hundred ms of real threading) — tier-1.
"""

from __future__ import annotations

import threading

import pytest

from seldon_core_tpu.runtime.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    AdmissionController,
    CircuitBreaker,
    ShedError,
)
from seldon_core_tpu.testing.faults import FaultClock

pytestmark = pytest.mark.faults

N_THREADS = 8
N_OPS = 200


def _run_all(workers):
    threads = [threading.Thread(target=w, name=f"stress-{i}")
               for i, w in enumerate(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "stress worker wedged"


def test_admission_counters_consistent_under_stress():
    adm = AdmissionController(max_inflight=3, max_queue=4)
    admitted = [0] * N_THREADS
    shed = [0] * N_THREADS
    errors = []

    def worker(i):
        def run():
            for _ in range(N_OPS):
                try:
                    adm.acquire_sync(timeout_s=0.05)
                except ShedError:
                    shed[i] += 1
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                else:
                    admitted[i] += 1
                    adm.release()
        return run

    _run_all([worker(i) for i in range(N_THREADS)])
    assert not errors
    # every op resolved exactly one way, and the controller agrees
    assert sum(admitted) + sum(shed) == N_THREADS * N_OPS
    assert adm.shed_total == sum(shed)
    assert adm.shed_total >= 0 and adm.admitted_total >= 0
    # in-flight gauge returns to zero and the queue drains
    assert adm.inflight == 0
    assert adm.queue_depth() == 0


def test_admission_inflight_never_exceeds_limit():
    adm = AdmissionController(max_inflight=2, max_queue=N_THREADS)
    high_water = []
    hw_lock = threading.Lock()

    def run():
        for _ in range(50):
            try:
                adm.acquire_sync(timeout_s=1.0)
            except ShedError:
                continue
            with hw_lock:
                high_water.append(adm.inflight)
            adm.release()

    _run_all([run] * N_THREADS)
    assert adm.inflight == 0
    assert high_water and max(high_water) <= 2


def test_breaker_counters_consistent_under_stress():
    clock = FaultClock()
    breaker = CircuitBreaker("stress", failure_threshold=5,
                             reset_timeout_s=1e9, clock=clock)
    allowed = [0] * N_THREADS
    rejected = [0] * N_THREADS

    def worker(i):
        def run():
            for k in range(N_OPS):
                if breaker.allow():
                    allowed[i] += 1
                    (breaker.record_failure if k % 3 else breaker.record_success)()
                else:
                    rejected[i] += 1
        return run

    _run_all([worker(i) for i in range(N_THREADS)])
    # every rejection was counted exactly once, none went missing
    assert breaker.rejected_total == sum(rejected)
    assert breaker.rejected_total >= 0
    assert breaker.consecutive_failures >= 0
    assert breaker.state in (CLOSED, OPEN, HALF_OPEN)
    # transition accounting is an exact event count: with a huge reset
    # timeout the breaker can only ever CLOSE from half-open probes, and
    # those are impossible here, so opens can exceed closes by at most 1
    opens, closes = breaker.transitions[OPEN], breaker.transitions[CLOSED]
    assert 0 <= opens - closes <= 1


def test_breaker_probe_slot_never_leaks_under_stress():
    """Open -> eligible: exactly one allow() wins the half-open probe per
    cycle; record_failure re-opens; repeat. The probe slot must neither
    leak (two Trues per cycle) nor wedge (zero Trues forever)."""
    clock = FaultClock()
    breaker = CircuitBreaker("probe", failure_threshold=1,
                             reset_timeout_s=1.0, clock=clock)
    breaker.record_failure()  # OPEN
    clock.advance(1.0)        # make round 1's probe eligible
    wins = []
    wins_lock = threading.Lock()
    rounds = 30
    barrier = threading.Barrier(N_THREADS)

    def run():
        for _ in range(rounds):
            barrier.wait(timeout=30)
            got = breaker.allow()
            with wins_lock:
                if got:
                    wins.append(1)
            barrier.wait(timeout=30)
            if got:
                breaker.record_failure()  # probe fails -> OPEN again
                clock.advance(1.0)        # eligible for the next round

    _run_all([run] * N_THREADS)
    assert len(wins) == rounds  # exactly one winner per round
    assert breaker.state == OPEN
