"""Disaggregated prefill/decode serving (ISSUE 9 tentpole).

The contract: moving admission prefill onto a separate PREFILL slice and
handing the written KV device-to-device into the decode slice's pool
changes NOTHING about tokens — remote-prefill serving is bit-exact against
single-slice serving for greedy and seeded sampling, dense and paged
layouts, bf16 and int8 KV, including admissions landing while decode steps
are in flight — while the TransferQueue delivers every handoff exactly
once, sheds cancel staged jobs without double-freeing their decode-side
pages, and worker failures resolve their own request without touching the
batch. Runs on the virtual 8-device CPU mesh (tests/conftest.py forces
``--xla_force_host_platform_device_count=8``)."""

from __future__ import annotations

import asyncio

import pytest

from seldon_core_tpu.runtime.batcher import ContinuousBatcher
from seldon_core_tpu.runtime.disagg import (
    Handoff,
    TransferQueue,
    normalize_disaggregation,
)
from seldon_core_tpu.runtime.resilience import ShedError
from seldon_core_tpu.servers.llmserver import LLMServer

pytestmark = pytest.mark.leakcheck  # conftest leak canary (ISSUE 19)

KW = dict(vocab_size=96, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
          ffn_dim=64, max_seq_len=96)


def make_server(**extra) -> LLMServer:
    base = dict(model="transformer", model_kwargs=KW, init_random=True,
                max_new_tokens=8, len_buckets=(16,), batch_buckets=(1, 4),
                temperature=0.0, eos_id=-1, seed=3)
    base.update(extra)
    s = LLMServer(**base)
    s.load()
    return s


@pytest.fixture(scope="module")
def server():
    return make_server(disaggregation="remote_prefill", prefill_devices=2)


@pytest.fixture(scope="module")
def int8_server():
    return make_server(disaggregation="remote_prefill", prefill_devices=2,
                       kv_cache_dtype="int8")


@pytest.fixture(scope="module")
def sampled_server():
    return make_server(disaggregation="remote_prefill", prefill_devices=2,
                       temperature=0.8, top_k=20, seed=5)


def run_batch(server, prompts, *, n=8, seeds=None, disaggregation=None,
              **batcher_kw):
    """Drive one batch through a fresh ContinuousBatcher. ``disaggregation``
    overrides the server's mode, so the SAME server object produces both
    the single-slice baseline and the disaggregated run (identical params,
    identical rng chain — any token difference is the handoff's fault)."""
    batcher_kw.setdefault("layout", "paged")
    batcher_kw.setdefault("page_size", 8)

    async def go():
        b = ContinuousBatcher(server, disaggregation=disaggregation,
                              **batcher_kw)
        outs = await asyncio.gather(*[
            b.submit(p, max_new_tokens=n,
                     seed=None if seeds is None else seeds[i])
            for i, p in enumerate(prompts)])
        stats = {"handoff": b.handoff_stats(),
                 "pages": b.page_stats() if b.paged else None}
        await b.close()
        return outs, stats

    return asyncio.run(go())


PROMPTS = [[5, 9, 17], [40, 3, 22, 8, 11, 60, 2, 33, 7, 7, 12, 13],
           [7], [60, 61, 62, 63, 64, 65]]


# ---------------------------------------------------------------- parity
@pytest.mark.parametrize("fixt", [
    "server",
    # tier-1 keeps the bf16 pair; int8 rides CI's unfiltered step AND the
    # pinned disaggregation-parity step (ci.yaml runs this file unfiltered)
    pytest.param("int8_server", marks=pytest.mark.slow),
])
@pytest.mark.parametrize("layout", [
    # tier-1 870s budget: the full cross rides the pinned unfiltered
    # disagg CI step; tier-1 keeps seeded[paged] below plus the greedy
    # paged anchor test_remote_admission_mid_decode_steps_in_flight
    pytest.param("paged", marks=pytest.mark.slow),
    pytest.param("dense", marks=pytest.mark.slow),
])
def test_remote_prefill_greedy_parity(fixt, layout, request):
    """The acceptance bar: prefill-on-slice-A + decode-on-slice-B equals
    single-slice serving token for token, both layouts, both KV dtypes —
    and the handoffs actually happened (every admission crossed the
    TransferQueue, none were served by local prefill)."""
    s = request.getfixturevalue(fixt)
    base, _ = run_batch(s, PROMPTS, disaggregation="off", layout=layout,
                        max_slots=3, max_len=40, len_buckets=(8,))
    dis, stats = run_batch(s, PROMPTS, layout=layout,
                           max_slots=3, max_len=40, len_buckets=(8,))
    assert dis == base
    assert stats["handoff"]["handoffs_total"] == len(PROMPTS)
    assert stats["handoff"]["handoff_queue_depth"] == 0
    assert stats["handoff"]["handoff_transfer_bytes_total"] > 0
    if layout == "paged":
        assert stats["pages"]["kv_pages_in_use"] == 0


@pytest.mark.parametrize("layout", [
    "paged",
    # tier-1 870s budget: dense greedy parity above keeps the dense axis;
    # dense seeded runs in CI (the pinned disagg step is unfiltered)
    pytest.param("dense", marks=pytest.mark.slow),
])
def test_remote_prefill_seeded_parity(sampled_server, layout):
    """Seeded sampling through the disaggregated path reproduces the
    single-slice chain exactly: the first token samples from the worker's
    handed-off logits on the same per-request key, and every later token
    comes off the slot's untouched device rng."""
    prompts = [[5, 9, 17, 2], [40, 3, 22], [7, 7, 7, 7, 7]]
    seeds = [42, 1234, 7]
    base, _ = run_batch(sampled_server, prompts, seeds=seeds,
                        disaggregation="off", layout=layout,
                        max_slots=3, max_len=40, len_buckets=(8,))
    dis, _ = run_batch(sampled_server, prompts, seeds=seeds, layout=layout,
                       max_slots=3, max_len=40, len_buckets=(8,))
    assert dis == base


@pytest.mark.slow  # tier-1 870s budget: the solo-generate bar also holds via test_remote_admission_mid_decode (vs generate()); CI disagg step unfiltered
def test_remote_prefill_matches_generate(server):
    """Directly against the solo generate() ground truth (not just the
    single-slice batcher): the same bar every batcher feature meets."""
    expected = [server.generate([p], max_new_tokens=8)["tokens"][0]
                for p in PROMPTS]
    outs, _ = run_batch(server, PROMPTS, max_slots=3, max_len=40,
                        len_buckets=(8,))
    assert outs == expected


def test_remote_admission_mid_decode_steps_in_flight(server):
    """An admission handed off while >=2 decode steps are in flight: the
    in-flight request's tokens are untouched, the admitted prompt decodes
    its exact solo tokens, and the handoff landed while decode kept
    dispatching (the whole point: the burst never stalls the victims)."""
    p1 = [5, 9, 17, 33]
    p2 = list(range(2, 31))  # 29 tokens: a long-prefill adversary
    e1 = server.generate([p1], max_new_tokens=24)["tokens"][0]
    e2 = server.generate([p2], max_new_tokens=6)["tokens"][0]

    async def go():
        b = ContinuousBatcher(server, max_slots=2, max_len=64,
                              len_buckets=(32,), pipeline_depth=3,
                              layout="paged", page_size=8, prefill_chunk=8)
        t1 = asyncio.ensure_future(b.submit(p1, max_new_tokens=24))
        for _ in range(400):
            if b._inflight_hwm >= 2 and any(s.active for s in b._slots):
                break
            await asyncio.sleep(0.005)
        t2 = asyncio.ensure_future(b.submit(p2, max_new_tokens=6))
        o1, o2 = await asyncio.gather(t1, t2)
        hwm = b._inflight_hwm
        handoffs = b.handoff_stats()["handoffs_total"]
        await b.close()
        return o1, o2, hwm, handoffs

    o1, o2, hwm, handoffs = asyncio.run(go())
    assert o1 == e1
    assert o2 == e2
    assert hwm >= 2
    assert handoffs == 2


@pytest.mark.slow
def test_multiple_prefill_workers_concurrent_admissions(server):
    """M=2 workers, a burst of admissions: least-backlog dispatch spreads
    them, every handoff is delivered exactly once, tokens stay exact."""
    prompts = [[i + 1, i + 2, i + 3, i + 4] for i in range(6)]
    expected = [server.generate([p], max_new_tokens=6)["tokens"][0]
                for p in prompts]
    outs, stats = run_batch(server, prompts, n=6, max_slots=4, max_len=32,
                            len_buckets=(8,), prefill_workers=2)
    assert outs == expected
    assert stats["handoff"]["handoffs_total"] == len(prompts)
    assert stats["pages"]["kv_pages_in_use"] == 0


# ------------------------------------------------- transfer-queue protocol
def test_transfer_queue_exactly_once_lifecycle():
    q = TransferQueue()
    q.register(1)
    q.register(2)
    assert q.depth() == 2 and q.ready_depth() == 0
    assert q.put(Handoff(1, staged="kv1", transfer_bytes=10))
    assert q.put(Handoff(2, staged="kv2", transfer_bytes=20))
    assert q.ready_depth() == 2
    h = q.pop()
    assert h.job_id == 1 and h.staged == "kv1"  # FIFO
    assert q.pop().job_id == 2
    assert q.pop() is None
    assert q.depth() == 0
    assert q.stats() == (2, 30, 0)


def test_transfer_queue_cancel_staged_refuses_late_put():
    """Shed-before-handoff: cancel marks the job, the worker's later put
    is refused (payload dropped), and nothing is ever poppable — the
    CANCELLER freed the pages, exactly once."""
    q = TransferQueue()
    q.register(7)
    assert q.cancel(7) is None          # staged: caller frees pages NOW
    assert not q.put(Handoff(7, staged="kv"))   # worker's put refused
    assert q.pop() is None
    assert q.depth() == 0
    assert q.stats()[0] == 0            # a refused put is not a delivery


def test_transfer_queue_cancel_ready_returns_handoff_once():
    """Shed-after-handoff: cancel takes the READY record out of the queue
    and hands it to the canceller (who frees the pages); a second cancel
    and a pop both come up empty — no path sees it twice."""
    q = TransferQueue()
    q.register(3)
    q.put(Handoff(3, staged="kv"))
    h = q.cancel(3)
    assert h is not None and h.job_id == 3
    assert q.cancel(3) is None
    assert q.pop() is None


def test_transfer_queue_cancel_after_pop_is_noop():
    """Shed racing consume, consume wins: the slot owns the pages, so the
    late cancel must return None (caller must NOT free)."""
    q = TransferQueue()
    q.register(4)
    q.put(Handoff(4, staged="kv"))
    assert q.pop().job_id == 4
    assert q.cancel(4) is None


def test_transfer_queue_on_ready_hook_fires_outside_lock():
    q = TransferQueue()
    fired = []

    def hook():
        # re-entering the queue from the hook must not deadlock: the hook
        # runs OUTSIDE the lock
        fired.append(q.ready_depth())

    q.on_ready = hook
    q.register(1)
    q.put(Handoff(1, staged="kv"))
    assert fired == [1]


# --------------------------------------------------- shed / failure paths
@pytest.mark.slow  # tier-1 870s budget: runs in CI's unfiltered disagg step
def test_worker_exception_propagates_to_submitter():
    """End-to-end worker failure: a prompt whose token ids exceed the
    embedding table blows up inside the worker's prefill program — the
    submitter gets the error, pages are freed, the NEXT request serves."""
    s = make_server(disaggregation="remote_prefill", prefill_devices=2)

    async def go():
        b = ContinuousBatcher(s, max_slots=2, max_len=32, len_buckets=(8,),
                              layout="dense")
        # monkeypatch the pool to fail one specific job
        worker = b._remote.workers[0]
        real = worker._prefill_one

        def boom(req):
            if req.ids[0] == 99:
                raise RuntimeError("injected prefill failure")
            return real(req)

        worker._prefill_one = boom
        bad = asyncio.ensure_future(b.submit([99, 1, 2], max_new_tokens=4))
        with pytest.raises(RuntimeError, match="injected prefill failure"):
            await bad
        ok = await b.submit([5, 9, 17], max_new_tokens=4)
        stats = b.handoff_stats()
        await b.close()
        return ok, stats

    ok, stats = asyncio.run(go())
    assert len(ok) == 4
    assert stats["handoff_queue_depth"] == 0


def test_pool_exhaustion_sheds_staged_remote_job_503(server):
    """LIFO shed order reaches staged remote jobs: when decode growth
    exhausts the pool, the newest STAGED admission sheds with 503 +
    RESOURCE_EXHAUSTED, its pages come back exactly once, and the oldest
    request completes bit-exact."""
    p1 = [5, 9, 17, 33]
    e1 = server.generate([p1], max_new_tokens=24)["tokens"][0]

    async def go():
        b = ContinuousBatcher(server, max_slots=2, max_len=32,
                              len_buckets=(8,), layout="paged",
                              page_size=4, pool_pages=10)
        t1 = asyncio.ensure_future(b.submit(p1, max_new_tokens=24))
        await asyncio.sleep(0)  # keep admission order deterministic
        t2 = asyncio.ensure_future(b.submit([40, 3, 22, 8],
                                            max_new_tokens=24))
        r1, r2 = await asyncio.gather(t1, t2, return_exceptions=True)
        stats = b.page_stats()
        await b.close()
        return r1, r2, stats

    r1, r2, stats = asyncio.run(go())
    # whichever got shed, the survivor is bit-exact and accounting is clean
    survivors = [r for r in (r1, r2) if not isinstance(r, Exception)]
    sheds = [r for r in (r1, r2) if isinstance(r, ShedError)]
    if sheds:  # timing-dependent: both can fit if decode outpaces growth
        assert sheds[0].status_code == 503
        assert sheds[0].reason == "RESOURCE_EXHAUSTED"
    assert r1 == e1 or isinstance(r1, ShedError)
    assert survivors
    assert stats["kv_pages_in_use"] == 0


def test_close_fails_staged_jobs_instead_of_hanging():
    """Batcher shutdown with a job still staged on the prefill slice: the
    submitter's future resolves with an error — never hangs."""
    s = make_server(disaggregation="remote_prefill", prefill_devices=2)

    async def go():
        b = ContinuousBatcher(s, max_slots=2, max_len=32, len_buckets=(8,),
                              layout="paged", page_size=8)
        worker = b._remote.workers[0]

        def stall(req):
            import time
            time.sleep(30)
            raise RuntimeError("unreachable")

        worker._prefill_one = stall
        fut = asyncio.ensure_future(b.submit([5, 9, 17], max_new_tokens=4))
        # let the admission stage onto the (stalled) worker
        for _ in range(200):
            if b._remote_jobs:
                break
            await asyncio.sleep(0.005)
        assert b._remote_jobs
        close_task = asyncio.ensure_future(b.close())
        with pytest.raises(RuntimeError):
            await asyncio.wait_for(fut, timeout=10)
        # close() joins workers with a bounded timeout; don't wait the
        # stalled worker out — the future resolving is the contract
        close_task.cancel()
        return True

    assert asyncio.run(go())


# ------------------------------------------------------------- mesh layer
def test_disaggregated_mesh_splits_and_validates():
    import jax

    from seldon_core_tpu.parallel.mesh import (DisaggregatedMesh,
                                               disaggregated_mesh)

    m = disaggregated_mesh(2)
    assert len(m.prefill_devices) == 2
    assert len(m.decode_devices) == len(jax.devices()) - 2
    # prefill takes the END of the enumeration; decode keeps the default
    # device (the batcher anchors its slot pool there)
    assert jax.devices()[0] in m.decode_devices
    assert jax.devices()[-1] in m.prefill_devices
    assert not set(map(id, m.prefill_devices)) & set(
        map(id, m.decode_devices))

    m2 = disaggregated_mesh(1, 3)
    assert len(m2.prefill_devices) == 1 and len(m2.decode_devices) == 3

    devs = jax.devices()
    m3 = disaggregated_mesh(devs[6:], devs[:2])
    assert m3.prefill_devices == devs[6:]

    with pytest.raises(ValueError, match="overlap"):
        DisaggregatedMesh(devs[:2], devs[1:3])
    with pytest.raises(ValueError, match=">=1 device per role"):
        DisaggregatedMesh([], devs[:2])
    with pytest.raises(ValueError, match="no decode devices"):
        disaggregated_mesh(len(devs))


def test_partition_prefers_physical_slice_boundaries():
    from seldon_core_tpu.parallel.multihost import (
        partition_for_disaggregation)

    class Dev:
        def __init__(self, i, s):
            self.id, self.slice_index = i, s

        def __repr__(self):
            return f"d{self.id}s{self.slice_index}"

    # two physical slices of 4: prefill_count=4 takes the whole second slice
    devs = [Dev(i, i // 4) for i in range(8)]
    pre, dec = partition_for_disaggregation(devs, 4)
    assert [d.slice_index for d in pre] == [1, 1, 1, 1]
    assert [d.slice_index for d in dec] == [0, 0, 0, 0]
    # ragged count: falls back to a contiguous tail
    pre, dec = partition_for_disaggregation(devs, 3)
    assert len(pre) == 3 and pre[0].id == 5
    with pytest.raises(ValueError):
        partition_for_disaggregation(devs, 8)
    with pytest.raises(ValueError):
        partition_for_disaggregation(devs, 0)


def test_decode_slice_must_hold_default_device(server):
    """A mesh whose decode slice excludes the process default device is
    rejected at batcher build: the slot pool lives on the default."""
    import jax

    from seldon_core_tpu.parallel.mesh import DisaggregatedMesh

    devs = jax.devices()
    bad = DisaggregatedMesh(devs[:2], devs[2:])  # default dev 0 in PREFILL
    with pytest.raises(ValueError, match="default device"):
        ContinuousBatcher(server, max_slots=2, max_len=32, len_buckets=(8,),
                          layout="dense", disagg_mesh=bad)


# ------------------------------------------------------------- validation
def test_normalize_disaggregation():
    assert normalize_disaggregation("") == "off"
    assert normalize_disaggregation(None) == "off"
    assert normalize_disaggregation("remote_prefill") == "remote_prefill"
    assert normalize_disaggregation("Remote-Prefill") == "remote_prefill"
    assert normalize_disaggregation("disagg") == "remote_prefill"
    with pytest.raises(ValueError, match="unknown disaggregation"):
        normalize_disaggregation("banana")


def test_load_validates_disagg_config():
    with pytest.raises(ValueError, match="unknown disaggregation"):
        make_server(disaggregation="banana")
    with pytest.raises(ValueError, match="must be >= 0"):
        make_server(disaggregation="remote_prefill", prefill_devices=-1)
    with pytest.raises(ValueError, match="tensor/sequence parallelism"):
        make_server(disaggregation="remote_prefill", tensor_parallel=2)


# --------------------------------------------------------------- metrics
@pytest.mark.slow  # tier-1 870s budget: runs in CI's unfiltered disagg step
def test_handoff_and_latency_series_reach_metrics(server):
    """ttft/inter-token/handoff flow llm_stats -> sync_llm -> /metrics
    (graftlint's metrics-drift check keeps the names in lockstep)."""
    from seldon_core_tpu.metrics.registry import MetricsRegistry
    from seldon_core_tpu.runtime.batcher import BatcherService

    s = make_server(disaggregation="remote_prefill", prefill_devices=2,
                    continuous_batching=2, continuous_batching_max_len=32)
    svc = BatcherService(s, max_slots=2)
    s._batcher_service = svc
    try:
        out = svc.submit_sync([3, 1, 4, 1, 5], 6)
        assert len(out) == 6
        st = s.llm_stats()
        assert st["disaggregation"] == "remote_prefill"
        assert st["handoffs_total"] == 1
        assert st["handoff_transfer_bytes_total"] > 0
        assert len(st["ttft_s"]) == 1 and st["ttft_s"][0] > 0
        assert len(st["inter_token_s"]) == 5  # 6 tokens -> 5 gaps
        assert len(st["handoff_times_s"]) == 1
        reg = MetricsRegistry(deployment="d", predictor="p")
        reg.sync_llm(s)
        text = reg.expose().decode()
        assert "seldon_llm_ttft_seconds" in text
        assert "seldon_llm_inter_token_seconds" in text
        assert "seldon_llm_handoff_seconds" in text
        assert "seldon_llm_handoffs_total" in text
        assert "seldon_llm_handoff_queue_depth" in text
    finally:
        svc.close()


def test_ttft_and_gaps_recorded_without_disaggregation():
    """The latency pair is unconditional (ROADMAP 5a): a plain single-slice
    batcher records TTFT + inter-token gaps too."""
    s = make_server()

    async def go():
        b = ContinuousBatcher(s, max_slots=2, max_len=32, len_buckets=(8,),
                              layout="paged", page_size=8)
        out = await b.submit([5, 9, 17], max_new_tokens=6)
        await b.close()
        return out

    out = asyncio.run(go())
    assert len(out) == 6
    assert len(s._ttft_times) == 1
    assert len(s._inter_token_times) == 5


# --------------------------------------------------------- replica routing
def test_replica_set_least_loaded_and_stats_merge():
    from seldon_core_tpu.runtime.engine import ReplicaSet, replica_load

    class Fake:
        def __init__(self, queued):
            self._queued = queued
            self.calls = 0

        def llm_stats(self):
            return {"tokens_generated": 10, "kv_occupancy": 0.5,
                    "decode_step_times_s": [0.01]}

        def predict(self, X, names, meta=None):
            self.calls += 1
            return ("ok", names)

    # no batcher -> (0, 0): plain components are equal targets
    a, b = Fake(0), Fake(0)
    assert replica_load(a) == (0.0, 0.0)
    rs = ReplicaSet([a, b])
    rs.predict([1], ["x"])
    assert a.calls == 1 and b.calls == 0  # ties break to the lowest index

    merged = rs.llm_stats()
    assert merged["tokens_generated"] == 20          # counters sum
    assert merged["kv_occupancy"] == 0.5             # fractions average
    assert merged["decode_step_times_s"] == [0.01, 0.01]  # lists concat
    assert rs.tags()["replicas"] == 2


def test_engine_list_component_becomes_replica_set():
    """Registering a LIST of components behind a unit name resolves to ONE
    cached ReplicaSet — the 'N decode replicas behind a predictor' shape."""
    import numpy as np

    from seldon_core_tpu.components.component import SeldonComponent
    from seldon_core_tpu.contracts.graph import PredictorSpec
    from seldon_core_tpu.contracts.payload import SeldonMessage
    from seldon_core_tpu.runtime.engine import GraphEngine, ReplicaSet

    class Echo(SeldonComponent):
        def __init__(self):
            self.calls = 0

        def predict(self, X, names, meta=None):
            self.calls += 1
            return np.asarray(X)

    replicas = [Echo(), Echo()]
    eng = GraphEngine(
        PredictorSpec.from_dict(
            {"name": "p", "graph": {"name": "m", "type": "MODEL"}}),
        components={"m": replicas})
    msg = SeldonMessage.from_dict(
        {"data": {"tensor": {"shape": [1, 1], "values": [1.0]}}})
    asyncio.run(eng.predict(msg))
    asyncio.run(eng.predict(msg))
    comp = eng._components["m"]
    assert isinstance(comp, ReplicaSet)
    # equal-load fakes: deterministic lowest-index dispatch takes both
    assert replicas[0].calls == 2 and replicas[1].calls == 0


def test_replica_set_routes_llm_replicas_end_to_end():
    """Two real LLMServer replicas behind one graph node: generate()
    routes to the least-loaded replica and returns the exact solo tokens."""
    from seldon_core_tpu.runtime.engine import ReplicaSet

    r1 = make_server()
    r2 = make_server()
    rs = ReplicaSet([r1, r2])
    expected = r1.generate([[5, 9, 17]], max_new_tokens=6)["tokens"][0]
    out = rs.generate([[5, 9, 17]], max_new_tokens=6)
    assert out["tokens"][0] == expected
    assert rs.llm_stats()["kv_cache_layout"] == r1.llm_stats()[
        "kv_cache_layout"]
