"""Tracing satellites (ISSUE 10): the span clock, W3C traceparent
hardening, OTLP export-failure accounting, and end-to-end propagation
(REST header -> engine node spans -> remote hop; gRPC metadata
round-trip). The flight-recorder span trees themselves live in
tests/test_flight.py."""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

import seldon_core_tpu.tracing as tracing
from seldon_core_tpu.metrics.registry import MetricsRegistry
from seldon_core_tpu.testing.faults import FaultClock
from seldon_core_tpu.tracing import (
    Span,
    TraceContext,
    Tracer,
    _parse_traceparent,
    current_traceparent,
    get_tracer,
    set_tracer,
    tail_thresholds,
)

TRACE_ID = "ab" * 16
SPAN_ID = "cd" * 8
VALID_TP = f"00-{TRACE_ID}-{SPAN_ID}-01"
UNSAMPLED_TP = f"00-{TRACE_ID}-{SPAN_ID}-00"


@pytest.fixture()
def fresh_tracer():
    old = get_tracer()
    t = Tracer(enabled=True)
    set_tracer(t)
    yield t
    set_tracer(old)
    tracing.anchor()  # restore the real span clock for later tests


# ---------------------------------------------------------------------------
# _parse_traceparent hardening
# ---------------------------------------------------------------------------

def test_parse_valid_sampled():
    assert _parse_traceparent(VALID_TP) == (TRACE_ID, SPAN_ID, True)


def test_parse_honors_unsampled_flag():
    assert _parse_traceparent(UNSAMPLED_TP) == (TRACE_ID, SPAN_ID, False)


def test_parse_future_version_extra_fields():
    # per W3C, unknown versions keep the first four fields' meaning
    assert _parse_traceparent(f"01-{TRACE_ID}-{SPAN_ID}-01-extrastate") == (
        TRACE_ID, SPAN_ID, True)


def test_parse_version_00_must_have_exactly_four_fields():
    # W3C trace-context §4: extra fields are only allowed for FUTURE
    # versions; a version-00 header with a fifth field is malformed
    assert _parse_traceparent(f"00-{TRACE_ID}-{SPAN_ID}-01-extra") is None


@pytest.mark.parametrize("header", [
    None,
    "",
    "garbage",
    "00-abc-def-01",                               # short fields
    f"00-{TRACE_ID}-{SPAN_ID}",                    # missing flags
    f"zz-{TRACE_ID}-{SPAN_ID}-01",                 # non-hex version
    f"ff-{TRACE_ID}-{SPAN_ID}-01",                 # forbidden version
    f"00-{'xy' * 16}-{SPAN_ID}-01",                # non-hex trace id
    f"00-{'0' * 32}-{SPAN_ID}-01",                 # all-zero trace id
    f"00-{TRACE_ID}-{'0' * 16}-01",                # all-zero span id
    f"00-{TRACE_ID[:-2]}-{SPAN_ID}-01",            # 30-hex trace id
    f"00-{TRACE_ID}-{SPAN_ID}ab-01",               # 18-hex span id
    f"00-+{TRACE_ID[:-1]}-{SPAN_ID}-01",           # int(x,16) sign tolerance
    f"00-{TRACE_ID}-{SPAN_ID}- 1",                 # whitespace in flags
    f"00- {TRACE_ID[:-1]}-{SPAN_ID}-01",           # whitespace in trace id
])
def test_parse_rejects_malformed(header):
    assert _parse_traceparent(header) is None


def test_malformed_header_starts_fresh_trace():
    ctx = TraceContext.from_traceparent("totally-not-a-traceparent",
                                        ingress="rest:/v1/generate")
    assert len(ctx.trace_id) == 32 and ctx.trace_id != TRACE_ID
    assert ctx.parent_span_id is None and ctx.sampled


def test_context_adopts_valid_header():
    ctx = TraceContext.from_traceparent(UNSAMPLED_TP, ingress="x")
    assert ctx.trace_id == TRACE_ID
    assert ctx.parent_span_id == SPAN_ID
    assert ctx.sampled is False


# ---------------------------------------------------------------------------
# Sampled-flag behavior in the tracer
# ---------------------------------------------------------------------------

def test_unsampled_span_not_recorded_and_flag_propagates(fresh_tracer):
    with fresh_tracer.span("op", traceparent=UNSAMPLED_TP) as s:
        assert s.sampled is False
        # outbound header keeps saying "don't sample" downstream
        assert s.traceparent().endswith("-00")
        assert current_traceparent() == s.traceparent()
        with fresh_tracer.span("child") as c:
            assert c.sampled is False  # inherited
    assert fresh_tracer.drain() == []


def test_sampled_span_recorded(fresh_tracer):
    with fresh_tracer.span("op", traceparent=VALID_TP) as s:
        assert s.traceparent().endswith("-01")
    spans = fresh_tracer.drain()
    assert [sp.name for sp in spans] == ["op"]
    assert spans[0].trace_id == TRACE_ID and spans[0].parent_id == SPAN_ID


# ---------------------------------------------------------------------------
# Span clock: monotonic, anchored, immune to wall steps
# ---------------------------------------------------------------------------

def test_span_duration_survives_backward_wall_step(fresh_tracer):
    """The historical bug: time.time() at both ends of a span made the
    duration negative when NTP stepped the wall clock back mid-span. The
    anchored clock's duration is purely monotonic."""
    clock = FaultClock(start=100.0)
    wall = {"t": 5_000.0}
    tracing.anchor(wall=lambda: wall["t"], mono=clock)
    with fresh_tracer.span("op") as s:
        wall["t"] -= 3600.0          # NTP steps the wall back an hour...
        clock.advance(0.25)          # ...while 250ms actually elapse
    assert s.end - s.start == pytest.approx(0.25)
    assert s.to_dict()["durationUs"] == 250_000


def test_span_absolute_time_is_anchor_plus_elapsed(fresh_tracer):
    clock = FaultClock(start=10.0)
    tracing.anchor(wall=lambda: 1_000.0, mono=clock)
    clock.advance(2.0)
    with fresh_tracer.span("op") as s:
        clock.advance(1.0)
    assert s.start == pytest.approx(1_002.0)
    assert s.end == pytest.approx(1_003.0)


def test_forward_wall_step_mid_span_also_ignored(fresh_tracer):
    clock = FaultClock(start=0.0)
    wall = {"t": 100.0}
    tracing.anchor(wall=lambda: wall["t"], mono=clock)
    with fresh_tracer.span("op") as s:
        wall["t"] += 10_000.0        # big forward step (leap smear etc.)
        clock.advance(0.5)
    assert s.end - s.start == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# OTLP export failure accounting: bounded re-enqueue, drop counter, latency
# ---------------------------------------------------------------------------

def _failing_exporter(fail_times):
    calls = []

    def exporter(spans):
        calls.append(list(spans))
        if len(calls) <= fail_times:
            raise RuntimeError("collector down")

    exporter.calls = calls
    return exporter


def test_transient_export_blip_does_not_lose_the_batch():
    tr = Tracer(enabled=True)
    tr.exporter = _failing_exporter(fail_times=1)
    with tr.span("a"):
        pass
    tr.flush()   # fails -> re-enqueued
    assert tr.spans_dropped_total == 0
    tr.flush()   # collector back -> delivered
    assert tr.spans_dropped_total == 0
    assert [s.name for s in tr.exporter.calls[1]] == ["a"]
    assert len(tr.export_stats()["export_times_s"]) == 2


def test_second_export_failure_drops_and_counts():
    tr = Tracer(enabled=True)
    tr.exporter = _failing_exporter(fail_times=10)
    with tr.span("a"):
        pass
    tr.flush()
    tr.flush()
    assert tr.spans_dropped_total == 1
    tr.flush()   # buffer empty now — nothing re-exported, nothing counted
    assert tr.spans_dropped_total == 1
    assert len(tr.exporter.calls) == 2


def test_reenqueue_respects_buffer_bound():
    tr = Tracer(enabled=True, max_buffer=2)
    tr.exporter = _failing_exporter(fail_times=10)
    spans = [Span(name=f"s{i}", trace_id=TRACE_ID, span_id=f"{i:016x}",
                  parent_id=None) for i in range(3)]
    tr.record_spans(spans)   # >= max_buffer -> auto flush -> fail
    # only max_buffer spans re-enqueue; the overflow is dropped and counted
    assert tr.spans_dropped_total == 1
    assert len(tr.drain()) == 2


def test_full_buffer_with_exporter_drops_without_inline_flush():
    """With an exporter installed, a full buffer means the collector is
    already failing: recording threads (the batcher loop!) must NEVER run
    the blocking HTTP flush inline — new spans drop and count, and the
    background flusher keeps owning the network I/O."""
    tr = Tracer(enabled=True, max_buffer=2)
    tr.exporter = _failing_exporter(fail_times=10)
    with tr.span("a"):
        pass
    with tr.span("b"):       # buffer reaches max_buffer — still no flush
        pass
    assert tr.exporter.calls == [] and tr.spans_dropped_total == 0
    extra = [Span(name=f"x{i}", trace_id=TRACE_ID, span_id=f"{i:016x}",
                  parent_id=None) for i in range(3)]
    tr.record_spans(extra)                      # full: drop, no exporter call
    with tr.span("c"):
        pass                                    # same for single spans
    assert tr.exporter.calls == []              # NO inline network attempt
    assert tr.spans_dropped_total == 4
    tr.flush()   # the background flusher's thread owns the (failing) export
    tr.flush()   # second failure drops the re-enqueued batch (bounded)
    assert len(tr.exporter.calls) == 2
    assert tr.spans_dropped_total == 6


def test_recorder_tracks_clock_reanchor():
    """A late tracing.anchor() correction (NTP fixed after boot) must reach
    the flight recorder's materialized timestamps, not just new Spans."""
    from seldon_core_tpu.runtime.flight import EV_FIRST_TOKEN, FlightRecorder
    from seldon_core_tpu.testing.faults import FaultClock

    mono = FaultClock(start=10.0)
    wall = {"t": 1_000.0}
    tracing.anchor(wall=lambda: wall["t"], mono=mono)
    try:
        fr = FlightRecorder(1)
        tr = Tracer(enabled=True)
        # the wall clock is stepped (NTP sync) and the operator re-anchors
        wall["t"] = 50_000.0
        tracing.anchor(wall=lambda: wall["t"], mono=mono)
        fr.begin(0, None, None, prompt_tokens=1)
        fr.record(0, EV_FIRST_TOKEN, tokens=1)
        fr.complete(0, "done", 1, tr)
        root = [s for s in tr.drain() if s.parent_id is None][0]
        assert root.start >= 49_000.0  # corrected epoch, not the stale one
    finally:
        tracing.anchor()


def test_sync_tracing_feeds_registry_idempotently():
    reg = MetricsRegistry(deployment="d", predictor="p")
    tr = Tracer(enabled=True)
    tr.exporter = _failing_exporter(fail_times=10)
    with tr.span("a"):
        pass
    tr.flush()
    tr.flush()               # drop 1, two export latencies observed
    tr.count_retained("tail")
    tr.count_retained("head")
    tr.count_retained("head")
    reg.sync_tracing(tr)
    reg.sync_tracing(tr)     # catch-up idiom: second sync adds nothing
    base = {"deployment_name": "d", "predictor_name": "p"}
    get = reg.registry.get_sample_value
    assert get("seldon_trace_spans_dropped_total", base) == 1
    assert get("seldon_trace_export_seconds_count", base) == 2
    assert get("seldon_llm_traces_retained_total", {**base, "mode": "tail"}) == 1
    assert get("seldon_llm_traces_retained_total", {**base, "mode": "head"}) == 2


def test_tail_thresholds_env_parsing():
    assert tail_thresholds({}) == (None, None)
    assert tail_thresholds({"TRACING_TAIL_TTFT_MS": "250"}) == (0.25, None)
    assert tail_thresholds({"TRACING_TAIL_GAP_MS": "50"}) == (None, 0.05)
    assert tail_thresholds({"TRACING_TAIL_TTFT_MS": "garbage"}) == (None, None)


# ---------------------------------------------------------------------------
# End-to-end propagation: REST -> engine node spans -> remote hop
# ---------------------------------------------------------------------------

def test_rest_header_to_engine_nodes_to_remote_hop(fresh_tracer):
    """The reference's span topology (PAPER.md §5): the inbound traceparent
    roots the server span, every graph node gets a child span, and the
    remote hop's outbound header carries the NODE span's id downstream."""
    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from seldon_core_tpu.contracts.graph import PredictorSpec
    from seldon_core_tpu.runtime.engine import GraphEngine
    from seldon_core_tpu.transport.rest import make_engine_app

    seen = {}

    async def go():
        async def remote_predict(request):
            seen["traceparent"] = request.headers.get("traceparent")
            return web.json_response(await request.json())

        remote_app = web.Application()
        remote_app.router.add_post("/predict", remote_predict)
        async with TestClient(TestServer(remote_app)) as rc:
            spec = PredictorSpec.from_dict({
                "name": "p",
                "graph": {"name": "m", "type": "MODEL",
                          "endpoint": {"service_host": "127.0.0.1",
                                       "service_port": rc.port,
                                       "type": "REST"}},
            })
            engine = GraphEngine(spec)
            app = make_engine_app(engine)
            async with TestClient(TestServer(app)) as ec:
                resp = await ec.post("/api/v0.1/predictions",
                                     json={"data": {"ndarray": [[1.0]]}},
                                     headers={"traceparent": VALID_TP})
                assert resp.status == 200

    asyncio.run(go())
    hop = seen["traceparent"]
    assert hop is not None and hop.split("-")[1] == TRACE_ID
    spans = {s.name: s for s in fresh_tracer.drain()}
    assert "predictions" in spans and "node:m" in spans
    assert all(s.trace_id == TRACE_ID for s in spans.values())
    # parenting: ingress span under the caller's span, node under ingress,
    # and the hop's outbound header names the node span
    assert spans["predictions"].parent_id == SPAN_ID
    assert spans["node:m"].parent_id == spans["predictions"].span_id
    assert hop.split("-")[2] == spans["node:m"].span_id


def test_remote_hop_without_span_sends_no_header(fresh_tracer):
    """Outside any span (tracing idle) the remote hop must not invent a
    traceparent."""
    import socket

    from aiohttp import web

    from seldon_core_tpu.contracts.graph import Endpoint
    from seldon_core_tpu.contracts.payload import SeldonMessage
    from seldon_core_tpu.runtime.remote import RemoteComponent

    seen = {}

    async def go():
        async def handler(request):
            seen["traceparent"] = request.headers.get("traceparent")
            return web.json_response(await request.json())

        app = web.Application()
        app.router.add_post("/predict", handler)
        runner = web.AppRunner(app)
        await runner.setup()
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        await web.SockSite(runner, s).start()
        comp = RemoteComponent(Endpoint(service_host="127.0.0.1",
                                        service_port=port, type="REST"))
        try:
            await comp.predict_raw(
                SeldonMessage.from_dict({"data": {"ndarray": [[1.0]]}}))
        finally:
            await comp.close()
            await runner.cleanup()

    asyncio.run(go())
    assert seen["traceparent"] is None


# ---------------------------------------------------------------------------
# gRPC metadata round-trip
# ---------------------------------------------------------------------------

class _Echo:
    def load(self):
        pass

    def predict(self, X, names, meta=None):
        return np.asarray(X)


def test_grpc_metadata_traceparent_roundtrip(fresh_tracer):
    from seldon_core_tpu.contracts.payload import SeldonMessage
    from seldon_core_tpu.transport.grpc_client import call_sync
    from seldon_core_tpu.transport.grpc_server import make_component_server

    server = make_component_server(_Echo(), port=None)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        out = call_sync(
            f"127.0.0.1:{port}", "Predict",
            SeldonMessage.from_dict({"data": {"ndarray": [[1.0, 2.0]]}}),
            metadata=[("traceparent", VALID_TP)])
        assert out.to_dict()["data"]["ndarray"] == [[1.0, 2.0]]
    finally:
        server.stop(None)
    spans = [s for s in fresh_tracer.drain() if s.name == "grpc:predict"]
    assert len(spans) == 1
    assert spans[0].trace_id == TRACE_ID and spans[0].parent_id == SPAN_ID


def test_grpc_unsampled_metadata_not_recorded(fresh_tracer):
    from seldon_core_tpu.contracts.payload import SeldonMessage
    from seldon_core_tpu.transport.grpc_client import call_sync
    from seldon_core_tpu.transport.grpc_server import make_component_server

    server = make_component_server(_Echo(), port=None)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        call_sync(f"127.0.0.1:{port}", "Predict",
                  SeldonMessage.from_dict({"data": {"ndarray": [[1.0]]}}),
                  metadata=[("traceparent", UNSAMPLED_TP)])
    finally:
        server.stop(None)
    assert fresh_tracer.drain() == []
