"""Bandit routers + outlier detectors — the reference tests these per
component (`components/routers/epsilon-greedy/test_EpsilonGreedy.py`,
outlier-detection test suites); here additionally through the in-process
graph engine (routing meta + feedback replay)."""

import asyncio
import json
import pickle

import numpy as np
import pytest

from seldon_core_tpu.analytics import (
    EpsilonGreedy,
    IsolationForestOutlierDetector,
    MahalanobisOutlierDetector,
    ThompsonSampling,
    VAEOutlierDetector,
)
from seldon_core_tpu.contracts.graph import PredictorSpec
from seldon_core_tpu.contracts.payload import Feedback, SeldonMessage
from seldon_core_tpu.runtime.engine import GraphEngine


def run(coro):
    return asyncio.run(coro)


def msg(values, shape):
    return SeldonMessage.from_dict({"data": {"tensor": {"shape": shape, "values": values}}})


X = np.array([[1.0, 2.0]])


# ---------------------------------------------------------------- routers
def test_epsilon_greedy_exploits_best_branch():
    r = EpsilonGreedy(n_branches=3, epsilon=0.0, seed=0)
    for _ in range(5):
        r.send_feedback(X, [], 1.0, None, routing=2)
        r.send_feedback(X, [], 0.0, None, routing=0)
    assert r.route(X, []) == 2
    assert r.branch_means()[2] == pytest.approx(1.0)


def test_epsilon_greedy_explores():
    r = EpsilonGreedy(n_branches=2, epsilon=1.0, seed=0)
    routes = {r.route(X, []) for _ in range(50)}
    assert routes == {0, 1}


def test_epsilon_greedy_rejects_bad_params():
    with pytest.raises(ValueError):
        EpsilonGreedy(n_branches=0)
    with pytest.raises(ValueError):
        EpsilonGreedy(epsilon=1.5)


def test_thompson_sampling_converges():
    r = ThompsonSampling(n_branches=2, seed=1)
    for _ in range(200):
        r.send_feedback(X, [], 0.9, None, routing=1)
        r.send_feedback(X, [], 0.1, None, routing=0)
    routes = [r.route(X, []) for _ in range(100)]
    assert np.mean(routes) > 0.9  # overwhelmingly prefers the good branch


def test_router_ignores_out_of_range_routing():
    r = ThompsonSampling(n_branches=2)
    r.send_feedback(X, [], 1.0, None, routing=None)
    r.send_feedback(X, [], 1.0, None, routing=7)
    assert r.pulls.sum() == 0


def test_router_pickle_roundtrip():
    r = EpsilonGreedy(n_branches=2, epsilon=0.0, seed=0)
    for _ in range(3):
        r.send_feedback(X, [], 1.0, None, routing=1)
    r2 = pickle.loads(pickle.dumps(r))
    assert r2.route(X, []) == 1
    assert list(r2.pulls) == list(r.pulls)


def test_bandit_graph_end_to_end():
    graph = {
        "name": "eg",
        "type": "ROUTER",
        "implementation": "EPSILON_GREEDY",
        "parameters": [
            {"name": "n_branches", "value": "2", "type": "INT"},
            {"name": "epsilon", "value": "0.0", "type": "FLOAT"},
            {"name": "best_branch", "value": "0", "type": "INT"},
        ],
        "children": [
            {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
            {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        ],
    }
    engine = GraphEngine(PredictorSpec.from_dict({"name": "p", "graph": graph}))
    out = run(engine.predict(msg([1.0], [1, 1]))).to_dict()
    assert out["meta"]["routing"]["eg"] == 0

    # feed rewards for branch 1 through the engine's feedback replay path
    for _ in range(5):
        fb = Feedback.from_dict(
            {
                "request": {"data": {"ndarray": [[1.0]]}},
                "response": {"meta": {"routing": {"eg": 1}}},
                "reward": 1.0,
            }
        )
        run(engine.send_feedback(fb))
    out2 = run(engine.predict(msg([1.0], [1, 1]))).to_dict()
    assert out2["meta"]["routing"]["eg"] == 1  # learned the rewarded branch
    # router surfaces its posterior in-band
    tag = out2["meta"]["tags"]["branch_means"]
    assert tag[1] == pytest.approx(1.0)


@pytest.mark.parametrize("impl,params", [
    ("EPSILON_GREEDY", [
        {"name": "n_branches", "value": "2", "type": "INT"},
        {"name": "epsilon", "value": "0.2", "type": "FLOAT"},
        {"name": "seed", "value": "0", "type": "INT"},
    ]),
    ("THOMPSON_SAMPLING", [
        {"name": "n_branches", "value": "2", "type": "INT"},
        {"name": "seed", "value": "0", "type": "INT"},
    ]),
])
def test_bandit_feedback_shifts_routing_mass(impl, params):
    """ISSUE 14 satellite regression: send-feedback through the engine's
    replay path must actually MOVE routing mass — not just flip a single
    greedy argmax — for both bandit families, because the canary router
    (analytics/canary.py) shares this exact reward path.  Seeded, so the
    mass comparison is deterministic."""
    graph = {
        "name": "b",
        "type": "ROUTER",
        "implementation": impl,
        "parameters": params,
        "children": [
            {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
            {"name": "c", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
        ],
    }
    engine = GraphEngine(PredictorSpec.from_dict({"name": "p", "graph": graph}))

    def mass(n=40):
        counts = [0, 0]
        for _ in range(n):
            out = run(engine.predict(msg([1.0], [1, 1]))).to_dict()
            counts[out["meta"]["routing"]["b"]] += 1
        return counts

    before = mass()
    for _ in range(15):  # reward branch 1, punish branch 0 — end to end
        for branch, reward in ((1, 1.0), (0, 0.0)):
            fb = Feedback.from_dict({
                "request": {"data": {"ndarray": [[1.0]]}},
                "response": {"meta": {"routing": {"b": branch}}},
                "reward": reward,
            })
            run(engine.send_feedback(fb))
    after = mass()
    assert after[1] > before[1], (
        f"{impl}: feedback did not shift routing mass "
        f"(before {before}, after {after})")
    assert after[1] >= 30  # the rewarded branch now dominates


# ------------------------------------------------------------- outliers
def test_mahalanobis_scores_outliers_higher():
    rng = np.random.default_rng(0)
    det = MahalanobisOutlierDetector(threshold=3.0, n_clip=10000)
    for _ in range(20):
        det.score(rng.normal(size=(64, 4)))
    inlier = det.score(rng.normal(size=(8, 4)))
    outlier = det.score(np.full((1, 4), 10.0))
    assert outlier[0] > inlier.max() * 2
    assert outlier[0] > det.threshold


def test_mahalanobis_transform_tags():
    rng = np.random.default_rng(1)
    det = MahalanobisOutlierDetector(threshold=3.0)
    for _ in range(10):
        det.score(rng.normal(size=(64, 3)))
    batch = np.vstack([rng.normal(size=(2, 3)), np.full((1, 3), 25.0)])
    out = det.transform_input(batch, ["a", "b", "c"])
    assert np.array_equal(out, batch)  # features pass through unchanged
    tags = det.tags()
    assert tags["is_outlier"] == [0, 0, 1]
    metric_keys = {m["key"] for m in det.metrics()}
    assert {"outlier_score_max", "n_outliers"} <= metric_keys


def test_mahalanobis_pickle_roundtrip():
    rng = np.random.default_rng(2)
    det = MahalanobisOutlierDetector()
    det.score(rng.normal(size=(32, 3)))
    det2 = pickle.loads(pickle.dumps(det))
    a = det.score(np.ones((2, 3)))
    b = det2.score(np.ones((2, 3)))
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_isolation_forest():
    rng = np.random.default_rng(3)
    train = rng.normal(size=(256, 2))
    det = IsolationForestOutlierDetector(threshold=0.0, n_estimators=50).fit(train)
    inlier = det.score(rng.normal(size=(8, 2)))
    outlier = det.score(np.full((1, 2), 8.0))
    assert outlier[0] > inlier.mean()
    assert outlier[0] > 0.0


def test_vae_outlier_detector():
    rng = np.random.default_rng(4)
    train = rng.normal(size=(256, 4)).astype(np.float32)
    det = VAEOutlierDetector(latent_dim=2, hidden_dim=32, seed=0)
    det.fit(train, epochs=150)
    inlier = det.score(rng.normal(size=(16, 4)))
    outlier = det.score(np.full((1, 4), 6.0))
    assert outlier[0] > inlier.mean() * 3
    det2 = pickle.loads(pickle.dumps(det))
    np.testing.assert_allclose(det2.score(train[:4]), det.score(train[:4]), rtol=1e-4)


def test_outlier_graph_transformer():
    """Outlier TRANSFORMER in front of a model: scores land in meta.tags."""
    rng = np.random.default_rng(5)
    det = MahalanobisOutlierDetector(threshold=3.0)
    for _ in range(10):
        det.score(rng.normal(size=(64, 2)))
    graph = {
        "name": "od",
        "type": "TRANSFORMER",
        "children": [{"name": "m", "type": "MODEL", "implementation": "SIMPLE_MODEL"}],
    }
    engine = GraphEngine(
        PredictorSpec.from_dict({"name": "p", "graph": graph}), components={"od": det}
    )
    out = run(engine.predict(msg([30.0, 30.0], [1, 2]))).to_dict()
    assert out["meta"]["tags"]["is_outlier"] == [1]
    keys = {m["key"] for m in out["meta"]["metrics"]}
    assert "outlier_score_max" in keys


@pytest.mark.slow  # tier-1 870s budget: seq2seq scoring also exercised by test_outliers; CI unit step unfiltered
def test_seq2seq_outlier_detector():
    """Seq2Seq reconstruction detector: a sine-wave series trains well; a
    noise burst reconstructs poorly and scores higher. Pickle round-trips
    (router/detector persistence contract)."""
    from seldon_core_tpu.analytics import Seq2SeqOutlierDetector

    t = np.arange(512, dtype=np.float32)
    series = np.stack([np.sin(t / 5.0), np.cos(t / 7.0)], axis=1)
    det = Seq2SeqOutlierDetector(timesteps=8, hidden_dim=24, seed=0, threshold=0.05)
    det.fit(series, epochs=300)

    inlier = det.score(series[:64])
    rng = np.random.default_rng(0)
    burst = rng.uniform(-3, 3, size=(16, 2)).astype(np.float32)
    outlier = det.score(burst)
    assert outlier.mean() > inlier.mean() * 3, (outlier.mean(), inlier.mean())
    # per-row scores align rows to their window
    assert inlier.shape == (64,)
    # 3-D input scores per sequence
    seq_scores = det.score(series[:32].reshape(4, 8, 2))
    assert seq_scores.shape == (4,)

    det2 = pickle.loads(pickle.dumps(det))
    np.testing.assert_allclose(det2.score(series[:16]), det.score(series[:16]), rtol=1e-4)


def test_seq2seq_from_graph_spec():
    """SEQ2SEQ_OD reachable as a graph implementation (4th detector family)."""
    from seldon_core_tpu.analytics import Seq2SeqOutlierDetector
    from seldon_core_tpu.components.builtin import make_builtin
    from seldon_core_tpu.contracts.graph import UnitImplementation

    det = make_builtin(UnitImplementation.SEQ2SEQ_OD, {"timesteps": 4, "threshold": 0.5})
    assert isinstance(det, Seq2SeqOutlierDetector)
    assert det.timesteps == 4 and det.threshold == 0.5


def test_sagemaker_proxy_round_trip():
    """SageMaker proxy against a local /invocations stub (JSON and CSV
    responses, error surface)."""
    import http.server
    import threading

    from seldon_core_tpu.contracts.payload import SeldonError
    from seldon_core_tpu.integrations import SageMakerProxy

    mode = {"kind": "json"}  # json | csv | scalar | err

    class Stub(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            assert self.path == "/invocations"
            body = self.rfile.read(int(self.headers["Content-Length"]))
            X = np.asarray(json.loads(body))
            if mode["kind"] == "json":
                out = (X * 2).tolist()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(json.dumps(out).encode())
            elif mode["kind"] == "scalar":
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(b"0.87")  # bare-scalar single prediction
            elif mode["kind"] == "flat":
                out = (X[:, 0] * 2).tolist()  # one score per input row
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(json.dumps(out).encode())
            elif mode["kind"] == "csv":
                lines = "\n".join(",".join(str(v * 2) for v in row) for row in X)
                self.send_response(200)
                self.send_header("Content-Type", "text/csv")
                self.end_headers()
                self.wfile.write(lines.encode())
            else:
                self.send_response(500)
                self.end_headers()
                self.wfile.write(b"boom")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Stub)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        proxy = SageMakerProxy(endpoint=f"http://127.0.0.1:{srv.server_port}")
        out = proxy.predict(np.array([[1.0, 2.0]]), ["a", "b"])
        np.testing.assert_allclose(out, [[2.0, 4.0]])
        mode["kind"] = "csv"
        out = proxy.predict(np.array([[1.0, 2.0], [3.0, 4.0]]), ["a", "b"])
        np.testing.assert_allclose(out, [[2.0, 4.0], [6.0, 8.0]])
        mode["kind"] = "scalar"
        out = proxy.predict(np.array([[1.0]]), ["a"])
        np.testing.assert_allclose(out, [[0.87]])
        mode["kind"] = "flat"
        out = proxy.predict(np.array([[1.0], [2.0], [3.0]]), ["a"])
        assert out.shape == (3, 1)  # per-row scores stay row-aligned
        np.testing.assert_allclose(out.ravel(), [2.0, 4.0, 6.0])
        mode["kind"] = "err"
        with pytest.raises(SeldonError):
            proxy.predict(np.array([[1.0]]), ["a"])
    finally:
        srv.shutdown()


# --------------------------------------------------------- replica sync
def test_replica_sync_converges_bandits(tmp_path):
    """Two serving replicas of one epsilon-greedy router share feedback via
    the G-counter ReplicaSync: each sees the other's counts, decisions use
    the combined posterior, and nothing double-counts."""
    from seldon_core_tpu.analytics import EpsilonGreedy
    from seldon_core_tpu.runtime.persistence import FileStateStore, ReplicaSync

    store = FileStateStore(str(tmp_path))
    r1 = EpsilonGreedy(n_branches=2, epsilon=0.0, seed=1)
    r2 = EpsilonGreedy(n_branches=2, epsilon=0.0, seed=2)
    s1 = ReplicaSync(r1, key="k", store=store, rid="a", period_s=999)
    s2 = ReplicaSync(r2, key="k", store=store, rid="b", period_s=999)

    # replica 1 learns branch 1 is great; replica 2 sees no feedback at all
    for _ in range(10):
        r1.send_feedback(np.zeros(1), [], reward=1.0, truth=None, routing=1)
        r1.send_feedback(np.zeros(1), [], reward=0.0, truth=None, routing=0)
    s1.sync()
    s2.sync()

    # replica 2 now exploits branch 1 purely from peer knowledge
    assert r2.route(np.zeros((1, 1)), []) == 1
    np.testing.assert_allclose(r2.branch_means(), r1.branch_means())

    # repeated syncs must not double-count (G-counter, not accumulation)
    s1.sync(); s2.sync(); s1.sync(); s2.sync()
    assert int(r2.peer_pulls.sum()) == 20
    assert int(r1.peer_pulls.sum()) == 0  # r2 never saw feedback

    # totals = own + peers on both sides
    total = (r1.pulls + r1.peer_pulls) + 0
    np.testing.assert_array_equal(total, r2.pulls + r2.peer_pulls)


def test_replica_sync_restart_resumes_own_counter(tmp_path):
    from seldon_core_tpu.analytics import ThompsonSampling
    from seldon_core_tpu.runtime.persistence import FileStateStore, ReplicaSync

    store = FileStateStore(str(tmp_path))
    r = ThompsonSampling(n_branches=3, seed=0)
    for _ in range(5):
        r.send_feedback(np.zeros(1), [], reward=1.0, truth=None, routing=2)
    ReplicaSync(r, key="k", store=store, rid="a", period_s=999).sync()

    # replica restarts: fresh object, same replica id
    r_new = ThompsonSampling(n_branches=3, seed=0)
    s_new = ReplicaSync(r_new, key="k", store=store, rid="a", period_s=999)
    assert s_new.restore_own()
    assert int(r_new.pulls[2]) == 5
    s_new.sync()
    assert int(r_new.peer_pulls.sum()) == 0  # own key excluded from peers


def test_replica_sync_requires_stats_contract():
    from seldon_core_tpu.runtime.persistence import FileStateStore, ReplicaSync

    class NoStats:
        pass

    with pytest.raises(TypeError, match="stats_snapshot"):
        ReplicaSync(NoStats(), key="k", store=FileStateStore("/tmp"), rid="x")


def test_replica_sync_shape_mismatch_guard(tmp_path):
    """A redeploy that changes n_branches must not let stale snapshots (own
    or peer) poison the new router's arrays."""
    from seldon_core_tpu.analytics import EpsilonGreedy
    from seldon_core_tpu.runtime.persistence import FileStateStore, ReplicaSync

    store = FileStateStore(str(tmp_path))
    old = EpsilonGreedy(n_branches=3, seed=0)
    for _ in range(4):
        old.send_feedback(np.zeros(1), [], reward=1.0, truth=None, routing=2)
    ReplicaSync(old, key="k", store=store, rid="a", period_s=999).sync()

    fresh = EpsilonGreedy(n_branches=2, seed=0)
    s_same = ReplicaSync(fresh, key="k", store=store, rid="a", period_s=999)
    assert not s_same.restore_own()  # stale 3-branch own snapshot rejected

    peer_view = EpsilonGreedy(n_branches=2, seed=0)
    s_other = ReplicaSync(peer_view, key="k", store=store, rid="b", period_s=999)
    s_other.sync()  # sees a's stale 3-branch snapshot as a peer
    assert peer_view.peer_pulls.tolist() == [0, 0]  # skipped, not crashed
    assert peer_view.route(np.zeros((1, 1)), []) in (0, 1)


def test_replica_sync_expires_dead_keys(tmp_path):
    """Snapshots from dead replicas older than expire_after_s are
    garbage-collected instead of biasing the posterior forever."""
    import pickle
    import time as _time

    from seldon_core_tpu.analytics import EpsilonGreedy
    from seldon_core_tpu.runtime.persistence import FileStateStore, ReplicaSync

    store = FileStateStore(str(tmp_path))
    dead = {"pulls": np.array([9, 0]), "reward_sum": np.array([9.0, 0.0]),
            "fail_sum": np.array([0.0, 0.0]), "ts": _time.time() - 3600}
    store.save("k:replica:dead", dead)

    r = EpsilonGreedy(n_branches=2, seed=0)
    s = ReplicaSync(r, key="k", store=store, rid="live", period_s=999,
                    expire_after_s=60.0)
    s.sync()
    assert r.peer_pulls.tolist() == [0, 0]  # expired, not summed
    assert store.restore("k:replica:dead") is None  # and deleted

    # fresh peers ARE summed
    fresh = dict(dead, ts=_time.time())
    store.save("k:replica:d2", fresh)
    s.sync()
    assert r.peer_pulls.tolist() == [9, 0]


def test_state_store_save_if_absent_and_unique_tmp(tmp_path):
    from seldon_core_tpu.runtime.persistence import FileStateStore

    store = FileStateStore(str(tmp_path))
    assert store.save_if_absent("claim", "a") is True
    assert store.save_if_absent("claim", "b") is False
    assert store.restore("claim") == "a"
    store.delete("claim")
    assert store.restore("claim") is None
    store.delete("claim")  # idempotent


# ------------------------------------------------------------- explainers
def test_saliency_explainer_attributions(tmp_path):
    """Gradient x input on a linear model equals weight * input exactly —
    the analytically checkable case."""
    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.analytics import SaliencyExplainer
    from seldon_core_tpu.models import get_model
    from seldon_core_tpu.servers.jaxserver import export_checkpoint

    # mlp with no hidden layers = softmax(x @ W + b); explain the max logit
    model = get_model("mlp", features=[], num_classes=3, dtype="float32")
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))
    ckpt = export_checkpoint(
        str(tmp_path / "ckpt"), model="mlp",
        kwargs={"features": [], "num_classes": 3, "dtype": "float32"},
        params=params, input_shape=[4], use_orbax=False,
    )
    exp = SaliencyExplainer(model_uri=ckpt)
    x = np.array([[0.5, -1.0, 2.0, 0.1]], dtype=np.float32)
    attr = exp.predict(x, ["a", "b", "c", "d"])
    assert attr.shape == x.shape
    assert np.isfinite(attr).all()
    # gradient of softmax-max wrt x is nonzero somewhere for a generic input
    assert np.abs(attr).max() > 0
    assert exp.tags()["explainer"] == "saliency"

    # integrated gradients path (steps > 1) also runs and differs in general
    exp_ig = SaliencyExplainer(model_uri=ckpt, steps=8)
    attr_ig = exp_ig.predict(x, ["a", "b", "c", "d"])
    assert attr_ig.shape == x.shape and np.isfinite(attr_ig).all()


def test_explainer_rendered_from_cr():
    """CRD explainer field -> explainer Deployment + Service (reference:
    proto/seldon_deployment.proto:45-51,63)."""
    from seldon_core_tpu.contracts.graph import SeldonDeploymentSpec
    from seldon_core_tpu.controlplane import render_manifests

    sdep = SeldonDeploymentSpec.from_dict({
        "name": "exp",
        "predictors": [{
            "name": "default",
            "graph": {"name": "clf", "type": "MODEL",
                      "implementation": "SIMPLE_MODEL"},
            "explainer": {"type": "saliency", "modelUri": "gs://b/ckpt"},
        }],
    })
    objs = render_manifests(sdep)
    kinds = [(m["kind"], m["metadata"]["name"]) for m in objs]
    assert ("Deployment", "exp-default-explainer") in kinds
    assert ("Service", "exp-default-explainer") in kinds
    dep = next(m for m in objs if m["metadata"]["name"] == "exp-default-explainer"
               and m["kind"] == "Deployment")
    env = {e["name"]: e["value"] for e in
           dep["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert "gs://b/ckpt" in env["PREDICTIVE_UNIT_PARAMETERS"]
    # round trip preserves the field
    assert sdep.predictors[0].to_dict()["explainer"]["type"] == "saliency"
