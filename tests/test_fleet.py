"""Distributed load-fleet tests: local multi-process fleet and TCP
master/worker mode against a live native edge (the locust master/slave
capability, `helm-charts/seldon-core-loadtesting/templates/`)."""

import json
import subprocess
import sys
import threading
import time

import pytest

from seldon_core_tpu.benchmarks.fleet import (
    merge_reports,
    run_distributed,
    run_local_fleet,
    worker_serve,
)
from seldon_core_tpu.runtime.edgeprogram import EDGE_BINARY, build_edge_binaries

from test_edge import free_port

pytestmark = pytest.mark.skipif(not build_edge_binaries(), reason="no C++ toolchain")

PROGRAM = {
    "deployment": "t", "predictor": "p", "native": True, "root": 0,
    "units": [{"name": "m", "kind": "SIMPLE_MODEL", "children": []}],
}
BODY = '{"data": {"ndarray": [[1.0, 2.0]]}}'


@pytest.fixture(scope="module")
def edge(tmp_path_factory):
    prog = tmp_path_factory.mktemp("fleet") / "prog.json"
    prog.write_text(json.dumps(PROGRAM))
    port = free_port()
    proc = subprocess.Popen([EDGE_BINARY, "--program", str(prog), "--port", str(port)],
                            stderr=subprocess.DEVNULL)
    import urllib.request

    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/live", timeout=1)
            break
        except Exception:
            time.sleep(0.05)
    yield port
    proc.terminate()
    proc.wait(timeout=10)


def job(port, **kw):
    base = {"host": "127.0.0.1", "port": port, "connections": 4,
            "duration": 1.0, "warmup": 0.2, "body": BODY}
    base.update(kw)
    return base


def test_local_fleet_merges(edge):
    report = run_local_fleet(job(edge), n_workers=2)
    assert report["workers"] == 2
    assert report["failures"] == 0
    assert report["requests"] > 100
    assert report["connections"] == 8  # 4 per worker
    assert report["latency_ms"]["p99"] > 0
    assert len(report["per_worker"]) == 2
    # merged throughput is the sum of the workers'
    assert report["throughput_rps"] == pytest.approx(
        sum(w["throughput_rps"] for w in report["per_worker"]), rel=1e-6
    )


def test_distributed_master_worker(edge):
    wport = free_port()
    t = threading.Thread(target=worker_serve, args=(wport,),
                         kwargs={"host": "127.0.0.1", "once": True}, daemon=True)
    t.start()
    time.sleep(0.2)
    report = run_distributed([f"127.0.0.1:{wport}"], job(edge))
    t.join(timeout=10)
    assert report["workers"] == 1
    assert report["failures"] == 0
    assert report["requests"] > 50


def test_worker_subprocess_cli(edge, tmp_path):
    """Full wire path through the CLI: worker process + fleet master."""
    wport = free_port()
    worker = subprocess.Popen(
        [sys.executable, "-m", "seldon_core_tpu.transport.cli",
         "loadtest-worker", "--listen", str(wport), "--host", "127.0.0.1", "--once"],
        cwd="/root/repo",
    )
    time.sleep(1.0)
    report_path = tmp_path / "report.json"
    subprocess.run(
        [sys.executable, "-m", "seldon_core_tpu.transport.cli",
         "loadtest-fleet", "127.0.0.1", str(edge),
         "--workers", f"127.0.0.1:{wport}", "--connections", "4",
         "--duration", "1", "--body", BODY, "--report", str(report_path)],
        cwd="/root/repo", check=True, capture_output=True,
    )
    worker.wait(timeout=15)
    report = json.loads(report_path.read_text())
    assert report["failures"] == 0 and report["requests"] > 50


def test_merge_reports_weighting():
    r1 = {"throughput_rps": 100.0, "requests": 100, "failures": 0, "duration_s": 1.0,
          "connections": 4, "latency_ms": {"p50": 1.0, "max": 5.0}}
    r2 = {"throughput_rps": 300.0, "requests": 300, "failures": 1, "duration_s": 1.0,
          "connections": 4, "latency_ms": {"p50": 3.0, "max": 9.0}}
    m = merge_reports([r1, r2])
    assert m["throughput_rps"] == 400.0
    assert m["failures"] == 1
    assert m["latency_ms"]["max"] == 9.0
    assert m["latency_ms"]["p50"] == pytest.approx(2.5)  # weighted 1:3


def test_fleet_contract_payloads(edge, tmp_path):
    """--contract: payloads generated from feature ranges (locust parity)."""
    contract = {
        "features": [
            {"name": "x", "ftype": "continuous", "range": [0, 1], "shape": [2]},
        ],
        "targets": [],
    }
    cpath = tmp_path / "contract.json"
    cpath.write_text(json.dumps(contract))
    report_path = tmp_path / "report.json"
    subprocess.run(
        [sys.executable, "-m", "seldon_core_tpu.transport.cli",
         "loadtest-fleet", "127.0.0.1", str(edge),
         "--local-workers", "1", "--connections", "4", "--duration", "1",
         "--contract", str(cpath), "--report", str(report_path)],
        cwd="/root/repo", check=True, capture_output=True,
    )
    report = json.loads(report_path.read_text())
    assert report["failures"] == 0 and report["requests"] > 50
