"""Resilience-layer tests: deadline propagation, circuit breakers, load
shedding, and graceful degradation — all driven by the deterministic
fault-injection harness (seldon_core_tpu.testing.faults). No wall-clock
randomness; no sleep exceeds 100ms; time moves by advancing a FaultClock.
"""

import asyncio

import numpy as np
import pytest

from seldon_core_tpu.components.component import SeldonComponent
from seldon_core_tpu.contracts.graph import PredictorSpec
from seldon_core_tpu.contracts.payload import SeldonError, SeldonMessage
from seldon_core_tpu.metrics.registry import MetricsRegistry
from seldon_core_tpu.runtime.engine import (
    TAG_DROPPED_BRANCHES,
    TAG_PARTIAL_RESPONSE,
    TAG_REROUTED,
    GraphEngine,
)
from seldon_core_tpu.runtime.resilience import (
    AdmissionController,
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    ResilienceConfig,
    ShedError,
    deadline_scope,
    effective_timeout,
    failure_counts_for_breaker,
)
from seldon_core_tpu.testing.faults import FaultClock, FaultSchedule, FaultSpec, FaultyComponent

pytestmark = pytest.mark.faults


def run(coro):
    return asyncio.run(coro)


def tensor_msg(values, shape):
    return SeldonMessage.from_dict({"data": {"tensor": {"shape": shape, "values": values}}})


def spec(graph) -> PredictorSpec:
    return PredictorSpec.from_dict({"name": "p", "graph": graph})


# ---------------------------------------------------------------------------
# Deadline budgets
# ---------------------------------------------------------------------------


def test_deadline_expires_mid_graph_skips_downstream():
    """(a) of the acceptance criteria: a budget that expires after the first
    node returns 504/DEADLINE_EXCEEDED and the downstream node NEVER runs."""
    clock = FaultClock()
    slow = FaultyComponent(FaultSchedule.always_ok(latency_s=0.2), clock=clock)
    downstream = FaultyComponent(FaultSchedule.always_ok(), clock=clock)
    engine = GraphEngine(
        spec({"name": "t", "type": "TRANSFORMER",
              "children": [{"name": "m", "type": "MODEL"}]}),
        components={"t": slow, "m": downstream},
    )
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(tensor_msg([1.0], [1, 1]),
                           deadline=Deadline(0.1, clock=clock)))
    assert exc.value.status_code == 504
    assert exc.value.reason == "DEADLINE_EXCEEDED"
    assert slow.calls == 1
    assert downstream.calls == 0  # short-circuited, not executed


def test_deadline_with_headroom_executes_whole_graph():
    clock = FaultClock()
    fast = FaultyComponent(FaultSchedule.always_ok(latency_s=0.01), clock=clock)
    downstream = FaultyComponent(FaultSchedule.always_ok(), clock=clock)
    engine = GraphEngine(
        spec({"name": "t", "type": "TRANSFORMER",
              "children": [{"name": "m", "type": "MODEL"}]}),
        components={"t": fast, "m": downstream},
    )
    out = run(engine.predict(tensor_msg([1.0], [1, 1]),
                             deadline=Deadline(1.0, clock=clock)))
    assert downstream.calls == 1
    assert out.data is not None


def test_deadline_already_expired_executes_nothing():
    clock = FaultClock()
    node = FaultyComponent(FaultSchedule.always_ok(), clock=clock)
    engine = GraphEngine(
        spec({"name": "m", "type": "MODEL"}), components={"m": node})
    d = Deadline(0.05, clock=clock)
    clock.advance(0.06)
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(tensor_msg([1.0], [1, 1]), deadline=d))
    assert exc.value.status_code == 504
    assert node.calls == 0


def test_default_deadline_from_annotation():
    clock = FaultClock()
    slow = FaultyComponent(FaultSchedule.always_ok(latency_s=0.2), clock=clock)
    downstream = FaultyComponent(FaultSchedule.always_ok(), clock=clock)
    engine = GraphEngine(
        spec({"name": "t", "type": "TRANSFORMER",
              "children": [{"name": "m", "type": "MODEL"}]}),
        components={"t": slow, "m": downstream},
        resilience=ResilienceConfig(default_deadline_ms=100.0, clock=clock),
    )
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(tensor_msg([1.0], [1, 1])))
    assert exc.value.reason == "DEADLINE_EXCEEDED"
    assert downstream.calls == 0


def test_effective_timeout_clamps_to_remaining_budget():
    clock = FaultClock()
    with deadline_scope(Deadline(2.0, clock=clock)):
        assert effective_timeout(5.0) == pytest.approx(2.0)
        assert effective_timeout(1.0) == pytest.approx(1.0)
        assert effective_timeout(None) == pytest.approx(2.0)
        clock.advance(1.5)
        assert effective_timeout(5.0) == pytest.approx(0.5)
        clock.advance(1.0)
        with pytest.raises(DeadlineExceeded):
            effective_timeout(5.0)
    # no deadline in scope: per-hop timeout passes through untouched
    assert effective_timeout(5.0) == 5.0
    assert effective_timeout(None) is None


# ---------------------------------------------------------------------------
# Circuit breakers
# ---------------------------------------------------------------------------


def breaker_engine(schedule, clock, failures=3, reset_s=1.0):
    comp = FaultyComponent(schedule, clock=clock)
    engine = GraphEngine(
        spec({"name": "m", "type": "MODEL"}),
        components={"m": comp},
        resilience=ResilienceConfig(
            breaker_failures=failures, breaker_reset_s=reset_s, clock=clock),
    )
    return engine, comp


def test_breaker_opens_rejects_half_opens_and_recovers():
    """(b) of the acceptance criteria: full open -> half-open -> closed cycle
    after the configured consecutive-failure threshold."""
    clock = FaultClock()
    # 3 errors trip the breaker; the probe (4th executed call) succeeds
    engine, comp = breaker_engine(FaultSchedule.flaps("EEEO"), clock, failures=3)
    breaker = dict(engine.breakers())["m"]

    msg = tensor_msg([1.0], [1, 1])
    for _ in range(3):
        with pytest.raises(SeldonError, match="injected fault"):
            run(engine.predict(msg))
    assert breaker.state == "open"
    assert comp.calls == 3

    # while open: rejected without executing the component
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(msg))
    assert exc.value.reason == "CIRCUIT_OPEN"
    assert exc.value.status_code == 503
    assert comp.calls == 3
    assert breaker.rejected_total == 1

    # after the reset window: half-open probe executes and closes the breaker
    clock.advance(1.1)
    out = run(engine.predict(msg))
    assert comp.calls == 4
    assert breaker.state == "closed"
    assert out.data is not None

    # and stays closed for subsequent traffic
    run(engine.predict(msg))
    assert comp.calls == 5


def test_breaker_failed_probe_reopens():
    clock = FaultClock()
    engine, comp = breaker_engine(FaultSchedule.always_fail(), clock, failures=2)
    breaker = dict(engine.breakers())["m"]
    msg = tensor_msg([1.0], [1, 1])
    for _ in range(2):
        with pytest.raises(SeldonError, match="injected fault"):
            run(engine.predict(msg))
    assert breaker.state == "open"
    clock.advance(1.1)
    with pytest.raises(SeldonError, match="injected fault"):
        run(engine.predict(msg))  # the probe itself fails...
    assert breaker.state == "open"  # ...and the breaker re-opens
    assert comp.calls == 3
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(msg))  # immediately rejected again
    assert exc.value.reason == "CIRCUIT_OPEN"
    assert comp.calls == 3


def test_breaker_half_open_probe_4xx_does_not_wedge():
    """A probe that draws a 4xx (node responded — healthy) must resolve the
    probe slot: the node answered, so the breaker closes. Regression: neither
    record ran, leaving _probe_inflight held forever (permanent 503s)."""
    clock = FaultClock()
    schedule = FaultSchedule(
        [FaultSpec.fail(status_code=503)] * 2 + [FaultSpec.fail(status_code=400)]
        + [FaultSpec.ok()])
    engine, comp = breaker_engine(schedule, clock, failures=2)
    breaker = dict(engine.breakers())["m"]
    msg = tensor_msg([1.0], [1, 1])
    for _ in range(2):
        with pytest.raises(SeldonError):
            run(engine.predict(msg))
    assert breaker.state == "open"
    clock.advance(1.1)
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(msg))  # the probe: node responds with a 400
    assert exc.value.status_code == 400
    assert breaker.state == "closed"  # responded => healthy, not wedged
    out = run(engine.predict(msg))  # traffic flows again
    assert out.data is not None and comp.calls == 4


def test_breaker_cancelled_probe_releases_slot():
    """Cancellation judges nothing: the probe slot frees so the NEXT call can
    probe, and the breaker stays half-open rather than wedging or re-opening."""
    clock = FaultClock()
    b = CircuitBreaker("n", failure_threshold=1, reset_timeout_s=1.0, clock=clock)
    b.record_failure()
    assert b.state == "open"
    clock.advance(1.1)
    assert b.allow()  # probe slot taken
    assert not b.allow()
    b.release_probe()  # probe cancelled mid-flight
    assert b.state == "half_open"
    assert b.allow()  # next call can probe immediately
    b.record_success()
    assert b.state == "closed"


def test_cancellation_never_counts_as_breaker_failure():
    assert not failure_counts_for_breaker(asyncio.CancelledError())
    assert not failure_counts_for_breaker(BreakerOpen("m", 1.0))
    assert failure_counts_for_breaker(TimeoutError())
    assert failure_counts_for_breaker(SeldonError("x", status_code=503))
    assert not failure_counts_for_breaker(SeldonError("x", status_code=400))


def test_breaker_client_errors_do_not_trip():
    clock = FaultClock()
    schedule = FaultSchedule([FaultSpec.fail(status_code=400)] * 10)
    engine, comp = breaker_engine(schedule, clock, failures=2)
    breaker = dict(engine.breakers())["m"]
    for _ in range(5):
        with pytest.raises(SeldonError):
            run(engine.predict(tensor_msg([1.0], [1, 1])))
    assert breaker.state == "closed"  # 4xx never opens a breaker
    assert comp.calls == 5


def test_local_sync_nodes_get_no_breaker():
    class Local(SeldonComponent):
        def predict(self, X, names, meta=None):
            return X

    engine = GraphEngine(spec({"name": "m", "type": "MODEL"}), components={"m": Local()})
    assert engine.breakers() == []


def test_router_reroutes_around_open_branch():
    clock = FaultClock()

    class Pick0(SeldonComponent):
        def route(self, X, names):
            return 0

    class Const(SeldonComponent):
        def predict(self, X, names, meta=None):
            return np.array([[42.0]])

    flaky = FaultyComponent(FaultSchedule.always_fail(), clock=clock)
    engine = GraphEngine(
        spec({"name": "r", "type": "ROUTER", "children": [
            {"name": "a", "type": "MODEL"}, {"name": "b", "type": "MODEL"}]}),
        components={"r": Pick0(), "a": flaky, "b": Const()},
        resilience=ResilienceConfig(breaker_failures=2, breaker_reset_s=60.0, clock=clock),
    )
    msg = tensor_msg([1.0], [1, 1])
    for _ in range(2):
        with pytest.raises(SeldonError, match="injected fault"):
            run(engine.predict(msg))
    assert dict(engine.breakers())["a"].state == "open"

    # router still picks 0, but the engine reroutes to healthy branch 1
    out = run(engine.predict(msg))
    d = out.to_dict()
    assert d["data"]["tensor"]["values"] == [42.0]
    assert d["meta"]["routing"] == {"r": 1}
    assert d["meta"]["tags"][TAG_REROUTED] == {"r": {"from": 0, "to": 1}}
    assert flaky.calls == 2  # open branch never executed again


def test_combiner_drops_open_branch_when_partial_allowed():
    clock = FaultClock()
    flaky = FaultyComponent(FaultSchedule.always_fail(), clock=clock)

    class Const(SeldonComponent):
        def predict(self, X, names, meta=None):
            return np.array([[10.0, 20.0]])

    graph = {
        "name": "c", "type": "COMBINER", "implementation": "AVERAGE_COMBINER",
        "children": [{"name": "m1", "type": "MODEL"}, {"name": "m2", "type": "MODEL"}],
    }
    engine = GraphEngine(
        spec(graph),
        components={"m1": flaky, "m2": Const()},
        resilience=ResilienceConfig(
            breaker_failures=2, breaker_reset_s=60.0, allow_partial=True, clock=clock),
    )
    msg = tensor_msg([1.0], [1, 1])
    # real failures (breaker closed) still fail the whole request
    for _ in range(2):
        with pytest.raises(SeldonError, match="injected fault"):
            run(engine.predict(msg))
    assert dict(engine.breakers())["m1"].state == "open"

    # open branch is dropped; the combiner averages the surviving branch
    out = run(engine.predict(msg))
    d = out.to_dict()
    assert d["data"]["tensor"]["values"] == [10.0, 20.0]
    assert d["meta"]["tags"][TAG_PARTIAL_RESPONSE] is True
    assert d["meta"]["tags"][TAG_DROPPED_BRANCHES] == ["m1"]
    assert flaky.calls == 2


def test_combiner_open_branch_fails_request_without_allow_partial():
    clock = FaultClock()
    flaky = FaultyComponent(FaultSchedule.always_fail(), clock=clock)

    class Const(SeldonComponent):
        def predict(self, X, names, meta=None):
            return np.array([[1.0]])

    graph = {
        "name": "c", "type": "COMBINER", "implementation": "AVERAGE_COMBINER",
        "children": [{"name": "m1", "type": "MODEL"}, {"name": "m2", "type": "MODEL"}],
    }
    engine = GraphEngine(
        spec(graph),
        components={"m1": flaky, "m2": Const()},
        resilience=ResilienceConfig(breaker_failures=2, breaker_reset_s=60.0, clock=clock),
    )
    msg = tensor_msg([1.0], [1, 1])
    for _ in range(2):
        with pytest.raises(SeldonError):
            run(engine.predict(msg))
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(msg))
    assert exc.value.reason == "CIRCUIT_OPEN"


def test_combiner_all_branches_open_raises():
    clock = FaultClock()
    f1 = FaultyComponent(FaultSchedule.always_fail(), clock=clock)
    f2 = FaultyComponent(FaultSchedule.always_fail(), clock=clock)
    graph = {
        "name": "c", "type": "COMBINER", "implementation": "AVERAGE_COMBINER",
        "children": [{"name": "m1", "type": "MODEL"}, {"name": "m2", "type": "MODEL"}],
    }
    engine = GraphEngine(
        spec(graph),
        components={"m1": f1, "m2": f2},
        resilience=ResilienceConfig(
            breaker_failures=1, breaker_reset_s=60.0, allow_partial=True, clock=clock),
    )
    msg = tensor_msg([1.0], [1, 1])
    with pytest.raises(SeldonError):
        run(engine.predict(msg))  # trips both breakers (threshold 1)
    with pytest.raises(SeldonError) as exc:
        run(engine.predict(msg))
    assert exc.value.reason == "CIRCUIT_OPEN"
    assert "every branch dropped" in exc.value.message


# ---------------------------------------------------------------------------
# Breaker unit-level state machine
# ---------------------------------------------------------------------------


def test_breaker_state_machine_codes_and_transitions():
    clock = FaultClock()
    b = CircuitBreaker("n", failure_threshold=2, reset_timeout_s=5.0, clock=clock)
    seen = []
    b.on_transition = lambda name, to: seen.append((name, to))
    assert b.allow() and b.state_code() == 0
    b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open" and b.state_code() == 2
    assert not b.allow()
    assert not b.available()
    assert b.retry_in_s() == pytest.approx(5.0)
    clock.advance(5.0)
    assert b.available()  # peek does not consume the probe
    assert b.allow()  # first probe
    assert b.state == "half_open" and b.state_code() == 1
    assert not b.allow()  # only one probe at a time
    b.record_success()
    assert b.state == "closed"
    assert seen == [("n", "open"), ("n", "half_open"), ("n", "closed")]


def test_breaker_disabled_with_zero_threshold():
    cfg = ResilienceConfig(breaker_failures=0)
    assert cfg.make_breaker("m") is None


def test_resilience_config_from_annotations():
    cfg = ResilienceConfig.from_annotations({
        "seldon.io/circuit-breaker-max-failures": "7",
        "seldon.io/circuit-breaker-reset-ms": "1500",
        "seldon.io/allow-partial": "true",
        "seldon.io/deadline-default-ms": "250",
    })
    assert cfg.breaker_failures == 7
    assert cfg.breaker_reset_s == pytest.approx(1.5)
    assert cfg.allow_partial is True
    assert cfg.default_deadline_ms == pytest.approx(250.0)
    # garbage/missing values keep defaults
    cfg = ResilienceConfig.from_annotations({"seldon.io/circuit-breaker-max-failures": "x"})
    assert cfg.breaker_failures == 5 and cfg.allow_partial is False


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


def test_admission_disabled_by_default():
    a = AdmissionController()
    assert not a.enabled
    run(a.acquire())  # no-ops
    a.acquire_sync()
    a.release()


def test_admission_sheds_when_full():
    async def go():
        a = AdmissionController(max_inflight=2, max_queue=0, retry_after_s=3)
        await a.acquire()
        await a.acquire()
        with pytest.raises(ShedError) as exc:
            await a.acquire()
        assert exc.value.status_code == 503
        assert exc.value.reason == "RESOURCE_EXHAUSTED"
        assert exc.value.retry_after_s == 3
        assert a.shed_total == 1
        a.release()
        await a.acquire()  # slot free again
        assert a.inflight == 2

    run(go())


def test_admission_queue_grants_fifo():
    async def go():
        a = AdmissionController(max_inflight=1, max_queue=2)
        await a.acquire()
        order = []

        async def waiter(tag):
            await a.acquire()
            order.append(tag)

        w1 = asyncio.ensure_future(waiter("first"))
        await asyncio.sleep(0)
        w2 = asyncio.ensure_future(waiter("second"))
        await asyncio.sleep(0)
        assert a.queue_depth() == 2
        with pytest.raises(ShedError):
            await a.acquire()  # queue full
        a.release()
        await w1
        a.release()
        await w2
        assert order == ["first", "second"]

    run(go())


def test_admission_sync_and_async_share_slots():
    async def go():
        a = AdmissionController(max_inflight=1, max_queue=1)
        await a.acquire()
        fut = asyncio.ensure_future(a.acquire())
        await asyncio.sleep(0)  # async waiter occupies the one queue slot
        with pytest.raises(ShedError):
            a.acquire_sync(timeout_s=0.01)  # sync path sees the full queue
        a.release()  # slot hands over to the queued async waiter
        await fut
        assert a.inflight == 1
        a.release()
        assert a.inflight == 0

    run(go())


def test_admission_from_annotations_and_env():
    a = AdmissionController.from_annotations(
        {"seldon.io/max-inflight": "8", "seldon.io/max-queue": "16"}, env={})
    assert a.max_inflight == 8 and a.max_queue == 16 and a.enabled
    a = AdmissionController.from_annotations(
        None, env={"SELDON_MAX_INFLIGHT": "4", "SELDON_SHED_RETRY_AFTER_S": "2.5"})
    assert a.max_inflight == 4 and a.retry_after_s == 2.5
    a = AdmissionController.from_annotations(None, env={})
    assert not a.enabled


def test_microbatcher_flush_is_deadline_free():
    """The flusher task snapshots the context of the request that created it;
    a stale (even expired) deadline must not poison merged batches."""
    from seldon_core_tpu.runtime.microbatch import MicroBatcher

    class Echo(SeldonComponent):
        def predict(self, X, names, meta=None):
            return np.asarray(X)

    engine = GraphEngine(spec({"name": "m", "type": "MODEL"}), components={"m": Echo()})
    mb = MicroBatcher(engine, max_batch=2, max_delay_ms=1.0)
    clock = FaultClock()
    expired = Deadline(0.01, clock=clock)
    clock.advance(1.0)

    async def go():
        with deadline_scope(expired):  # ambient context: an exhausted budget
            a = asyncio.ensure_future(mb.predict(tensor_msg([1.0], [1, 1])))
            b = asyncio.ensure_future(mb.predict(tensor_msg([2.0], [1, 1])))
            return await asyncio.gather(a, b)

    out_a, out_b = run(go())
    assert out_a.data is not None and out_b.data is not None


# ---------------------------------------------------------------------------
# Metrics visibility
# ---------------------------------------------------------------------------


def test_breaker_state_and_transitions_in_metrics():
    clock = FaultClock()
    engine, comp = breaker_engine(FaultSchedule.always_fail(), clock, failures=1)
    registry = MetricsRegistry(deployment="d", predictor="p")
    registry.sync_resilience(engine=engine)  # wires transition counters
    with pytest.raises(SeldonError):
        run(engine.predict(tensor_msg([1.0], [1, 1])))
    with pytest.raises(SeldonError):
        run(engine.predict(tensor_msg([1.0], [1, 1])))  # rejected by breaker
    registry.sync_resilience(engine=engine)
    text = registry.expose().decode()
    assert 'seldon_resilience_breaker_state{deployment_name="d",node="m",predictor_name="p"} 2.0' in text
    assert 'seldon_resilience_breaker_transitions_total{deployment_name="d",node="m",predictor_name="p",to="open"} 1.0' in text
    assert 'seldon_resilience_breaker_rejected_total{deployment_name="d",node="m",predictor_name="p"} 1.0' in text
