"""Native shared-memory staging ring: build, single-process semantics,
wraparound, cross-process MPMC correctness, and tensor round-trip."""

import multiprocessing as mp
import os

import numpy as np
import pytest

from seldon_core_tpu.native import SharedRing, build_native, native_available

pytestmark = pytest.mark.skipif(not native_available(), reason="no C++ toolchain")


@pytest.fixture()
def ring(tmp_path):
    r = SharedRing(str(tmp_path / "ring"), capacity=8, slot_size=4096, create=True)
    yield r
    r.close()


def test_build_produces_so():
    assert os.path.exists(build_native())


def test_push_pop_fifo(ring):
    for i in range(5):
        assert ring.push(f"msg{i}".encode())
    assert len(ring) == 5
    assert [ring.pop() for _ in range(5)] == [f"msg{i}".encode() for i in range(5)]
    assert ring.pop() is None


def test_full_and_wraparound(ring):
    for i in range(8):
        assert ring.push(bytes([i]))
    assert not ring.push(b"overflow")  # full
    assert ring.pop() == bytes([0])
    assert ring.push(b"wrapped")  # freed slot reused
    got = [ring.pop() for _ in range(8)]
    assert got[-1] == b"wrapped"


def test_payload_too_large(ring):
    from seldon_core_tpu.native.staging import PayloadTooLarge

    with pytest.raises(PayloadTooLarge):
        ring.push(b"x" * 5000)


def test_tensor_roundtrip(ring):
    arr = np.arange(256, dtype=np.float32).reshape(16, 16)
    assert ring.push(arr.tobytes())
    back = np.frombuffer(ring.pop(), dtype=np.float32).reshape(16, 16)
    np.testing.assert_array_equal(back, arr)


def test_attach_sees_existing_items(ring, tmp_path):
    ring.push(b"hello")
    other = SharedRing(str(tmp_path / "ring"), create=False)
    try:
        assert other.pop() == b"hello"
    finally:
        other.close()


def _producer(path, worker_id, n):
    r = SharedRing(path, create=False)
    for i in range(n):
        r.push_wait(worker_id.to_bytes(2, "little") + i.to_bytes(4, "little"), timeout_s=30)
    r.close()


def test_multiprocess_producers(tmp_path):
    """4 producer processes, 1 consumer: every message arrives exactly once
    and per-producer FIFO order is preserved."""
    path = str(tmp_path / "mpring")
    ring = SharedRing(path, capacity=64, slot_size=64, create=True)
    n_per, workers = 200, 4
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=_producer, args=(path, w, n_per)) for w in range(workers)]
    for p in procs:
        p.start()
    seen = {w: [] for w in range(workers)}
    total = n_per * workers
    got = 0
    while got < total:
        for item in ring.pop_batch(32, wait_s=10.0):
            w = int.from_bytes(item[:2], "little")
            i = int.from_bytes(item[2:6], "little")
            seen[w].append(i)
            got += 1
    for p in procs:
        p.join(timeout=30)
        assert p.exitcode == 0
    ring.close()
    for w in range(workers):
        assert seen[w] == list(range(n_per))  # per-producer FIFO


def test_np_rng_parity_numpy_and_cpython():
    """The native seeded-router RNG replays (native/np_rng.h, exposed via
    ctypes hooks in ring.cc) must match numpy's default_rng and CPython's
    random.Random DRAW-FOR-DRAW, including numpy's buffered-uint32
    interleaving between random() and integers() — this is the proof that
    lets seeded routers compile to the native edge."""
    import ctypes
    import random as pyrandom

    from seldon_core_tpu.native.staging import build_native

    lib = ctypes.CDLL(build_native())
    protos = [
        ("np_rng_new", ctypes.c_void_p, [ctypes.c_uint64]),
        ("np_rng_free", None, [ctypes.c_void_p]),
        ("np_rng_random", ctypes.c_double, [ctypes.c_void_p]),
        ("np_rng_next64", ctypes.c_uint64, [ctypes.c_void_p]),
        ("np_rng_integers", ctypes.c_uint64, [ctypes.c_void_p, ctypes.c_uint64]),
        ("py_rng_new", ctypes.c_void_p, [ctypes.c_uint64]),
        ("py_rng_free", None, [ctypes.c_void_p]),
        ("py_rng_random", ctypes.c_double, [ctypes.c_void_p]),
        ("py_rng_randrange", ctypes.c_uint64, [ctypes.c_void_p, ctypes.c_uint64]),
    ]
    for fname, res, args in protos:
        f = getattr(lib, fname)
        f.restype = res
        f.argtypes = args

    for seed in (0, 7, 3, 123456789, 2**40 + 17, 2**52 + 1):
        h = lib.np_rng_new(seed)
        ref = np.random.default_rng(seed)
        assert [lib.np_rng_next64(h) for _ in range(8)] == [
            int(x) for x in ref.integers(0, 2**64, 8, dtype=np.uint64)
        ], seed
        lib.np_rng_free(h)

    # interleaved random()/integers() across bucket sizes (exercises the
    # Lemire path, the power-of-two path, and the uint32 buffer carry)
    h = lib.np_rng_new(7)
    ref = np.random.default_rng(7)
    for i in range(5000):
        if i % 3 == 0:
            n = 2 + i % 7
            assert lib.np_rng_integers(h, n) == int(ref.integers(n)), i
        else:
            assert lib.np_rng_random(h) == float(ref.random()), i
    lib.np_rng_free(h)

    for seed in (0, 7, 3, 987654321, 2**41 + 5):
        h = lib.py_rng_new(seed)
        ref2 = pyrandom.Random(seed)
        for i in range(3000):
            if i % 3 == 0:
                n = 2 + i % 7
                assert lib.py_rng_randrange(h, n) == ref2.randrange(n), (seed, i)
            else:
                assert lib.py_rng_random(h) == ref2.random(), (seed, i)
        lib.py_rng_free(h)


def test_pop_many_distinguishes_oversized_first_frame(ring):
    """ADVICE r4: scr_pop_many returned 0 both for 'empty' and 'first frame
    does not fit in out_cap' — an undersized caller would spin forever on a
    non-empty ring. It must return -3 (matching scr_pop) instead."""
    import ctypes

    assert ring.push(b"x" * 600)
    lib = ring._lib
    small = ctypes.create_string_buffer(64)  # < 4 + 600
    used = ctypes.c_uint32(0)
    n = lib.scr_pop_many(ring._h, small, len(small), 8, ctypes.byref(used))
    assert n == -3
    # frame left in place: a properly sized drain still gets it
    big = ctypes.create_string_buffer(4096)
    n = lib.scr_pop_many(ring._h, big, len(big), 8, ctypes.byref(used))
    assert n == 1
    # and empty still reads as 0, not -3
    n = lib.scr_pop_many(ring._h, big, len(big), 8, ctypes.byref(used))
    assert n == 0


def test_np_rng_gamma_beta_parity():
    """VERDICT r4 #3: the ziggurat normal/exponential + Marsaglia-Tsang
    gamma + Johnk/two-gamma beta replays (np_rng.h over the tables
    extracted by native/gen_ziggurat_tables.py) must match numpy's
    Generator DRAW-FOR-DRAW across every sampler code path — the proof
    that lets SEEDED Thompson routing compile to the native edge."""
    import ctypes

    from seldon_core_tpu.native.staging import build_native

    lib = ctypes.CDLL(build_native())
    for fname, res, args in [
        ("np_rng_new", ctypes.c_void_p, [ctypes.c_uint64]),
        ("np_rng_free", None, [ctypes.c_void_p]),
        ("np_rng_integers", ctypes.c_uint64, [ctypes.c_void_p, ctypes.c_uint64]),
        ("np_rng_standard_normal", ctypes.c_double, [ctypes.c_void_p]),
        ("np_rng_standard_exponential", ctypes.c_double, [ctypes.c_void_p]),
        ("np_rng_standard_gamma", ctypes.c_double, [ctypes.c_void_p, ctypes.c_double]),
        ("np_rng_beta", ctypes.c_double, [ctypes.c_void_p, ctypes.c_double, ctypes.c_double]),
    ]:
        f = getattr(lib, fname)
        f.restype = res
        f.argtypes = args

    for seed in (0, 7, 123456789, 2**40 + 17):
        h = lib.np_rng_new(seed)
        ref = np.random.default_rng(seed)
        assert [lib.np_rng_standard_normal(h) for _ in range(3000)] == list(
            ref.standard_normal(3000)), seed
        lib.np_rng_free(h)

        h = lib.np_rng_new(seed)
        ref = np.random.default_rng(seed)
        assert [lib.np_rng_standard_exponential(h) for _ in range(3000)] == list(
            ref.standard_exponential(3000)), seed
        lib.np_rng_free(h)

    # every gamma path: 0 (degenerate), <1 (boost), ==1 (exponential
    # ziggurat), >1 (Marsaglia-Tsang incl. the squeeze-reject tail)
    for shape in (0.0, 0.05, 0.3, 0.9999, 1.0, 1.0001, 4.0 / 3.0, 2.5, 17.0, 500.0):
        for seed in (0, 3):
            h = lib.np_rng_new(seed)
            ref = np.random.default_rng(seed)
            assert [lib.np_rng_standard_gamma(h, shape) for _ in range(600)] == list(
                ref.standard_gamma(shape, 600)), (shape, seed)
            lib.np_rng_free(h)

    # beta: Johnk (both <=1), mixed, two-gamma; plus the Thompson shape —
    # elementwise array draws interleaved with Lemire integers (the
    # uint32 buffer must carry across beta's next64-only consumption)
    # (0.001, 0.001) drives the pow-underflow log-space Johnk branch on
    # ~24% of draws — a desync there poisons every later routing decision
    pairs = [(1.0, 1.0), (0.5, 0.5), (0.3, 0.9), (1.0, 2.0), (2.0, 1.0),
             (1.5, 3.25), (30.0, 2.0), (0.5, 2.0),
             (0.001, 0.001), (0.005, 0.005)]
    for a, b in pairs:
        h = lib.np_rng_new(11)
        ref = np.random.default_rng(11)
        assert [lib.np_rng_beta(h, a, b) for _ in range(500)] == list(
            ref.beta(a, b, 500)), (a, b)
        lib.np_rng_free(h)

    h = lib.np_rng_new(42)
    ref = np.random.default_rng(42)
    a = np.array([1.0, 3.5, 1.0, 0.7])
    b = np.array([2.0, 1.0, 1.0, 0.7])
    for i in range(400):
        want = ref.beta(a, b)
        got = [lib.np_rng_beta(h, ai, bi) for ai, bi in zip(a, b)]
        assert got == list(want), i
        if i % 5 == 0:
            assert lib.np_rng_integers(h, 3) == int(ref.integers(3)), i
    lib.np_rng_free(h)
