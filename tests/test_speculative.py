"""Speculative decoding parity + bookkeeping (ISSUE 8 tentpole).

The bar is the same one the pipelined batcher (PR 3) and the paged cache
(PR 7) already hold: speculation may change HOW MANY tokens arrive per
target forward, never WHICH tokens. Greedy and seeded-sampled outputs
through the speculative batcher must be bit-exact vs non-speculative
``generate()`` across K in {1, 2, 4}, both KV dtypes and both layouts —
the rng chain advances per ACCEPTED token, so the key state after any
prefix equals sequential decode's after the same prefix.

Redundant-coverage combos are marked ``slow`` (the 870s tier-1 budget);
all of them run in CI's unfiltered unit step, and this file is pinned as
its own CI step like the paged parity suite.
"""

import asyncio

import pytest

from seldon_core_tpu.runtime.batcher import ContinuousBatcher
from seldon_core_tpu.runtime.spec import SpecController, normalize_spec_mode
from seldon_core_tpu.servers.llmserver import LLMServer

KW = dict(vocab_size=96, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
          ffn_dim=64, max_seq_len=96)

# one shape vocabulary for every batcher in this file, so jit caches hit
# across tests (each (S, K, hist_len, mode, layout) tuple is a compile)
BKW = dict(max_slots=2, max_len=32, len_buckets=(8,), pipeline_depth=2)


def make_server(**extra) -> LLMServer:
    base = dict(model="transformer", model_kwargs=KW, init_random=True,
                max_new_tokens=8, len_buckets=(16,), batch_buckets=(1, 4),
                temperature=0.0, eos_id=-1, seed=3)
    base.update(extra)
    s = LLMServer(**base)
    s.load()
    return s


@pytest.fixture(scope="module")
def server():
    return make_server()


@pytest.fixture(scope="module")
def sampled_server():
    return make_server(temperature=0.8, top_k=20, seed=5)


@pytest.fixture(scope="module")
def int8_server():
    return make_server(kv_cache_dtype="int8", temperature=0.8, top_k=20,
                       seed=5)


@pytest.fixture(scope="module")
def draft_server():
    # draft config == target config and both random-init from the server
    # seed -> the draft is a bit-identical copy: the PERFECT drafter, whose
    # proposals the target must accept wholesale (greedy). Any parity break
    # here is a chain bug, never a drafting-quality artifact.
    return make_server(spec_mode="draft", draft_model="transformer",
                       draft_model_kwargs=KW)


def run_batch(server, prompts, *, n=8, seeds=None, **batcher_kw):
    kw = dict(BKW)
    kw.update(batcher_kw)

    async def go():
        b = ContinuousBatcher(server, **kw)
        outs = await asyncio.gather(*[
            b.submit(p, max_new_tokens=n,
                     seed=None if seeds is None else seeds[i])
            for i, p in enumerate(prompts)])
        stats = b.spec_stats()
        stats["admit_inflight"] = b._last_admit_inflight
        stats["hwm"] = b._inflight_hwm
        await b.close()
        return outs, stats

    return asyncio.run(go())


PROMPTS = [[5, 9, 17], [40, 3, 22, 8, 11], [7], [60, 61, 62, 63]]
# repetitive prompt: the n-gram proposer's home turf (and greedy decode
# falls into a cycle it then predicts perfectly)
REP = [3, 7, 11, 3, 7, 11, 3, 7, 11, 3, 7]


@pytest.fixture(scope="module")
def expected(server):
    return [server.generate([p], max_new_tokens=8)["tokens"][0]
            for p in PROMPTS]


# ----------------------------------------------------------- greedy parity
@pytest.mark.parametrize("k", [
    # tier-1 870s budget keeps the default depth; the K sweep rides CI's
    # unfiltered steps
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(2, marks=pytest.mark.slow),
    4,
])
def test_ngram_greedy_parity_dense(server, expected, k):
    outs, _ = run_batch(server, PROMPTS, layout="dense", spec_mode="ngram",
                        spec_k=k)
    assert outs == expected


def test_ngram_greedy_parity_paged(server, expected):
    outs, _ = run_batch(server, PROMPTS, layout="paged", page_size=8,
                        spec_mode="ngram", spec_k=4)
    assert outs == expected


@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 2])
def test_ngram_greedy_parity_paged_small_k(server, expected, k):
    outs, _ = run_batch(server, PROMPTS, layout="paged", page_size=8,
                        spec_mode="ngram", spec_k=k)
    assert outs == expected


# ------------------------------------------------------ seeded-sampled parity
SEEDED_PROMPTS = [[5, 9, 17, 2], [40, 3, 22], [7, 7, 7, 7, 7]]
SEEDS = [42, 1234, 7]


@pytest.mark.parametrize("layout", [
    # tier-1 870s budget keeps paged (the serving default; dense greedy
    # parity stays tier-1 above) — dense seeded rides CI's unfiltered steps
    pytest.param("dense", marks=pytest.mark.slow),
    "paged",
])
def test_ngram_seeded_parity(sampled_server, layout):
    """Seeded sampling through the verify step stays on generate()'s exact
    per-slot rng chain: one split per ACCEPTED token, never per forward."""
    expected = [sampled_server.generate([p], max_new_tokens=8, seed=s)["tokens"][0]
                for p, s in zip(SEEDED_PROMPTS, SEEDS)]
    kw = dict(page_size=8) if layout == "paged" else {}
    outs, _ = run_batch(sampled_server, SEEDED_PROMPTS, seeds=SEEDS,
                        layout=layout, spec_mode="ngram", spec_k=4, **kw)
    assert outs == expected


@pytest.mark.parametrize("layout", [
    # dense int8 is the redundant corner (dense layout + int8 write path
    # are each already covered tier-1); the paged param keeps int8 KV in
    # the tier-1 matrix — same trim as the paged parity suite (PR 7)
    pytest.param("dense", marks=pytest.mark.slow),
    # tier-1 870s budget: int8+spec rides CI's unfiltered speculative
    # step; tier-1 keeps seeded spec via test_ngram_seeded_parity[paged]
    pytest.param("paged", marks=pytest.mark.slow),
])
def test_int8_seeded_parity(int8_server, layout):
    """int8 KV x both layouts: quantize-on-write of a K-token verify block
    must round-trip identically to sequential single-token writes (scales
    are per-position, so block width cannot change them)."""
    expected = [int8_server.generate([p], max_new_tokens=8, seed=s)["tokens"][0]
                for p, s in zip(SEEDED_PROMPTS, SEEDS)]
    kw = dict(page_size=8) if layout == "paged" else {}
    outs, _ = run_batch(int8_server, SEEDED_PROMPTS, seeds=SEEDS,
                        layout=layout, spec_mode="ngram", spec_k=4, **kw)
    assert outs == expected


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_int8_greedy_parity(layout):
    s8 = make_server(kv_cache_dtype="int8")
    expected = [s8.generate([p], max_new_tokens=8)["tokens"][0]
                for p in PROMPTS]
    kw = dict(page_size=8) if layout == "paged" else {}
    outs, _ = run_batch(s8, PROMPTS, layout=layout, spec_mode="ngram",
                        spec_k=4, **kw)
    assert outs == expected


# --------------------------------------------------------- draft-model path
def test_draft_model_greedy_parity_dense(draft_server):
    expected = [draft_server.generate([p], max_new_tokens=8)["tokens"][0]
                for p in PROMPTS]
    outs, st = run_batch(draft_server, PROMPTS, layout="dense",
                         spec_mode="draft", spec_k=4)
    assert outs == expected
    # the perfect drafter's proposals all verify: acceptance 1.0 and the
    # multiplier approaches K+1 (EOS-less 8-token budgets cap the tail)
    assert st["spec_accept_rate"] == pytest.approx(1.0)
    assert st["spec_tokens_per_forward"] > 2.0


@pytest.mark.slow
def test_draft_model_seeded_parity_paged():
    s = make_server(spec_mode="draft", draft_model="transformer",
                    draft_model_kwargs=KW, temperature=0.8, top_k=20, seed=5)
    expected = [s.generate([p], max_new_tokens=8, seed=sd)["tokens"][0]
                for p, sd in zip(SEEDED_PROMPTS, SEEDS)]
    outs, _ = run_batch(s, SEEDED_PROMPTS, seeds=SEEDS, layout="paged",
                        page_size=8, spec_mode="draft", spec_k=4)
    assert outs == expected


# ------------------------------------------------- EOS inside a draft block
@pytest.mark.slow  # tier-1 870s budget: redundant coverage — runs in CI's unfiltered unit step
def test_eos_inside_accepted_draft_block():
    """The device accepts past EOS (it cannot see host semantics); the
    drain must cut the credit loop AT the EOS and drop the trailing
    accepted tokens — same posture as a trailing run-ahead step."""
    s = make_server(spec_mode="draft", draft_model="transformer",
                    draft_model_kwargs=KW, eos_id=6)
    expected = s.generate([REP], max_new_tokens=8)["tokens"][0]
    outs, st = run_batch(s, [REP], layout="dense", spec_mode="draft",
                         spec_k=4)
    assert outs[0] == expected
    # proof the EOS really landed INSIDE an accepted block: the device
    # advanced further per forward than the host surfaced (trailing
    # accepted tokens after EOS were dropped, never credited)
    assert st["spec_tokens_per_forward"] > len(expected) / max(
        st["spec_slot_steps_total"], 1)


# ------------------------------------------------------- mid-stream admission
def test_midstream_admit_with_steps_in_flight(server, expected):
    """An admission landing while verify steps are in flight: the insert
    queues behind them in device program order and the gen counter masks
    the old occupant's trailing variable-advance tokens."""
    prompts = PROMPTS + [[12, 13], [80, 2, 5]]
    exp = expected + [server.generate([p], max_new_tokens=8)["tokens"][0]
                      for p in [[12, 13], [80, 2, 5]]]
    outs, st = run_batch(server, prompts, layout="paged", page_size=8,
                         spec_mode="ngram", spec_k=4)
    assert outs == exp
    # 6 requests through 2 slots: later admits MUST have found steps in
    # flight (the pipeline keeps dispatching while slots turn over)
    assert st["admit_inflight"] >= 1


# ------------------------------------------------- acceptance-rate criterion
@pytest.mark.slow  # tier-1 870s budget: redundant coverage — runs in CI's unfiltered unit step
def test_repetitive_text_beats_1_5_tokens_per_forward(server):
    """The ISSUE 8 acceptance bar: >1.5 accepted tokens per target forward
    at K=4 with the n-gram drafter on repetitive text."""
    expected = server.generate([REP], max_new_tokens=18)["tokens"][0]
    outs, st = run_batch(server, [REP], n=18, layout="paged", page_size=8,
                         spec_mode="ngram", spec_k=4)
    assert outs[0] == expected
    assert st["spec_tokens_per_forward"] > 1.5, st
    assert st["spec_accept_rate"] > 0.0


# ----------------------------------------------------------------- metrics
@pytest.mark.slow  # tier-1 870s budget: redundant coverage — runs in CI's unfiltered unit step
def test_spec_metrics_reach_llm_stats_and_metrics():
    """spec series flow llm_stats -> sync_llm -> /metrics (the graftlint
    metrics-drift round-trip: recorded => declared, declared => recorded)."""
    from seldon_core_tpu.metrics.registry import MetricsRegistry
    from seldon_core_tpu.runtime.batcher import BatcherService

    s = make_server(continuous_batching=2, continuous_batching_max_len=32,
                    kv_page_size=8, spec_mode="ngram", spec_k=4)
    svc = BatcherService(s, max_slots=2)
    s._batcher_service = svc
    try:
        out = svc.submit_sync(REP, 8)
        assert len(out) == 8
        st = s.llm_stats()
        assert st["spec_mode"] == "ngram"
        assert st["spec_k"] == 4
        assert st["spec_slot_steps_total"] > 0
        assert st["spec_tokens_per_forward"] > 0.0
        assert len(st["spec_accept_rate_per_slot"]) == 2
        assert 0.0 <= st["spec_draft_overhead_fraction"] <= 1.0
        assert st["spec_accepted_per_step"], "no accepted-tokens observations"
        reg = MetricsRegistry(deployment="d", predictor="p")
        reg.sync_llm(s)
        text = reg.expose().decode()
        assert "seldon_llm_spec_accept_rate" in text
        assert "seldon_llm_spec_accept_rate_per_slot" in text
        assert "seldon_llm_spec_tokens_per_forward" in text
        assert "seldon_llm_spec_accepted_tokens_per_step" in text
        assert "seldon_llm_spec_draft_overhead_fraction" in text
        assert "seldon_llm_spec_slot_verify_steps_total" in text
    finally:
        svc.close()


# ------------------------------------------------------------- validation
def test_fuse_steps_with_speculation_rejected(server):
    """Fused fixed-K scan and variable accept length are incompatible: the
    combination must fail loudly at construction, not corrupt advance
    bookkeeping at runtime."""
    with pytest.raises(ValueError, match="decode_fuse_steps"):
        ContinuousBatcher(server, max_slots=2, max_len=32,
                          len_buckets=(8,), fuse_steps=4, spec_mode="ngram")


def test_spec_mode_validated_at_load():
    with pytest.raises(ValueError, match="spec_mode"):
        make_server(spec_mode="warp-drive")
    with pytest.raises(ValueError, match="spec_k"):
        make_server(spec_mode="ngram", spec_k=-1)
    with pytest.raises(ValueError, match="draft model"):
        make_server(spec_mode="draft")  # no draft_model given


def test_draft_vocab_mismatch_rejected():
    bad = dict(KW)
    bad["vocab_size"] = 64
    with pytest.raises(ValueError, match="vocab"):
        make_server(spec_mode="draft", draft_model="transformer",
                    draft_model_kwargs=bad)


def test_spec_mode_normalization():
    assert normalize_spec_mode("") == "off"
    assert normalize_spec_mode(None) == "off"
    assert normalize_spec_mode("prompt-lookup") == "ngram"
    assert normalize_spec_mode("DRAFT") == "draft"
    with pytest.raises(ValueError):
        normalize_spec_mode("banana")


# ------------------------------------------------- draft-length controller
def test_controller_warmup_then_adapts():
    c = SpecController(slots=2, k=4)
    # warmup: full depth regardless of early luck
    assert c.cap(0) == 4
    c.observe(0, 0, 4, 1)
    assert c.cap(0) == 4  # still warming up (1 < WARMUP_STEPS)
    c.observe(0, 0, 4, 1)
    # two full rejections: EMA fell below 0.5 -> depth steps down
    assert c.cap(0) < 4
    # the OTHER slot is untouched
    assert c.cap(1) == 4


def test_controller_floor_is_one_probe_not_zero():
    """Cap 0 would stop producing observations and strand the EMA forever;
    the floor is one probe draft per forward."""
    c = SpecController(slots=1, k=4)
    for _ in range(20):
        c.observe(0, 0, 4, 1)  # relentless rejection
    assert c.cap(0) == 1
    # acceptance returning lifts the cap back up
    for _ in range(20):
        c.observe(0, 1, 1, 2)  # the probe draft starts landing
    assert c.cap(0) >= 2


def test_controller_reset_forgets_previous_occupant():
    c = SpecController(slots=1, k=4)
    for _ in range(10):
        c.observe(0, 0, 4, 1)
    assert c.cap(0) == 1
    c.reset(0)
    assert c.cap(0) == 4  # fresh occupant starts at full depth


def test_controller_snapshot_math():
    c = SpecController(slots=1, k=4)
    c.observe(0, 3, 4, 4)   # 3 of 4 drafts accepted, 4 tokens emitted
    c.observe(0, 1, 4, 2)   # 1 of 4 accepted, 2 tokens
    snap = c.snapshot()
    assert snap["spec_slot_steps_total"] == 2
    assert snap["spec_accept_rate"] == pytest.approx(0.5)
    assert snap["spec_tokens_per_forward"] == pytest.approx(3.0)
    # 8 drafted + 2 base columns = 10 columns, 4 rejected drafts wasted
    assert snap["spec_draft_overhead_fraction"] == pytest.approx(0.4)
