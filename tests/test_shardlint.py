"""shardlint self-tests: every rule proven red against a minimal
reconstruction of the discipline violation it exists to catch — a stray
``jax.devices()``/``Mesh(...)`` outside parallel/, a typo'd mesh axis, a
provably-overlapping disagg slice pair, an implicit ``devices[0]`` /
``process_index == 0`` / ``slice_index`` host assumption — plus the
suppression / baseline mechanics the CI gate relies on, the virtual-mesh
conformance harness, and the PR 20 burn-down regressions (servers
consume an injected Topology instead of re-deriving the device world).

The pure-lint tests are stdlib-only synthetic trees under tmp_path, like
tests/test_leaklint.py; the conformance and burn-down tests compile tiny
models on the virtual 8-device CPU mesh from conftest.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.graftlint.core import save_baseline
from tools.shardlint import RULES, run_lint, run_lint_parallel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "shardlint", "baseline.json")

# every fixture tree declares ITS OWN registries — the linter reads the
# scanned tree's parallel/topology.py, not the repo's
FIXTURE_TOPOLOGY = """
    DECLARED_AXES = {
        "data": "batch-parallel",
        "model": "tensor-parallel",
        "seq": "sequence-parallel",
    }
    SINGLE_HOST_GUARDS = {
        "detect_world": "the one declared derivation site",
    }
    SLICE_CONTRACTS = {
        "disaggregated_mesh": "validates prefill/decode overlap at runtime",
    }
"""

REDERIVE = """
    import jax
    from jax.sharding import Mesh

    def build():
        devs = jax.devices()
        return Mesh(devs, ("data",))
"""

TYPO_AXIS = """
    from jax.sharding import PartitionSpec as P

    def cache_spec():
        return P("data", "modle")
"""

OVERLAP_SLICE = """
    def split(devs):
        return DisaggregatedMesh(devs[:2], devs[1:])
"""

HOST_ASSUMPTION = """
    def pick(devices, topo):
        lead = devices[0]
        if topo.process_index == 0:
            return lead
        return [d for d in devices if hasattr(d, "slice_index")]
"""


def write_tree(root, files, topology=FIXTURE_TOPOLOGY):
    files = dict(files)
    files.setdefault("parallel/topology.py", topology)
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(root)


def lint(path, baseline=None, rules=None):
    return run_lint([path], baseline_path=baseline, rules=rules)


def rules_of(findings):
    return [f.rule for f in findings]


def cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.shardlint", *args],
        capture_output=True, text=True, cwd=cwd)


# ---------------------------------------------------------------------------
# mesh-rederivation
# ---------------------------------------------------------------------------

def test_world_derivation_outside_parallel_fires(tmp_path):
    root = write_tree(tmp_path / "pkg", {"runtime/engine.py": REDERIVE})
    reported, _, _ = lint(root)
    hits = [f for f in reported if f.rule == "mesh-rederivation"]
    assert len(hits) == 2  # jax.devices() AND Mesh(...)
    assert any("jax.devices()" in f.message for f in hits)
    assert any("Mesh construction" in f.message for f in hits)


def test_same_code_inside_parallel_is_the_declared_site(tmp_path):
    root = write_tree(tmp_path / "pkg", {"parallel/world.py": REDERIVE})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_mesh_utils_import_outside_parallel_fires(tmp_path):
    src = """
        from jax.experimental import mesh_utils

        def grid(n):
            return mesh_utils.create_device_mesh((n,))
    """
    root = write_tree(tmp_path / "pkg", {"servers/grid.py": src})
    reported, _, _ = lint(root)
    assert "mesh-rederivation" in rules_of(reported)


def test_topology_consumer_is_clean(tmp_path):
    src = """
        def build(topo):
            return topo.mesh({"data": -1, "model": 2})
    """
    root = write_tree(tmp_path / "pkg", {"runtime/engine.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


# ---------------------------------------------------------------------------
# axis-name-discipline
# ---------------------------------------------------------------------------

def test_typoed_axis_in_partition_spec_fires(tmp_path):
    """The motivating bug: P("modle") silently REPLICATES instead of
    sharding — here it goes red against the declared registry."""
    root = write_tree(tmp_path / "pkg", {"servers/spec.py": TYPO_AXIS})
    reported, _, _ = lint(root)
    hits = [f for f in reported if f.rule == "axis-name-discipline"]
    assert len(hits) == 1  # the declared "data" in the same spec is quiet
    assert "'modle'" in hits[0].message


def test_declared_axes_are_quiet_everywhere(tmp_path):
    src = """
        from jax.sharding import PartitionSpec as P
        import jax

        def specs(topo):
            kv = P("data", "seq", ("model",), None)
            mesh = topo.mesh({"data": -1, "seq": 1, "model": 2})
            out = jax.lax.psum(1, "model")
            return kv, mesh, out
    """
    root = write_tree(tmp_path / "pkg", {"servers/spec.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_collective_and_axis_name_kwarg_literals_checked(tmp_path):
    src = """
        import jax

        def reduce(x, blocks):
            y = jax.lax.psum(x, "modle")
            return ring_attention(y, blocks, axis_name="sqe")
    """
    root = write_tree(tmp_path / "pkg", {"ops/ring.py": src})
    reported, _, _ = lint(root)
    names = {f.message.split("'")[1] for f in reported
             if f.rule == "axis-name-discipline"}
    assert names == {"modle", "sqe"}


def test_mesh_dict_keys_checked(tmp_path):
    src = """
        def build(topo):
            return topo.mesh({"data": -1, "modell": 2})
    """
    root = write_tree(tmp_path / "pkg", {"runtime/engine.py": src})
    reported, _, _ = lint(root)
    assert "axis-name-discipline" in rules_of(reported)


def test_single_file_scan_falls_back_to_repo_registry(tmp_path):
    """Scanning a lone file (no parallel/topology.py in the tree) checks
    against the repo's own DECLARED_AXES."""
    good = tmp_path / "good.py"
    good.write_text('from jax.sharding import PartitionSpec as P\n'
                    'S = P("data", "model")\n')
    bad = tmp_path / "bad.py"
    bad.write_text('from jax.sharding import PartitionSpec as P\n'
                   'S = P("bogus")\n')
    reported, _, _ = run_lint([str(good)])
    assert rules_of(reported) == []
    reported, _, _ = run_lint([str(bad)])
    assert "axis-name-discipline" in rules_of(reported)


# ---------------------------------------------------------------------------
# slice-disjointness
# ---------------------------------------------------------------------------

def test_provable_overlap_fires_even_with_contract(tmp_path):
    """devs[:2] and devs[1:] share device 1 at every world size — red
    even when the callee would raise at runtime (a certain overlap is a
    bug; the contract just turns it into a crash)."""
    src = OVERLAP_SLICE.replace("DisaggregatedMesh", "disaggregated_mesh")
    root = write_tree(tmp_path / "pkg", {"runtime/disagg.py": src})
    reported, _, _ = lint(root)
    hits = [f for f in reported if f.rule == "slice-disjointness"]
    assert hits and "PROVABLY overlapping" in hits[0].message


def test_disjoint_constant_slices_are_clean(tmp_path):
    src = """
        def split(devs):
            return DisaggregatedMesh(devs[:2], devs[2:])
    """
    root = write_tree(tmp_path / "pkg", {"runtime/disagg.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_complementary_tail_head_split_is_clean(tmp_path):
    src = """
        def split(devs, n):
            return DisaggregatedMesh(devs[:-n], devs[-n:])
    """
    root = write_tree(tmp_path / "pkg", {"runtime/disagg.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_opaque_sets_need_a_declared_contract(tmp_path):
    bad = """
        def split(pre, dec):
            return DisaggregatedMesh(pre, dec)
    """
    root = write_tree(tmp_path / "pkg", {"runtime/disagg.py": bad})
    reported, _, _ = lint(root)
    hits = [f for f in reported if f.rule == "slice-disjointness"]
    assert hits and "SLICE_CONTRACTS" in hits[0].message

    # same call through the CONTRACTED callee: covered
    ok = bad.replace("DisaggregatedMesh", "disaggregated_mesh")
    root = write_tree(tmp_path / "pkg2", {"runtime/disagg.py": ok})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_integer_counts_are_the_librarys_problem(tmp_path):
    src = """
        def split(topo):
            return topo.disaggregated(1, 0)
    """
    root = write_tree(tmp_path / "pkg", {"runtime/disagg.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


# ---------------------------------------------------------------------------
# host-assumption
# ---------------------------------------------------------------------------

def test_implicit_host_assumptions_fire(tmp_path):
    root = write_tree(tmp_path / "pkg",
                      {"controlplane/host.py": HOST_ASSUMPTION})
    reported, _, _ = lint(root)
    hits = [f.message for f in reported if f.rule == "host-assumption"]
    assert len(hits) == 3
    assert any("devices[k]" in m for m in hits)
    assert any("process_index" in m for m in hits)
    assert any("slice_index" in m for m in hits)


def test_declared_guard_function_is_waived(tmp_path):
    src = """
        def detect_world(devices):
            return devices[0]
    """
    root = write_tree(tmp_path / "pkg", {"controlplane/host.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_lexical_topology_guard_is_waived(tmp_path):
    src = """
        def pick(devices, topo):
            if topo.single_host:
                return devices[0]
            if topo.is_primary_process:
                return devices[1]
            return None
    """
    root = write_tree(tmp_path / "pkg", {"controlplane/host.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_jax_devices_zero_outside_parallel_reports_once(tmp_path):
    """jax.devices()[0] outside parallel/ is ONE finding (the call, as
    mesh-rederivation) — the [0] symptom isn't double-billed."""
    src = """
        import jax

        def lead():
            return jax.devices()[0]
    """
    root = write_tree(tmp_path / "pkg", {"servers/lead.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == ["mesh-rederivation"]


def test_device_list_indexing_inside_parallel_still_needs_a_guard(tmp_path):
    src = """
        def lead(devices):
            return devices[0]
    """
    root = write_tree(tmp_path / "pkg", {"parallel/lead.py": src})
    reported, _, _ = lint(root)
    assert "host-assumption" in rules_of(reported)


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------

def test_suppression_with_reason_silences(tmp_path):
    src = REDERIVE.replace(
        "        devs = jax.devices()",
        "        # shardlint: allow-mesh-rederivation(fixture: platform probe, no world derived)\n"
        "        devs = jax.devices()\n"
        "        # shardlint: allow-mesh-rederivation(fixture: test-only mesh)")
    root = write_tree(tmp_path / "pkg", {"runtime/engine.py": src})
    reported, _, suppressed = lint(root)
    assert rules_of(reported) == []
    assert len(suppressed) == 2


def test_suppression_with_empty_reason_is_a_finding(tmp_path):
    src = TYPO_AXIS.replace(
        'return P("data", "modle")',
        'return P("data", "modle")  # shardlint: allow-axis-name-discipline()')
    root = write_tree(tmp_path / "pkg", {"servers/spec.py": src})
    reported, _, _ = lint(root)
    assert "bad-suppression" in rules_of(reported)
    assert "axis-name-discipline" in rules_of(reported)  # NOT silenced


def test_unknown_rule_suppression_is_flagged(tmp_path):
    src = TYPO_AXIS.replace(
        'return P("data", "modle")',
        'return P("data", "modle")  # shardlint: allow-made-up-rule(nope)')
    root = write_tree(tmp_path / "pkg", {"servers/spec.py": src})
    reported, _, _ = lint(root)
    assert "bad-suppression" in rules_of(reported)


def test_other_tools_tags_do_not_silence_shardlint(tmp_path):
    """Cross-tool tag isolation: racelint/leaklint/graftlint comments
    answer to their own layers only."""
    src = TYPO_AXIS.replace(
        'return P("data", "modle")',
        'return P("data", "modle")  '
        '# racelint: allow-axis-name-discipline(wrong tool)  '
        '# leaklint: allow-axis-name-discipline(wrong tool)')
    root = write_tree(tmp_path / "pkg", {"servers/spec.py": src})
    reported, _, _ = lint(root)
    assert "axis-name-discipline" in rules_of(reported)


def test_shardlint_tag_does_not_silence_leaklint(tmp_path):
    from tools.leaklint import run_lint as leak_lint

    src = """
        class Batcher:
            def _admit(self, req):
                # shardlint: allow-leak-on-path(wrong tool)
                aid = self._adapters.resolve_and_pin(req.adapter)
                slot = self.find_slot()
                if slot is None:
                    return False
                self._commit_slot(slot, aid)
                return True
    """
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": src})
    reported, _, _ = leak_lint([root])
    assert "leak-on-path" in rules_of(reported)


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------

def test_baseline_absorbs_then_dies_with_the_code(tmp_path):
    root = write_tree(tmp_path / "pkg", {"servers/spec.py": TYPO_AXIS})
    reported, _, _ = lint(root)
    findings = [f for f in reported if f.rule in RULES]
    assert findings
    bpath = str(tmp_path / "baseline.json")
    save_baseline(bpath, findings)
    data = json.loads(open(bpath).read())
    for e in data["entries"]:
        e["reason"] = "grandfathered for the mechanics test"
    with open(bpath, "w") as f:
        json.dump(data, f)

    reported2, absorbed, _ = lint(root, baseline=bpath)
    assert rules_of(reported2) == []
    assert len(absorbed) == len(findings)

    # touch the fingerprinted line: the entry dies, the finding resurfaces
    mutated = TYPO_AXIS.replace('P("data", "modle")', 'P("seq", "modle")')
    write_tree(tmp_path / "pkg", {"servers/spec.py": mutated})
    reported3, _, _ = lint(root, baseline=bpath)
    assert "axis-name-discipline" in rules_of(reported3)


def test_baseline_without_reason_is_rejected(tmp_path):
    root = write_tree(tmp_path / "pkg", {"servers/spec.py": TYPO_AXIS})
    reported, _, _ = lint(root)
    bpath = str(tmp_path / "baseline.json")
    save_baseline(bpath, [f for f in reported if f.rule in RULES])
    data = json.loads(open(bpath).read())
    data["entries"][0]["reason"] = "  "
    with open(bpath, "w") as f:
        json.dump(data, f)
    with pytest.raises(ValueError, match="no reason"):
        lint(root, baseline=bpath)


def test_real_tree_has_zero_unsuppressed_findings():
    """The gate itself: the shipped tree + shipped (empty) baseline lint
    clean. The PR 20 burn-down fixed every real finding — the only live
    suppressions are the ops/ Pallas platform probes, each with a
    reviewable reason."""
    reported, absorbed, suppressed = run_lint(
        [os.path.join(REPO, "seldon_core_tpu")],
        baseline_path=BASELINE if os.path.exists(BASELINE) else None)
    assert reported == [], "\n".join(f.render() for f in reported)
    assert absorbed == []  # nothing grandfathered — keep it that way
    assert all(f.rule == "mesh-rederivation" for f in suppressed), \
        "only the Pallas platform probes carry suppressions today"


def test_real_baseline_count_only_decreases():
    """The ratchet: the shardlint baseline shipped EMPTY. Growing it
    means shipping a known sharding-discipline hole; fix it or suppress
    it inline with a reason a reviewer can judge."""
    with open(BASELINE) as f:
        data = json.load(f)
    assert len(data.get("entries", [])) <= 0


# ---------------------------------------------------------------------------
# burn-down regressions: servers consume the injected Topology
# ---------------------------------------------------------------------------

def test_migrated_modules_never_touch_the_world_directly():
    """batcher/llmserver/jaxserver passed the burn-down: zero
    mesh-rederivation findings WITHOUT suppressions in any of them."""
    targets = [
        os.path.join(REPO, "seldon_core_tpu", "runtime", "batcher.py"),
        os.path.join(REPO, "seldon_core_tpu", "servers", "llmserver.py"),
        os.path.join(REPO, "seldon_core_tpu", "servers", "jaxserver.py"),
    ]
    reported, _, suppressed = run_lint(targets,
                                       rules=["mesh-rederivation"])
    assert reported == [], "\n".join(f.render() for f in reported)
    assert suppressed == []


def test_topology_registry_shape():
    """DECLARED_AXES is the single source of axis truth: the serving
    axes exist, and Topology.mesh rejects an undeclared axis with a
    message naming the registry."""
    from seldon_core_tpu.parallel import DECLARED_AXES, Topology

    assert {"data", "model", "seq"} <= set(DECLARED_AXES)
    topo = Topology.detect()
    assert topo.device_count == 8  # conftest virtual mesh
    with pytest.raises(ValueError, match="DECLARED_AXES"):
        topo.mesh({"data": -1, "modle": 2})


def test_llmserver_builds_its_mesh_from_the_injected_topology():
    """The server's world view is the Topology it was handed — a
    4-device sub-topology yields a mesh over exactly those 4 devices,
    not the process's 8 (the partition_for_disaggregation pre-work:
    each disagg slice gets a sub-mesh view)."""
    from seldon_core_tpu.parallel import Topology
    from seldon_core_tpu.servers.llmserver import LLMServer

    topo = Topology.detect()
    sub = topo.sub_topology(topo.devices[:4])
    s = LLMServer(model="llama-tiny", init_random=True, max_new_tokens=2,
                  len_buckets=(16,), batch_buckets=(1,), seed=7,
                  tensor_parallel=2, topology=sub)
    s.load()
    assert s.topology is sub
    assert set(s.mesh.devices.flat) == set(sub.devices)
    assert dict(s.mesh.shape) == {"data": 2, "seq": 1, "model": 2}


def test_disaggregated_meshes_carry_sub_topology_views():
    from seldon_core_tpu.parallel import Topology

    topo = Topology.detect()
    dm = topo.disaggregated(prefill_devices=2)
    assert dm.prefill_topology is not None
    assert set(dm.prefill_topology.devices) == set(dm.prefill_devices)
    assert set(dm.decode_topology.devices) == set(dm.decode_devices)
    assert not (set(dm.prefill_topology.devices)
                & set(dm.decode_topology.devices))


# ---------------------------------------------------------------------------
# virtual-mesh conformance harness
# ---------------------------------------------------------------------------

def test_conformance_compare_goes_red_on_spec_drift():
    """The harness's own red path: a declared spec the compiled program
    doesn't carry must be reported, with the diff naming both sides."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from seldon_core_tpu.parallel import Topology
    from tools.shardlint.conformance import _compare

    mesh = Topology.detect().mesh({"data": -1, "model": 2})
    declared = [NamedSharding(mesh, P("model"))]
    compiled = [NamedSharding(mesh, P())]
    mismatches = []
    _compare(declared, compiled, [1], ["w"], "4x2", "predict", mismatches)
    assert len(mismatches) == 1
    assert mismatches[0]["declared"] != mismatches[0]["compiled"]

    mismatches = []
    _compare(declared, [NamedSharding(mesh, P("model"))], [1], ["w"],
             "4x2", "predict", mismatches)
    assert mismatches == []


def test_conformance_4x2():
    """Tier-1 cell: compiled shardings match the declared specs at the
    4x2 (data x model) shape, both cells."""
    from tools.shardlint.conformance import run_conformance

    report, mismatches = run_conformance(["4x2"])
    assert mismatches == [], json.dumps(mismatches, indent=2)
    assert report["4x2"]["leaves_checked"]["predict"] > 0
    assert report["4x2"]["leaves_checked"]["decode"] > 0


@pytest.mark.slow  # tier-1 budget: CI's multi-chip dryrun step runs these
def test_conformance_2x4_and_1x8():
    from tools.shardlint.conformance import run_conformance

    report, mismatches = run_conformance(["2x4", "1x8"])
    assert mismatches == [], json.dumps(mismatches, indent=2)
    assert set(report) == {"2x4", "1x8"}


# ---------------------------------------------------------------------------
# CLI + parallel runner
# ---------------------------------------------------------------------------

def test_cli_exit_codes_and_json(tmp_path):
    """The acceptance contract: non-zero on EACH mutated fixture class —
    rederivation, typo'd axis, overlapping slice, host assumption,
    empty-reason suppression — and 0 on a clean tree."""
    bad = write_tree(tmp_path / "bad", {
        "runtime/engine.py": REDERIVE,
        "servers/spec.py": TYPO_AXIS,
        "runtime/disagg.py": OVERLAP_SLICE,
        "controlplane/host.py": HOST_ASSUMPTION,
        "runtime/supp.py": """
            def f(topo):
                return topo.mesh({"data": -1, "oops": 2})  # shardlint: allow-axis-name-discipline()
        """,
    })
    ok = write_tree(tmp_path / "ok", {"runtime/c.py": "X = 1\n"})

    r = cli(bad, "--no-baseline", "--format", "json")
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    seen = {f["rule"] for f in payload["findings"]}
    assert set(RULES) | {"bad-suppression"} <= seen

    # each rule's gate bites solo too
    for rule in RULES:
        assert cli(bad, "--no-baseline", "--rules", rule).returncode == 1, rule

    assert cli(ok, "--no-baseline").returncode == 0
    assert cli(str(tmp_path / "missing")).returncode == 2
    assert cli(bad, "--rules", "not-a-rule").returncode == 2


def test_cli_real_tree_is_the_gate():
    r = cli("seldon_core_tpu/")
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow  # tier-1 870s budget: runs in CI's unfiltered shardlint proofs step
def test_parallel_matches_serial(tmp_path):
    root = write_tree(tmp_path / "pkg", {
        "runtime/engine.py": REDERIVE,
        "servers/spec.py": TYPO_AXIS,
        "controlplane/host.py": HOST_ASSUMPTION,
        "runtime/supp.py": """
            def f(topo):
                return topo.mesh({"data": -1, "oops": 2})  # shardlint: allow-axis-name-discipline()
        """,
    })
    serial = run_lint([root])
    parallel = run_lint_parallel([root], None, None, jobs=4)
    for s, p in zip(serial, parallel):
        assert [(f.rule, f.path, f.line) for f in s] == \
            [(f.rule, f.path, f.line) for f in p]
    # meta findings (the empty-reason suppression) appear exactly once
    assert sum(1 for f in parallel[0] if f.rule == "bad-suppression") == 1


def test_rules_filter(tmp_path):
    root = write_tree(tmp_path / "pkg", {
        "runtime/engine.py": REDERIVE,
        "servers/spec.py": TYPO_AXIS,
    })
    reported, _, _ = lint(root, rules=["mesh-rederivation"])
    assert set(rules_of(reported)) == {"mesh-rederivation"}
    reported, _, _ = lint(root, rules=["axis-name-discipline"])
    assert set(rules_of(reported)) == {"axis-name-discipline"}
