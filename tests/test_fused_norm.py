"""Fused residual+RMSNorm Pallas kernel: interpret-mode parity with the
reference XLA expression (and the model's unfused path), padding behaviour,
jit-ability, and the TransformerBlock fused_norm flag. Runs the kernel body
under the Pallas interpreter on CPU (ops/pallas_int8.py pattern); the
compiled path is probe-gated on real TPUs."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models import get_model
from seldon_core_tpu.models.transformer import rms_norm
from seldon_core_tpu.ops.fused_norm import (
    fused_residual_rmsnorm,
    probe_tpu_compile,
    residual_rmsnorm_ref,
)

pytestmark = pytest.mark.pallas


def _f32(a):
    return np.asarray(a, np.float32)


@pytest.mark.parametrize("shape,dtype", [
    ((2, 5, 64), jnp.float32),
    ((8, 2048), jnp.bfloat16),   # the decode shape the profile flags
    ((3, 100), jnp.float32),     # lane dim padded to 128 inside the kernel
    ((7, 130), jnp.bfloat16),    # both dims padded
])
def test_interpret_parity_with_reference(shape, dtype):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(shape), dtype)
    h = jnp.asarray(rng.standard_normal(shape), dtype)
    w = jnp.asarray(rng.standard_normal(shape[-1]), jnp.float32)
    y, o = fused_residual_rmsnorm(x, h, w, 1e-5, interpret=True)
    y_ref, o_ref = residual_rmsnorm_ref(x, h, w, 1e-5)
    assert y.dtype == x.dtype and o.dtype == x.dtype
    # acceptance bar: <=1e-5 relative for f32; bf16-relative means within
    # ~1 ulp of bf16 (eps = 2^-8 ~= 4e-3) — the kernel replays the same
    # dtype chain, the residual difference is reduction order (sum/d vs mean)
    if dtype == jnp.bfloat16:
        rtol, atol = 8e-3, 8e-3
    else:
        rtol, atol = 1e-5, 1e-5
    np.testing.assert_allclose(_f32(y), _f32(y_ref), rtol=rtol, atol=atol)
    np.testing.assert_allclose(_f32(o), _f32(o_ref), rtol=rtol, atol=atol)


def test_parity_with_model_rms_norm():
    """The kernel's contract is rms_norm(x + h, w, eps) from the model."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 3, 64)), jnp.float32)
    h = jnp.asarray(rng.standard_normal((2, 3, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal(64), jnp.float32)
    y, o = fused_residual_rmsnorm(x, h, w, 1e-5, interpret=True)
    np.testing.assert_allclose(_f32(y), _f32(x + h), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(_f32(o), _f32(rms_norm(x + h, w, 1e-5)),
                               rtol=1e-5, atol=1e-5)


def test_kernel_is_jittable():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    h = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    w = jnp.ones((64,), jnp.float32)

    @jax.jit
    def f(x, h, w):
        return fused_residual_rmsnorm(x, h, w, 1e-5, interpret=True)

    y, o = f(x, h, w)
    y_ref, o_ref = residual_rmsnorm_ref(x, h, w, 1e-5)
    np.testing.assert_allclose(_f32(o), _f32(o_ref), rtol=1e-5, atol=1e-5)


def test_cpu_fallback_is_reference_expression():
    """Without interpret=True on a non-TPU backend, the entry point must
    return the XLA reference (never attempt a TPU Pallas compile)."""
    assert probe_tpu_compile().startswith("error: no TPU")
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 16)), jnp.float32)
    w = jnp.ones((16,), jnp.float32)
    y, o = fused_residual_rmsnorm(x, x, w, 1e-5)
    y_ref, o_ref = residual_rmsnorm_ref(x, x, w, 1e-5)
    np.testing.assert_array_equal(_f32(o), _f32(o_ref))


def test_transformer_fused_norm_flag_matches_unfused():
    """Same params, fused_norm on vs off: identical logits (on CPU the flag
    lowers to the identical XLA expression, so this is exact)."""
    full = get_model("llama-tiny")
    fused = get_model("llama-tiny", fused_norm=True)
    tokens = jnp.asarray(np.random.default_rng(4).integers(0, 255, (2, 16)), jnp.int32)
    variables = full.init(jax.random.PRNGKey(0), tokens)
    ref, _ = full.apply(variables, tokens)
    out, _ = fused.apply(variables, tokens)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


@pytest.mark.slow  # tier-1 870s budget: redundant coverage — runs in CI's unfiltered unit step
def test_llmserver_generate_with_fused_norm():
    """End-to-end: a fused-norm server produces the same greedy tokens as
    the unfused twin (flag changes cost, never tokens)."""
    from seldon_core_tpu.servers.llmserver import LLMServer

    def build(fused):
        s = LLMServer(model="llama-tiny",
                      model_kwargs={"fused_norm": True} if fused else {},
                      init_random=True, max_new_tokens=8, len_buckets=(16,),
                      batch_buckets=(1,), temperature=0.0, eos_id=-1, seed=5)
        s.load()
        return s

    prompt = [5, 9, 17, 33]
    want = build(False).generate([prompt], max_new_tokens=8)["tokens"][0]
    got = build(True).generate([prompt], max_new_tokens=8)["tokens"][0]
    assert got == want
