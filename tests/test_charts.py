"""Chart drift tests (VERDICT r2 item 5): the helm charts, the raw
manifests, and the example CRs must describe the SAME objects — a helm
user and a kubectl-apply user can never see different installs."""

from __future__ import annotations

import json
import os
import shutil

import pytest

yaml = pytest.importorskip("yaml")

from seldon_core_tpu.controlplane.charts import (  # noqa: E402
    CHARTS_DIR,
    render_chart,
    render_chart_docs,
    render_template,
)

DEPLOY = os.path.dirname(CHARTS_DIR)


def test_operator_chart_defaults_match_raw_manifests():
    docs = render_chart_docs(os.path.join(CHARTS_DIR, "seldon-core-tpu-operator"))
    with open(os.path.join(DEPLOY, "operator.yaml")) as f:
        raw = [d for d in yaml.safe_load_all(f) if d is not None]
    assert docs == raw


def test_operator_chart_crd_is_verbatim_copy():
    with open(os.path.join(CHARTS_DIR, "seldon-core-tpu-operator", "crds", "crd.yaml")) as f:
        chart_crd = f.read()
    with open(os.path.join(DEPLOY, "crd.yaml")) as f:
        raw_crd = f.read()
    assert chart_crd == raw_crd


@pytest.mark.parametrize("chart,example", [
    ("seldon-single-model", "single-model.json"),
    ("seldon-abtest", "abtest.json"),
    ("seldon-mab", "mab.json"),
])
def test_topology_chart_defaults_match_example_cr(chart, example):
    docs = render_chart_docs(os.path.join(CHARTS_DIR, chart))
    with open(os.path.join(DEPLOY, "examples", example)) as f:
        want = json.load(f)
    assert docs == [want]


def test_topology_chart_values_flow_and_validate():
    """Overridden values land in the CR and the result passes the same
    validation the operator applies."""
    from seldon_core_tpu.contracts.graph import SeldonDeploymentSpec
    from seldon_core_tpu.controlplane.validate import require_valid

    docs = render_chart_docs(
        os.path.join(CHARTS_DIR, "seldon-mab"),
        values={"name": "bandit2", "epsilon": "0.05", "replicas": 3,
                "modelA": {"uri": "gs://b/a2"}})
    cr = docs[0]
    assert cr["metadata"]["name"] == "bandit2"
    assert cr["spec"]["predictors"][0]["replicas"] == 3
    graph = cr["spec"]["predictors"][0]["graph"]
    assert graph["parameters"][1]["value"] == "0.05"
    assert graph["children"][0]["modelUri"] == "gs://b/a2"
    assert graph["children"][1]["modelUri"] == "gs://my-bucket/model-b"
    sdep = SeldonDeploymentSpec.from_dict(cr)
    require_valid(sdep)


def test_operator_chart_istio_toggle():
    docs_on = render_chart_docs(os.path.join(CHARTS_DIR, "seldon-core-tpu-operator"))
    docs_off = render_chart_docs(
        os.path.join(CHARTS_DIR, "seldon-core-tpu-operator"),
        values={"istio": {"enabled": False}})
    role_on = next(d for d in docs_on if d["kind"] == "ClusterRole")
    role_off = next(d for d in docs_off if d["kind"] == "ClusterRole")
    groups_on = {r["apiGroups"][0] for r in role_on["rules"]}
    groups_off = {r["apiGroups"][0] for r in role_off["rules"]}
    assert "networking.istio.io" in groups_on
    assert "networking.istio.io" not in groups_off
    # kustomize istio-off overlay removes the same (last) rule
    assert role_on["rules"][4]["apiGroups"] == ["networking.istio.io"]


def test_operator_chart_engine_values():
    docs = render_chart_docs(
        os.path.join(CHARTS_DIR, "seldon-core-tpu-operator"),
        values={"namespace": "ml", "engine": {"image": "r/engine:v9",
                                              "httpPort": 9000}})
    dep = next(d for d in docs if d["kind"] == "Deployment")
    assert dep["metadata"]["namespace"] == "ml"
    env = {e["name"]: e["value"]
           for e in dep["spec"]["template"]["spec"]["containers"][0]["env"]}
    assert env["ENGINE_CONTAINER_IMAGE_AND_VERSION"] == "r/engine:v9"
    assert env["ENGINE_SERVER_PORT"] == "9000"
    assert env["ENGINE_SERVER_GRPC_PORT"] == "5001"
    sa = next(d for d in docs if d["kind"] == "ServiceAccount")
    assert sa["metadata"]["namespace"] == "ml"


def test_renderer_subset_semantics():
    ctx = {"Values": {"a": {"b": "x"}, "flag": False, "n": 3},
           "Release": {"Namespace": "ns"}}
    assert render_template("v={{ .Values.a.b }}", ctx) == "v=x"
    assert render_template('{{ .Values.missing | default "d" }}', ctx) == "d"
    assert render_template("{{ .Values.n | quote }}", ctx) == '"3"'
    out = render_template(
        "a\n{{- if .Values.flag }}\nyes\n{{- else }}\nno\n{{- end }}\nb", ctx)
    assert out == "a\nno\nb"
    with pytest.raises(ValueError):
        render_template("{{ .Values.x | exotic }}", {"Values": {"x": 1}})


def test_kustomize_base_points_at_raw_manifests():
    base = os.path.join(DEPLOY, "kustomize", "seldon-core-tpu-operator", "base",
                        "kustomization.yaml")
    with open(base) as f:
        kust = yaml.safe_load(f)
    for rel in kust["resources"]:
        assert os.path.exists(os.path.join(os.path.dirname(base), rel)), rel


def test_chart_render_cli_lists_all_templates():
    rendered = render_chart(os.path.join(CHARTS_DIR, "seldon-core-tpu-operator"))
    assert [name for name, _ in rendered] == ["operator.yaml"]


def test_model_chart_widened_values_flow():
    """Round-4 values surface (reference seldon-single-model parity:
    sdepLabels / predictorLabels / annotations / engine resources+env
    passthrough) flows into the CR and validates."""
    from seldon_core_tpu.contracts.graph import SeldonDeploymentSpec
    from seldon_core_tpu.controlplane.validate import require_valid

    docs = render_chart_docs(
        os.path.join(CHARTS_DIR, "seldon-single-model"),
        values={
            "sdepLabels": {"app": "seldon", "team": "ranking"},
            "predictorLabels": {"version": "v2"},
            "annotations": {"seldon.io/rest-read-timeout": "5000",
                            "seldon.io/grpc-max-message-size": "10485760"},
            "replicas": 3,
            "engine": {
                "resources": {"requests": {"cpu": "2", "memory": "1Gi"}},
                "env": [{"name": "SELDON_LOG_LEVEL", "value": "DEBUG"},
                        {"name": "EXTRA", "value": "1"}],
            },
        })
    cr = docs[0]
    assert cr["metadata"]["labels"] == {"app": "seldon", "team": "ranking"}
    p = cr["spec"]["predictors"][0]
    assert p["labels"] == {"version": "v2"}
    assert p["replicas"] == 3
    assert cr["spec"]["annotations"]["seldon.io/grpc-max-message-size"] == "10485760"
    assert p["svcOrchSpec"]["resources"]["requests"]["memory"] == "1Gi"
    assert {e["name"] for e in p["svcOrchSpec"]["env"]} == {"SELDON_LOG_LEVEL", "EXTRA"}
    require_valid(SeldonDeploymentSpec.from_dict(cr))
    # the engine renderer actually consumes what the chart exposes
    from seldon_core_tpu.controlplane.render import render_manifests

    sdep = SeldonDeploymentSpec.from_dict(cr)
    manifests = render_manifests(sdep, namespace="ns", tpu_chips=0)
    dep = next(m for m in manifests if m["kind"] == "Deployment")
    eng = dep["spec"]["template"]["spec"]["containers"][0]
    assert eng["resources"]["requests"]["memory"] == "1Gi"
    assert {"name": "EXTRA", "value": "1"} in eng["env"]


def test_mab_chart_svcorch_values_flow():
    docs = render_chart_docs(
        os.path.join(CHARTS_DIR, "seldon-mab"),
        values={"engine": {"resources": {"requests": {"cpu": "1"}},
                           "env": [{"name": "A", "value": "b"}]},
                "annotations": {"seldon.io/rest-read-timeout": "2000"}})
    p = docs[0]["spec"]["predictors"][0]
    assert p["svcOrchSpec"]["resources"]["requests"]["cpu"] == "1"
    assert docs[0]["spec"]["annotations"]["seldon.io/rest-read-timeout"] == "2000"


@pytest.mark.skipif(shutil.which("helm") is None, reason="no helm binary")
@pytest.mark.parametrize("chart", [
    "seldon-core-tpu-operator", "seldon-single-model", "seldon-abtest", "seldon-mab",
])
def test_stock_helm_agrees_with_subset_renderer(chart, tmp_path):
    """When a real helm binary exists (the CI helm-parity job provides one),
    `helm template` must produce byte-identical objects to the in-repo
    subset renderer, and `helm lint` must pass — proving the charts are
    stock-helm-valid, not just subset-renderer-valid."""
    import subprocess

    chart_dir = os.path.join(CHARTS_DIR, chart)
    lint = subprocess.run(["helm", "lint", chart_dir], capture_output=True, text=True)
    assert lint.returncode == 0, lint.stdout + lint.stderr
    out = subprocess.run(
        ["helm", "template", "seldon", chart_dir, "--namespace", "seldon-system"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    import yaml

    helm_docs = [d for d in yaml.safe_load_all(out.stdout) if d is not None]
    ours = render_chart_docs(chart_dir)
    # helm template skips crds/; our renderer does too (templates/ only)
    def key(d):
        return (d.get("kind"), d.get("metadata", {}).get("name"))

    assert sorted(helm_docs, key=lambda d: str(key(d))) == \
        sorted(ours, key=lambda d: str(key(d)))
