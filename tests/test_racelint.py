"""racelint self-tests: every rule proven against a minimal reconstruction
of the bug class it exists to catch (the PR 6 burn-down races), plus the
suppression / baseline mechanics the CI gate relies on.

Tier-1 and stdlib-only, like tests/test_graftlint.py: every fixture is a
synthetic tree under tmp_path and the CLI subprocess tests run in tens of
milliseconds.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.graftlint.core import save_baseline
from tools.racelint import RULES, run_lint, run_lint_parallel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "racelint", "baseline.json")


def write_tree(root, files):
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(root)


def lint(path, baseline=None, rules=None):
    return run_lint([path], baseline_path=baseline, rules=rules)


def rules_of(findings):
    return [f.rule for f in findings]


def cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.racelint", *args],
        capture_output=True, text=True, cwd=cwd)


# ---------------------------------------------------------------------------
# unguarded-shared-state
# ---------------------------------------------------------------------------

# the PR 6 AdmissionController._shed reconstruction: a discipline exists
# (the lock guards the writes) but one internal path runs unguarded
PR6_SHED = """
    import threading

    class Admission:
        def __init__(self):
            self._lock = threading.Lock()
            self.shed_total = 0
            self.inflight = 0

        def acquire(self):
            with self._lock:
                self.inflight += 1
                raise self._shed()

        def acquire_sync(self):
            with self._lock:
                self.inflight += 1
            self.release()
            raise self._shed()   # pre-fix: no lock held on this path

        def release(self):
            with self._lock:
                self.inflight -= 1

        def _shed(self):
            self.shed_total += 1
            return RuntimeError(self.inflight)
"""


def test_unguarded_write_fires_on_pr6_shed_reconstruction(tmp_path):
    """The burn-down bug: _shed's read-modify-writes are guarded through
    three call sites and unguarded through the fourth — the entry-lock
    intersection is empty, so its accesses count as unguarded."""
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": PR6_SHED})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the pre-fix _shed pattern must fire"
    assert any("shed_total" in f.message for f in us)


def test_all_call_sites_locked_is_clean(tmp_path):
    """The post-fix shape: every path into _shed holds the lock, so the
    entry-lock intersection guards its accesses."""
    fixed = PR6_SHED.replace(
        "            self.release()\n"
        "            raise self._shed()   # pre-fix: no lock held on this path",
        "            self.release()\n"
        "            with self._lock:\n"
        "                raise self._shed()")
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


PAGE_ALLOCATOR = """
    import threading

    class PageAllocator:
        # the PR 7 host-side KV page allocator shape: every free-list
        # transition under the lock — except the mutated path below
        def __init__(self, total):
            self._lock = threading.Lock()
            self._free = list(range(total))
            self.shed_total = 0

        def alloc(self, n):
            with self._lock:
                if n > len(self._free):
                    return None
                return [self._free.pop() for _ in range(n)]

        def free(self, pages):
            for p in pages:              # pre-fix: no lock on the return path
                self._free.append(p)

        def count_shed(self):
            with self._lock:
                self.shed_total += 1
"""


def test_page_allocator_unlocked_free_fires(tmp_path):
    """The PR 7 allocator discipline: alloc/count_shed establish the
    guarded-writes pattern on the free list; an unlocked free() path is
    exactly the double-allocation corruption the lock exists to prevent
    (the dynamic proof lives in tests/test_schedules.py)."""
    root = write_tree(tmp_path / "pkg", {"runtime/pages.py": PAGE_ALLOCATOR})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked free-list mutation must fire"
    assert any("_free" in f.message for f in us)


def test_page_allocator_locked_free_is_clean(tmp_path):
    fixed = PAGE_ALLOCATOR.replace(
        "        def free(self, pages):\n"
        "            for p in pages:              # pre-fix: no lock on the return path\n"
        "                self._free.append(p)",
        "        def free(self, pages):\n"
        "            with self._lock:\n"
        "                for p in pages:\n"
        "                    self._free.append(p)")
    assert fixed != PAGE_ALLOCATOR
    root = write_tree(tmp_path / "pkg", {"runtime/pages.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


RADIX_TRIE = """
    import threading

    class RadixPrefixCache:
        # the ISSUE 12 trie discipline: match_and_pin/insert/evict run on
        # the batcher's offload threads while match_len (the ReplicaSet
        # routing probe) and stats run on transport threads — every
        # structure walk and counter bump belongs under the trie lock
        def __init__(self):
            self._lock = threading.Lock()
            self._blocks = 0
            self.hit_blocks_total = 0

        def insert(self, n):
            with self._lock:
                self._blocks += n

        def evict(self, n):
            with self._lock:
                self._blocks -= n

        def match_and_pin(self, n):
            self.hit_blocks_total += n     # pre-fix: unlocked counter RMW
            return self._blocks            # pre-fix: unlocked read

        def stats(self):
            with self._lock:
                return (self._blocks, self.hit_blocks_total)
"""


def test_radix_trie_unlocked_match_fires(tmp_path):
    """The trie/refcount discipline (ISSUE 12 satellite): insert/evict/
    stats establish the guarded pattern on the block count and hit
    counter; an unlocked match path is the lost-hit/torn-read race the
    schedules suite explores dynamically."""
    root = write_tree(tmp_path / "pkg", {"runtime/radix.py": RADIX_TRIE})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked match_and_pin accesses must fire"
    assert any("hit_blocks_total" in f.message or "_blocks" in f.message
               for f in us)


def test_radix_trie_locked_match_is_clean(tmp_path):
    fixed = RADIX_TRIE.replace(
        "        def match_and_pin(self, n):\n"
        "            self.hit_blocks_total += n     # pre-fix: unlocked counter RMW\n"
        "            return self._blocks            # pre-fix: unlocked read",
        "        def match_and_pin(self, n):\n"
        "            with self._lock:\n"
        "                self.hit_blocks_total += n\n"
        "                return self._blocks")
    assert fixed != RADIX_TRIE
    root = write_tree(tmp_path / "pkg", {"runtime/radix.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


SPEC_CONTROLLER = """
    import threading

    class SpecController:
        # the PR 8 draft-length controller shape: observe() runs on the
        # batcher drain thread, cap() on its dispatch thread, rates() on
        # transport threads at /metrics scrape time
        def __init__(self, slots, k):
            self._lock = threading.Lock()
            self._rate = [1.0] * slots
            self._accepted_total = 0

        def observe(self, slot, accepted, offered):
            self._accepted_total += accepted     # pre-fix: unlocked RMW
            self._rate[slot] += 0.3 * (accepted / offered - self._rate[slot])

        def cap(self, slot):
            with self._lock:
                return 4 if self._rate[slot] >= 0.5 else 1

        def rates(self):
            with self._lock:
                return list(self._rate)
"""


def test_spec_controller_unlocked_observe_fires(tmp_path):
    """The PR 8 acceptance-rate controller discipline: cap/rates establish
    the guarded pattern on the EMA list; an unlocked observe() is the
    lost-observation race tests/test_schedules.py explores dynamically."""
    root = write_tree(tmp_path / "pkg", {"runtime/spec.py": SPEC_CONTROLLER})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked EMA read-modify-write must fire"
    assert any("_rate" in f.message or "_accepted_total" in f.message
               for f in us)


def test_spec_controller_locked_observe_is_clean(tmp_path):
    fixed = SPEC_CONTROLLER.replace(
        "        def observe(self, slot, accepted, offered):\n"
        "            self._accepted_total += accepted     # pre-fix: unlocked RMW\n"
        "            self._rate[slot] += 0.3 * (accepted / offered - self._rate[slot])",
        "        def observe(self, slot, accepted, offered):\n"
        "            with self._lock:\n"
        "                self._accepted_total += accepted\n"
        "                self._rate[slot] += 0.3 * (accepted / offered - self._rate[slot])")
    assert fixed != SPEC_CONTROLLER
    root = write_tree(tmp_path / "pkg", {"runtime/spec.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


AUTOSCALER = """
    import threading

    class Autoscaler:
        # the PR 14 elastic-control-loop shape: tick() runs on the
        # controller thread (run_forever) AND from admin triggers, while
        # autoscaler_stats() serves /metrics scrape threads — the
        # tally/history block is the only shared state (decisions are
        # pure functions)
        def __init__(self):
            self._lock = threading.Lock()
            self._ticks_total = 0
            self._scale_ups_total = 0

        def tick(self, over):
            self._ticks_total += 1           # pre-fix: unlocked RMW
            if over:
                self._scale_ups_total += 1   # pre-fix: unlocked RMW

        def autoscaler_stats(self):
            with self._lock:
                return {"ticks": self._ticks_total,
                        "ups": self._scale_ups_total}
"""


def test_autoscaler_unlocked_tick_fires(tmp_path):
    """The PR 14 controller discipline: autoscaler_stats establishes the
    guarded pattern on the tallies; an unlocked tick() is the lost-update
    race tests/test_schedules.py finds and replays dynamically."""
    root = write_tree(tmp_path / "pkg",
                      {"controlplane/autoscaler.py": AUTOSCALER})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked tick tallies must fire"
    assert any("_ticks_total" in f.message or "_scale_ups_total" in f.message
               for f in us)


def test_autoscaler_locked_tick_is_clean(tmp_path):
    fixed = AUTOSCALER.replace(
        "        def tick(self, over):\n"
        "            self._ticks_total += 1           # pre-fix: unlocked RMW\n"
        "            if over:\n"
        "                self._scale_ups_total += 1   # pre-fix: unlocked RMW",
        "        def tick(self, over):\n"
        "            with self._lock:\n"
        "                self._ticks_total += 1\n"
        "                if over:\n"
        "                    self._scale_ups_total += 1")
    assert fixed != AUTOSCALER
    root = write_tree(tmp_path / "pkg",
                      {"controlplane/autoscaler.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


ADAPTER_REGISTRY = """
    import threading

    class AdapterRegistry:
        # the ISSUE 15 LoRA-pool discipline: load/evict run on management
        # (transport) threads, pin/unpin on the batcher loop's offload
        # context, stats on /metrics scrape threads — the row map and
        # refcounts are the shared truth evict reads before freeing
        def __init__(self, n):
            self._lock = threading.Lock()
            self._pins = {}
            self._free_rows = list(range(n, 0, -1))
            self.evictions_total = 0

        def load(self, name):
            with self._lock:
                row = self._free_rows.pop()
                self._pins[row] = 0
                return row

        def pin(self, row):
            self._pins[row] += 1             # pre-fix: unlocked RMW

        def unpin(self, row):
            self._pins[row] -= 1             # pre-fix: unlocked RMW

        def evict(self, row):
            with self._lock:
                if self._pins.get(row, 0) > 0:
                    return False
                del self._pins[row]
                self._free_rows.append(row)
                self.evictions_total += 1
                return True
"""


def test_adapter_registry_unlocked_pin_fires(tmp_path):
    """The adapter-refcount discipline (ISSUE 15 satellite): load/evict
    establish the guarded pattern on the pin map; an unlocked pin/unpin
    RMW is exactly the lost-reference race that lets evict free an
    adapter a live slot is about to gather —
    tests/test_schedules.py proves it dynamically."""
    root = write_tree(tmp_path / "pkg",
                      {"runtime/adapters.py": ADAPTER_REGISTRY})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked pin refcount RMW must fire"
    assert any("_pins" in f.message for f in us)


def test_adapter_registry_locked_pin_is_clean(tmp_path):
    fixed = ADAPTER_REGISTRY.replace(
        "        def pin(self, row):\n"
        "            self._pins[row] += 1             # pre-fix: unlocked RMW\n"
        "\n"
        "        def unpin(self, row):\n"
        "            self._pins[row] -= 1             # pre-fix: unlocked RMW",
        "        def pin(self, row):\n"
        "            with self._lock:\n"
        "                self._pins[row] += 1\n"
        "\n"
        "        def unpin(self, row):\n"
        "            with self._lock:\n"
        "                self._pins[row] -= 1")
    assert fixed != ADAPTER_REGISTRY
    root = write_tree(tmp_path / "pkg",
                      {"runtime/adapters.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


WFQ_SCHEDULER = """
    import threading

    class WeightedFairScheduler:
        # the ISSUE 15 admission-queue discipline: push() runs from
        # submit coroutines, next_request/commit from the batcher's
        # admission turns, counters/depths from /metrics scrape threads
        # — the size, virtual clocks and tenant tallies all share state
        def __init__(self):
            self._lock = threading.Lock()
            self._size = 0
            self._class_vt = {"interactive": 0.0, "batch": 0.0}
            self._shed_total = 0

        def push(self, cls):
            self._size += 1                  # pre-fix: unlocked RMW
            return True

        def commit(self, cls):
            with self._lock:
                self._size -= 1
                self._class_vt[cls] += 1.0

        def count_shed(self):
            with self._lock:
                self._shed_total += 1

        def __len__(self):
            with self._lock:
                return self._size
"""


def test_wfq_scheduler_unlocked_push_fires(tmp_path):
    """The scheduler discipline (ISSUE 15 satellite): commit/count_shed/
    __len__ establish the guarded pattern on the queue size; an unlocked
    push() loses admissions under the interleaving
    tests/test_schedules.py finds."""
    root = write_tree(tmp_path / "pkg",
                      {"runtime/scheduler.py": WFQ_SCHEDULER})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked push size RMW must fire"
    assert any("_size" in f.message for f in us)


def test_wfq_scheduler_locked_push_is_clean(tmp_path):
    fixed = WFQ_SCHEDULER.replace(
        "        def push(self, cls):\n"
        "            self._size += 1                  # pre-fix: unlocked RMW\n"
        "            return True",
        "        def push(self, cls):\n"
        "            with self._lock:\n"
        "                self._size += 1\n"
        "                return True")
    assert fixed != WFQ_SCHEDULER
    root = write_tree(tmp_path / "pkg",
                      {"runtime/scheduler.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


FLEET_HEALTH = """
    import threading

    class ReplicaFleet:
        # the ISSUE 16 health-model shape: ejection happens from dispatch
        # threads (after a failed submit) AND from the autoscaler tick
        # thread (check_health), while ejected_members()/dispatchable()
        # serve transport and /metrics scrape threads — the quarantine
        # list and its tally are the shared membership truth
        def __init__(self, replicas):
            self._lock = threading.Lock()
            self._replicas = list(replicas)
            self._ejected = []
            self.ejections_total = 0

        def eject(self, r):
            if r not in self._ejected:       # pre-fix: unlocked check...
                self._ejected.append(r)      # ...then unlocked act
                self.ejections_total += 1    # pre-fix: unlocked RMW

        def reinstate(self, r):
            with self._lock:
                if r in self._ejected:
                    self._ejected.remove(r)

        def ejected_members(self):
            with self._lock:
                return list(self._ejected)

        def dispatchable(self):
            with self._lock:
                return [r for r in self._replicas
                        if r not in self._ejected]
"""


def test_fleet_health_unlocked_eject_fires(tmp_path):
    """The fleet-health discipline (ISSUE 16 tentpole): reinstate/
    ejected_members/dispatchable establish the guarded pattern on the
    quarantine list; an unlocked eject() is the check-then-act race that
    double-ejects a replica (and double-counts the ejection) when a
    dispatch failure and the health sweep observe the same death —
    tests/test_schedules.py explores the membership interleavings on the
    REAL ReplicaSet."""
    root = write_tree(tmp_path / "pkg", {"runtime/fleet.py": FLEET_HEALTH})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked eject membership mutation must fire"
    assert any("_ejected" in f.message or "ejections_total" in f.message
               for f in us)


def test_fleet_health_locked_eject_is_clean(tmp_path):
    fixed = FLEET_HEALTH.replace(
        "        def eject(self, r):\n"
        "            if r not in self._ejected:       # pre-fix: unlocked check...\n"
        "                self._ejected.append(r)      # ...then unlocked act\n"
        "                self.ejections_total += 1    # pre-fix: unlocked RMW",
        "        def eject(self, r):\n"
        "            with self._lock:\n"
        "                if r not in self._ejected:\n"
        "                    self._ejected.append(r)\n"
        "                    self.ejections_total += 1")
    assert fixed != FLEET_HEALTH
    root = write_tree(tmp_path / "pkg", {"runtime/fleet.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


RESUME_JOURNAL = """
    import threading

    class ResumeJournal:
        # the ISSUE 16 recovery-journal shape: batcher worker threads
        # append each delivered token while the fleet's retry loop
        # snapshots the prefix it must re-admit after an ejection and the
        # /metrics scrape reads the depth — the token lists ARE the
        # at-most-once contract, so a lost append double-delivers
        def __init__(self):
            self._lock = threading.Lock()
            self._entries = {}
            self._seq = 0
            self.appended_total = 0

        def open(self, prompt):
            with self._lock:
                self._seq += 1
                self._entries[self._seq] = []
                return self._seq

        def append(self, jid, tok):
            self._entries[jid].append(tok)   # pre-fix: unlocked mutate
            self.appended_total += 1         # pre-fix: unlocked RMW

        def snapshot(self, jid):
            with self._lock:
                return list(self._entries[jid])

        def close(self, jid):
            with self._lock:
                self._entries.pop(jid, None)
"""


def test_resume_journal_unlocked_append_fires(tmp_path):
    """The resume-journal discipline (ISSUE 16 tentpole): open/snapshot/
    close establish the guarded pattern on the entry map; an unlocked
    append() races the retry loop's snapshot — the resumed replica then
    replays a token the client already has, breaking at-most-once
    delivery (the dynamic find-and-replay proof lives in
    tests/test_schedules.py)."""
    root = write_tree(tmp_path / "pkg",
                      {"runtime/journal.py": RESUME_JOURNAL})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us, "the unlocked journal append must fire"
    assert any("_entries" in f.message or "appended_total" in f.message
               for f in us)


def test_resume_journal_locked_append_is_clean(tmp_path):
    fixed = RESUME_JOURNAL.replace(
        "        def append(self, jid, tok):\n"
        "            self._entries[jid].append(tok)   # pre-fix: unlocked mutate\n"
        "            self.appended_total += 1         # pre-fix: unlocked RMW",
        "        def append(self, jid, tok):\n"
        "            with self._lock:\n"
        "                self._entries[jid].append(tok)\n"
        "                self.appended_total += 1")
    assert fixed != RESUME_JOURNAL
    root = write_tree(tmp_path / "pkg",
                      {"runtime/journal.py": fixed})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_unguarded_read_against_guarded_writes_fires(tmp_path):
    """The CircuitBreaker.state_code class: guarded writes establish the
    discipline, an unguarded public read violates it."""
    src = """
        import threading

        class Breaker:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = "closed"

            def record(self):
                with self._lock:
                    self.state = "open"

            def state_code(self):
                return self.state
    """
    root = write_tree(tmp_path / "pkg", {"runtime/b.py": src})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us and any("state_code" in f.function for f in us)


def test_multi_context_rmw_without_any_lock_fires(tmp_path):
    """The BatcherService.submitted class: no lock anywhere, but the
    counter is bumped from an async (loop) and a sync (caller) surface of
    a thread-spawning class."""
    src = """
        import asyncio
        import threading

        class Service:
            def __init__(self):
                self._loop = asyncio.new_event_loop()
                threading.Thread(target=self._loop.run_forever).start()
                self.submitted = 0

            def submit_sync(self):
                self.submitted += 1

            async def submit(self):
                self.submitted += 1
    """
    root = write_tree(tmp_path / "pkg", {"runtime/svc.py": src})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert len(us) == 2  # both increments
    assert all("read-modify-write" in f.message for f in us)


def test_single_context_rmw_is_clean(tmp_path):
    """An rmw only ever touched by the one spawned worker (the batcher's
    _admit updating the rng chain from the loop's awaited to_thread) is
    sequential — no finding."""
    src = """
        import asyncio

        class Batcher:
            def __init__(self):
                self.rng = 0

            async def _run(self):
                await asyncio.to_thread(self._admit)

            def _admit(self):
                self.rng = self.rng + 1
    """
    root = write_tree(tmp_path / "pkg", {"runtime/b.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_inactive_class_is_ignored(tmp_path):
    """No locks, no threads, no async: plain single-threaded classes are
    out of scope no matter how they mutate themselves."""
    src = """
        class Plain:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
    """
    root = write_tree(tmp_path / "pkg", {"runtime/p.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_module_global_discipline_checked(tmp_path):
    """Module-level shared state with a module-level lock (the gRPC
    channel cache): unguarded mutation against the practiced discipline
    fires."""
    src = """
        import threading

        _cache = {}
        _lock = threading.Lock()

        def get(key):
            with _lock:
                if key not in _cache:
                    _cache[key] = object()
                return _cache[key]

        def evict(key):
            _cache.pop(key, None)
    """
    root = write_tree(tmp_path / "pkg", {"transport/chan.py": src})
    reported, _, _ = lint(root)
    us = [f for f in reported if f.rule == "unguarded-shared-state"]
    assert us and any("evict" in f.function for f in us)


def test_scoped_to_concurrent_dirs(tmp_path):
    """Packages outside runtime/transport/servers/controlplane/metrics are
    not scanned (same scoping idea as graftlint's hot dirs)."""
    root = write_tree(tmp_path / "pkg", {"analytics/x.py": PR6_SHED})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


# ---------------------------------------------------------------------------
# lock-order-inversion
# ---------------------------------------------------------------------------

INVERSION = """
    import threading

    class Engine:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def route(self):
            with self._a:
                with self._b:
                    pass

        def scrape(self):
            with self._b:
                self._peek()

        def _peek(self):
            with self._a:
                pass
"""


def test_lock_order_inversion_fires_including_via_call(tmp_path):
    root = write_tree(tmp_path / "pkg", {"runtime/e.py": INVERSION})
    reported, _, _ = lint(root)
    lo = [f for f in reported if f.rule == "lock-order-inversion"]
    assert len(lo) >= 2  # both directions of the cycle are witnessed
    assert any("via call to _peek" in f.message for f in lo)


def test_consistent_lock_order_is_clean(tmp_path):
    src = INVERSION.replace(
        "            with self._b:\n                self._peek()",
        "            with self._a:\n                self._take_b()",
    ).replace(
        "        def _peek(self):\n            with self._a:\n                pass",
        "        def _take_b(self):\n            with self._b:\n                pass",
    )
    root = write_tree(tmp_path / "pkg", {"runtime/e.py": src})
    reported, _, _ = lint(root)
    assert rules_of(reported) == []


def test_nonreentrant_self_acquire_fires(tmp_path):
    """Calling a lock-taking helper while already holding the same
    threading.Lock deadlocks immediately — the exact trap the _shed fix
    had to avoid (release() takes the lock itself)."""
    src = """
        import threading

        class Adm:
            def __init__(self):
                self._lock = threading.Lock()

            def acquire_sync(self):
                with self._lock:
                    self.release()

            def release(self):
                with self._lock:
                    pass
    """
    root = write_tree(tmp_path / "pkg", {"runtime/a.py": src})
    reported, _, _ = lint(root)
    lo = [f for f in reported if f.rule == "lock-order-inversion"]
    assert lo and any("not reentrant" in f.message for f in lo)


def test_rlock_self_acquire_is_clean(tmp_path):
    src = """
        import threading

        class Adm:
            def __init__(self):
                self._lock = threading.RLock()

            def acquire_sync(self):
                with self._lock:
                    self.release()

            def release(self):
                with self._lock:
                    pass
    """
    root = write_tree(tmp_path / "pkg", {"runtime/a.py": src})
    reported, _, _ = lint(root)
    assert [f for f in reported if f.rule == "lock-order-inversion"] == []


# ---------------------------------------------------------------------------
# await-with-lock-held
# ---------------------------------------------------------------------------


def test_await_with_threading_lock_fires(tmp_path):
    src = """
        import asyncio
        import threading

        class Gate:
            def __init__(self):
                self._lock = threading.Lock()

            async def acquire(self):
                with self._lock:
                    await asyncio.sleep(0.1)
    """
    root = write_tree(tmp_path / "pkg", {"runtime/g.py": src})
    reported, _, _ = lint(root)
    aw = [f for f in reported if f.rule == "await-with-lock-held"]
    assert aw and "THREADING lock" in aw[0].message


def test_await_inside_test_expression_fires(tmp_path):
    """An await buried in an if/while condition is the same hazard as a
    bare one (found in review: _stmt scanned the test expression but
    never noted its awaits)."""
    src = """
        import threading

        class Gate:
            def __init__(self):
                self._lock = threading.Lock()

            async def check(self):
                return True

            async def acquire(self):
                with self._lock:
                    if await self.check():
                        pass
    """
    root = write_tree(tmp_path / "pkg", {"runtime/g.py": src})
    reported, _, _ = lint(root)
    assert [f for f in reported if f.rule == "await-with-lock-held"]


def test_condition_self_reacquire_is_clean(tmp_path):
    """threading.Condition's default internal lock is an RLock — re-entry
    through a helper is legal, not a self-deadlock."""
    src = """
        import threading

        class Q:
            def __init__(self):
                self._cond = threading.Condition()

            def put(self):
                with self._cond:
                    self._notify()

            def _notify(self):
                with self._cond:
                    pass
    """
    root = write_tree(tmp_path / "pkg", {"runtime/q.py": src})
    reported, _, _ = lint(root)
    assert [f for f in reported if f.rule == "lock-order-inversion"] == []


def test_await_after_lock_released_is_clean(tmp_path):
    """The real AdmissionController.acquire shape: enqueue under the lock,
    await the future OUTSIDE it."""
    src = """
        import asyncio
        import threading

        class Gate:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []

            async def acquire(self):
                loop = asyncio.get_running_loop()
                with self._lock:
                    fut = loop.create_future()
                    self._q.append(fut)
                await fut
    """
    root = write_tree(tmp_path / "pkg", {"runtime/g.py": src})
    reported, _, _ = lint(root)
    assert [f for f in reported if f.rule == "await-with-lock-held"] == []


# ---------------------------------------------------------------------------
# unbounded-shutdown-wait
# ---------------------------------------------------------------------------


def test_timeoutless_wait_on_shutdown_path_fires(tmp_path):
    src = """
        import threading

        class Saver:
            def __init__(self):
                self._halt = threading.Event()
                self._t = threading.Thread(target=self._run)

            def _run(self):
                pass

            def stop(self):
                self._halt.wait()
                self._t.join()
    """
    root = write_tree(tmp_path / "pkg", {"runtime/s.py": src})
    reported, _, _ = lint(root)
    sw = [f for f in reported if f.rule == "unbounded-shutdown-wait"]
    assert len(sw) == 2  # the wait() and the join()


def test_bounded_waits_and_hot_path_waits_are_clean(tmp_path):
    """Timeouts make shutdown waits fine; waits outside shutdown-named
    functions (the drain loop) are a different rule's business."""
    src = """
        import threading

        class Saver:
            def __init__(self):
                self._halt = threading.Event()
                self._t = threading.Thread(target=self._run)

            def _run(self):
                while not self._halt.wait(0.5):
                    pass

            def stop(self):
                self._halt.set()
                self._t.join(timeout=5.0)
    """
    root = write_tree(tmp_path / "pkg", {"runtime/s.py": src})
    reported, _, _ = lint(root)
    assert [f for f in reported if f.rule == "unbounded-shutdown-wait"] == []


def test_awaited_wait_is_not_a_sync_wait(tmp_path):
    """``await done.wait()`` on an asyncio.Event (the ipc drain shutdown)
    is the async world — deadline-governed, not this rule."""
    src = """
        import asyncio

        class Drain:
            async def close(self):
                done = asyncio.Event()
                await done.wait()
    """
    root = write_tree(tmp_path / "pkg", {"transport/d.py": src})
    reported, _, _ = lint(root)
    assert [f for f in reported if f.rule == "unbounded-shutdown-wait"] == []


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------


def test_suppression_with_reason_silences(tmp_path):
    src = PR6_SHED.replace(
        "            self.shed_total += 1",
        "            self.shed_total += 1  # racelint: allow-unguarded-shared-state(reconstruction fixture: counted once by the caller)")
    # the other two accesses in _shed also fire; suppress the whole set
    src = src.replace(
        "            return RuntimeError(self.inflight)",
        "            # racelint: allow-unguarded-shared-state(fixture)\n"
        "            return RuntimeError(self.inflight)")
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": src})
    reported, _, suppressed = lint(root)
    assert rules_of(reported) == []
    assert len(suppressed) >= 2


def test_suppression_with_empty_reason_is_a_finding(tmp_path):
    src = PR6_SHED.replace(
        "            self.shed_total += 1",
        "            self.shed_total += 1  # racelint: allow-unguarded-shared-state()")
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": src})
    reported, _, _ = lint(root)
    assert "bad-suppression" in rules_of(reported)
    # and the underlying finding is NOT silenced
    assert "unguarded-shared-state" in rules_of(reported)


def test_unknown_rule_suppression_is_flagged(tmp_path):
    src = PR6_SHED.replace(
        "            self.shed_total += 1",
        "            self.shed_total += 1  # racelint: allow-made-up-rule(nope)")
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": src})
    reported, _, _ = lint(root)
    assert "bad-suppression" in rules_of(reported)


def test_graftlint_tag_does_not_silence_racelint(tmp_path):
    """The layers answer to different comment tags by construction."""
    src = PR6_SHED.replace(
        "            self.shed_total += 1",
        "            self.shed_total += 1  # graftlint: allow-unguarded-shared-state(wrong tool)")
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": src})
    reported, _, _ = lint(root)
    assert "unguarded-shared-state" in rules_of(reported)


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------


def test_baseline_absorbs_then_dies_with_the_code(tmp_path):
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": PR6_SHED})
    reported, _, _ = lint(root)
    findings = [f for f in reported if f.rule in RULES]
    assert findings
    bpath = str(tmp_path / "baseline.json")
    save_baseline(bpath, findings)
    data = json.loads(open(bpath).read())
    for e in data["entries"]:
        e["reason"] = "grandfathered for the mechanics test"
    with open(bpath, "w") as f:
        json.dump(data, f)

    reported2, absorbed, _ = lint(root, baseline=bpath)
    assert rules_of(reported2) == []
    assert len(absorbed) == len(findings)

    # touch the fingerprinted line: the entry dies, the finding resurfaces
    mutated = PR6_SHED.replace("self.shed_total += 1",
                               "self.shed_total += 2")
    write_tree(tmp_path / "pkg", {"runtime/adm.py": mutated})
    reported3, _, _ = lint(root, baseline=bpath)
    assert any("shed_total" in f.message for f in reported3
               if f.rule == "unguarded-shared-state")


def test_baseline_without_reason_is_rejected(tmp_path):
    root = write_tree(tmp_path / "pkg", {"runtime/adm.py": PR6_SHED})
    reported, _, _ = lint(root)
    bpath = str(tmp_path / "baseline.json")
    save_baseline(bpath, [f for f in reported if f.rule in RULES])
    # save_baseline leaves TODO reasons; load must refuse them? No — the
    # TODO text is non-empty by design. Blank one out to prove the guard.
    data = json.loads(open(bpath).read())
    data["entries"][0]["reason"] = "  "
    with open(bpath, "w") as f:
        json.dump(data, f)
    with pytest.raises(ValueError, match="no reason"):
        lint(root, baseline=bpath)


def test_real_tree_has_zero_unsuppressed_findings():
    """The gate itself: the shipped tree + shipped baseline lint clean.
    The PR 6 burn-down fixed every finding instead of baselining it."""
    reported, absorbed, _ = run_lint(
        [os.path.join(REPO, "seldon_core_tpu")],
        baseline_path=BASELINE if os.path.exists(BASELINE) else None)
    assert reported == [], "\n".join(f.render() for f in reported)
    assert absorbed == []  # nothing grandfathered — keep it that way


def test_real_baseline_reasons_are_filled_in():
    with open(BASELINE) as f:
        data = json.load(f)
    for e in data.get("entries", []):
        assert str(e.get("reason", "")).strip(), f"reason missing: {e}"
        assert "TODO" not in str(e.get("reason", "")), f"unfilled: {e}"


def test_real_baseline_count_only_decreases():
    """The ratchet: the racelint baseline shipped EMPTY (every burn-down
    finding was fixed, not grandfathered). It must stay empty — growing
    it means shipping a known race; fix it or suppress it inline with a
    reason a reviewer can judge."""
    with open(BASELINE) as f:
        data = json.load(f)
    assert len(data.get("entries", [])) <= 0


# ---------------------------------------------------------------------------
# CLI + parallel runner
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_json(tmp_path):
    """The acceptance contract: non-zero on EACH mutated fixture class —
    unguarded shared write, lock-order inversion, await-with-lock-held,
    empty-reason suppression — and 0 on a clean tree."""
    bad = write_tree(tmp_path / "bad", {
        "runtime/adm.py": PR6_SHED,
        "runtime/eng.py": INVERSION,
        "runtime/gate.py": """
            import asyncio
            import threading

            class Gate:
                def __init__(self):
                    self._lock = threading.Lock()

                async def acquire(self):
                    with self._lock:
                        await asyncio.sleep(0.1)
        """,
        "runtime/supp.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    self.n += 1  # racelint: allow-unguarded-shared-state()
        """,
    })
    ok = write_tree(tmp_path / "ok", {"runtime/c.py": "X = 1\n"})

    r = cli(bad, "--no-baseline", "--format", "json")
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    seen = {f["rule"] for f in payload["findings"]}
    assert {"unguarded-shared-state", "lock-order-inversion",
            "await-with-lock-held", "bad-suppression"} <= seen

    # each rule's gate bites solo too
    for rule in ("unguarded-shared-state", "lock-order-inversion",
                 "await-with-lock-held"):
        assert cli(bad, "--no-baseline", "--rules", rule).returncode == 1, rule

    assert cli(ok, "--no-baseline").returncode == 0
    assert cli(str(tmp_path / "missing")).returncode == 2
    assert cli(bad, "--rules", "not-a-rule").returncode == 2


def test_cli_real_tree_is_the_gate():
    r = cli("seldon_core_tpu/")
    assert r.returncode == 0, r.stdout + r.stderr


@pytest.mark.slow  # tier-1 870s budget: runs in CI's unfiltered racelint proofs step
def test_parallel_matches_serial(tmp_path):
    root = write_tree(tmp_path / "pkg", {
        "runtime/adm.py": PR6_SHED,
        "runtime/e.py": INVERSION,
        "runtime/bad_supp.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def a(self):
                    with self._lock:
                        self.n += 1

                def b(self):
                    self.n += 1  # racelint: allow-unguarded-shared-state()
        """,
    })
    serial = run_lint([root])
    parallel = run_lint_parallel([root], None, None, jobs=4)
    for s, p in zip(serial, parallel):
        assert [(f.rule, f.path, f.line) for f in s] == \
            [(f.rule, f.path, f.line) for f in p]
    # meta findings (the empty-reason suppression) appear exactly once
    assert sum(1 for f in parallel[0] if f.rule == "bad-suppression") == 1


def test_rules_filter(tmp_path):
    root = write_tree(tmp_path / "pkg", {"runtime/e.py": INVERSION})
    reported, _, _ = lint(root, rules=["unguarded-shared-state"])
    assert [f for f in reported if f.rule == "lock-order-inversion"] == []
    reported, _, _ = lint(root, rules=["lock-order-inversion"])
    assert [f for f in reported if f.rule == "lock-order-inversion"]
