"""Continuous batcher correctness: mixed-occupancy decode must reproduce solo
greedy generation exactly, with admissions mid-flight."""

import asyncio

import numpy as np
import pytest

from seldon_core_tpu.runtime.batcher import ContinuousBatcher
from seldon_core_tpu.servers.llmserver import LLMServer


@pytest.fixture(scope="module")
def server():
    s = LLMServer(
        model="llama-tiny",
        init_random=True,
        max_new_tokens=6,
        len_buckets=(8, 16),
        batch_buckets=(1, 4),
        seed=11,
    )
    s.load()
    return s


def solo(server, prompt, n):
    return server.generate([prompt], max_new_tokens=n)["tokens"][0]


def test_batcher_matches_solo_generation(server):
    prompts = [[5, 9, 17], [40, 3, 22, 8, 11], [7], [60, 61, 62, 63]]
    expected = [solo(server, p, 6) for p in prompts]

    async def go():
        batcher = ContinuousBatcher(server, max_slots=2, max_len=32, len_buckets=(8,))
        outs = await asyncio.gather(*[batcher.submit(p, max_new_tokens=6) for p in prompts])
        await batcher.close()
        return outs

    outs = asyncio.run(go())
    assert outs == expected


def test_batcher_staggered_admission(server):
    """Submit a second request while the first is mid-decode: both must still
    match their solo outputs (slot isolation under PAD_POS masking)."""
    p1, p2 = [5, 9, 17, 33], [2, 4]
    e1, e2 = solo(server, p1, 6), solo(server, p2, 6)

    async def go():
        batcher = ContinuousBatcher(server, max_slots=2, max_len=32, len_buckets=(8,))
        t1 = asyncio.ensure_future(batcher.submit(p1, max_new_tokens=6))
        await asyncio.sleep(0.05)  # let a few decode steps run
        t2 = asyncio.ensure_future(batcher.submit(p2, max_new_tokens=6))
        outs = await asyncio.gather(t1, t2)
        await batcher.close()
        return outs

    o1, o2 = asyncio.run(go())
    assert o1 == e1
    assert o2 == e2


def test_batcher_more_requests_than_slots(server):
    prompts = [[i + 1, i + 2] for i in range(5)]
    expected = [solo(server, p, 4) for p in prompts]

    async def go():
        batcher = ContinuousBatcher(server, max_slots=2, max_len=32, len_buckets=(8,))
        outs = await asyncio.gather(*[batcher.submit(p, max_new_tokens=4) for p in prompts])
        await batcher.close()
        return outs

    assert asyncio.run(go()) == expected


def test_batcher_string_prompt(server):
    async def go():
        batcher = ContinuousBatcher(server, max_slots=2, max_len=32, len_buckets=(8,))
        out = await batcher.submit("hey", max_new_tokens=3)
        await batcher.close()
        return out

    out = asyncio.run(go())
    assert isinstance(out, list) and len(out) <= 3


def test_batcher_honors_sampling_config():
    """A temperature-configured server must sample through the batcher too
    (regression: batcher was silently greedy-only)."""
    import jax

    s1 = LLMServer(model="llama-tiny", init_random=True, temperature=0.9,
                   len_buckets=(8,), seed=21)
    s1.load()

    async def run_batch(seed):
        b = ContinuousBatcher(s1, max_slots=1, max_len=32, len_buckets=(8,))
        b._rng = jax.random.PRNGKey(seed)
        out = await b.submit([3, 5], max_new_tokens=8)
        await b.close()
        return out

    a = asyncio.run(run_batch(0))
    outs = {tuple(asyncio.run(run_batch(s))) for s in range(1, 5)}
    assert len(outs | {tuple(a)}) > 1  # different rng seeds -> different samples


def test_batcher_max_len_zero_means_default(server):
    """max_len<=0 from a direct constructor caller means 'unset' — taking it
    literally produced plen=min(...,-1) nonsense slicing (ADVICE.md r5)."""
    b_default = ContinuousBatcher(server, max_slots=1, len_buckets=(8,))
    for bad in (0, -4):
        b = ContinuousBatcher(server, max_slots=1, max_len=bad, len_buckets=(8,))
        assert b.max_len == b_default.max_len > 0


def test_batcher_truncation_reported_via_info(server):
    """Truncation changes outputs, so it must reach the client (response meta
    via the transports), not only the server log."""

    async def go():
        batcher = ContinuousBatcher(server, max_slots=1, max_len=10, len_buckets=(8,))
        info: dict = {}
        long_prompt = list(range(1, 25))  # 24 tokens >> 9-token cap
        await batcher.submit(long_prompt, max_new_tokens=2, info=info)
        short_info: dict = {}
        await batcher.submit([1, 2], max_new_tokens=2, info=short_info)
        await batcher.close()
        return info, short_info

    info, short_info = asyncio.run(go())
    rec = info["truncated_prompt"]
    assert rec["prompt_tokens"] == 24
    assert rec["kept_tokens"] < 24
    assert rec["max_len"] == 10
    assert "truncated_prompt" not in short_info  # untouched when it fits


def test_batcher_rejects_after_close(server):
    """A closed batcher rejects with a RETRYABLE shed (503 + Retry-After),
    not a hard RuntimeError: since the elastic control plane (ISSUE 14)
    a batcher is closed by scale-down detach, and a stale dispatch that
    reaches it must bounce back through routing onto a live replica
    instead of failing the client (docs/control-plane.md)."""
    from seldon_core_tpu.runtime.resilience import ShedError

    async def go():
        batcher = ContinuousBatcher(server, max_slots=1, max_len=32, len_buckets=(8,))
        await batcher.submit([1, 2], max_new_tokens=2)
        await batcher.close()
        with pytest.raises(ShedError) as e:
            await batcher.submit([3], max_new_tokens=2)
        assert e.value.status_code == 503

    asyncio.run(go())


@pytest.mark.slow  # tier-1 870s budget: redundant coverage — runs in CI's unfiltered unit step
def test_continuous_batcher_int8_matches_generate():
    """int8 serving: the batcher's decode_step must dequant inside the jit
    like the server's own prefill/decode paths (round-5 fix: it applied
    raw QuantizedTensor leaves and crashed at 7B)."""
    import asyncio

    from seldon_core_tpu.runtime.batcher import ContinuousBatcher
    from seldon_core_tpu.servers.llmserver import LLMServer

    kw = dict(vocab_size=96, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
              ffn_dim=64, max_seq_len=96)
    s = LLMServer(model="transformer", model_kwargs=kw, init_random=True,
                  max_new_tokens=6, len_buckets=(16,), batch_buckets=(1, 4),
                  temperature=0.0, eos_id=-1, seed=3, quantize="int8")
    s.load()
    solo = s.generate([[5, 9, 11, 2]])["tokens"][0]

    async def run():
        b = ContinuousBatcher(s, max_slots=2)
        got = await b.submit([5, 9, 11, 2])
        await b.close()
        return got

    assert asyncio.run(run()) == solo
