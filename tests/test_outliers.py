"""Seq2Seq stacking protocol (stack_segments, round 5): window-granularity
batching with solo-identical scores."""

import numpy as np

from seldon_core_tpu.analytics import Seq2SeqOutlierDetector


def _ulp_distance(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Element-wise distance in float32 ULPs (units in the last place)."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    spacing = np.spacing(np.maximum(np.abs(a), np.abs(b)))
    return np.abs(a - b) / spacing


def test_seq2seq_stacked_matches_solo():
    """stack_segments parity: framing windows per segment makes stacked
    scoring SEMANTICALLY identical to solo scoring for every segment,
    including tail-padded ones (rows not a multiple of timesteps), and
    padding the window batch to a compile bucket must not change which
    rows land in which window.

    Root cause of the tolerance (this test originally asserted bit
    equality): solo calls score their windows in small batches (the 6-row
    segment frames to 2 windows -> the W=2 compile bucket) while the
    stacked call scores ALL segments' windows in one batch (7 windows ->
    the W=8 bucket). XLA compiles one program per window-batch bucket, and
    the GRU matmuls pick batch-shape-dependent tilings/FMA contractions,
    so the f32 accumulations of IDENTICAL window rows can round
    differently in the last bit — across jax/XLA upgrades this drifted
    between exactly-equal and one-ULP-off. The stacking protocol
    guarantees window CONTENT identity (no window straddles a request
    boundary); it never promised bit-identical floats across two different
    compiled programs. Principled bound: the per-window reduction touches
    timesteps*features*hidden terms, each reassociation step costs at most
    1 ULP, and observed drift is ~1-2 ULP — 4 ULPs separates codegen
    noise (<=4) from mis-framing (a straddled window moves scores by many
    orders of magnitude more, asserted below)."""
    rng = np.random.default_rng(11)
    det = Seq2SeqOutlierDetector(timesteps=4, hidden_dim=8, seed=1)
    det.fit(rng.normal(size=(40, 3)), epochs=10)

    batches = [rng.normal(size=(r, 3)) for r in (6, 4, 9, 1)]
    solo = [np.asarray(det.score(b)) for b in batches]

    det.stack_segments([b.shape[0] for b in batches])
    stacked = np.asarray(det.score(np.concatenate(batches, axis=0)))
    off = 0
    for b, s in zip(batches, solo):
        got = stacked[off:off + b.shape[0]]
        assert got.shape == s.shape
        assert _ulp_distance(got, s).max() <= 4, (
            f"stacked segment at rows [{off}, {off + b.shape[0]}) drifted "
            f"beyond codegen noise: {got} vs solo {s}")
        off += b.shape[0]

    # consume-once: the next plain call is solo semantics again — its
    # windows straddle the old request boundaries, so scores must differ
    # MACROSCOPICALLY (far beyond the ULP band above); anything less means
    # the segment list leaked into the plain call
    plain = np.asarray(det.score(np.concatenate(batches, axis=0)))
    assert plain.shape == stacked.shape
    assert np.max(np.abs(plain - stacked) / np.abs(stacked)) > 1e-4


def test_seq2seq_stale_segment_counts_fall_back_to_solo():
    """A segment list that does not sum to the batch's rows (stale or
    foreign) must be ignored, not crash or mis-frame."""
    rng = np.random.default_rng(3)
    det = Seq2SeqOutlierDetector(timesteps=4, hidden_dim=8, seed=1)
    det.fit(rng.normal(size=(16, 2)), epochs=5)
    X = rng.normal(size=(8, 2))
    want = np.asarray(det.score(X))
    det.stack_segments([3, 3])  # sums to 6 != 8
    got = np.asarray(det.score(X))
    np.testing.assert_array_equal(got, want)


def test_seq2seq_save_load_roundtrip(tmp_path):
    """Offline-fit -> save() -> serve-side load() via model_uri: the
    adopted detector scores identically to the fitted original."""
    rng = np.random.default_rng(7)
    det = Seq2SeqOutlierDetector(timesteps=4, hidden_dim=8, seed=2,
                                 threshold=0.4)
    det.fit(rng.normal(size=(24, 3)), epochs=5)
    det.save(str(tmp_path))

    served = Seq2SeqOutlierDetector(model_uri=str(tmp_path))
    served.load()
    assert served.threshold == det.threshold
    X = rng.normal(size=(9, 3))
    np.testing.assert_array_equal(served.score(X), det.score(X))


def test_seq2seq_load_rejects_unfitted(tmp_path):
    import pickle

    with open(tmp_path / "detector.pkl", "wb") as f:
        pickle.dump(Seq2SeqOutlierDetector(), f)
    det = Seq2SeqOutlierDetector(model_uri=str(tmp_path))
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        det.load()
